"""Storage: the transactional store — percolator KV truth + columnar cache.

Plays the role of the reference's `kv.Storage` + embedded unistore
(reference: kv/kv.go:462, store/mockstore/unistore.go). There is ONE
transaction path: commits run the percolator two-phase protocol through
the region tier (TwoPhaseCommitter over RegionManager over MVCCStore,
mirroring session/session.go:573 -> store/tikv/2pc.go:78), with the C++
ordered-KV engine as the substrate when available. Each table owns its
region (register_table splits at the table prefix, the create-table
split-region analog, ddl/split_region.go), so multi-table transactions
exercise region-grouped batches and RegionError retries for real.

The per-table column epochs (TableStore) are the COPROCESSOR-FACING fold
of the same committed data — applied under the commit lock immediately
after the percolator commit lands, the way TiFlash folds the raft log into
its delta tree. Snapshots read the columnar fold; the KV tier holds the
write-ahead truth (locks, write records, versioned values).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Optional

from ..catalog.schema import Catalog, TableInfo
from ..kv import codec, tablecodec
from ..kv.memdb import MemDB, TOMBSTONE
from ..kv.mvcc import (
    KVError,
    MVCCStore,
    Mutation,
    OP_DEL,
    OP_PUT,
    WriteConflictError as KVWriteConflict,
)
from ..analysis import lockcheck
from ..kv.region import RegionManager
from ..kv.tso import TimestampOracle
from ..kv.twopc import CommitError, TwoPhaseCommitter
from .table_store import TableSnapshot, TableStore


from ..errno import (ER_SCHEMA_CHANGED, ER_TXN_TOO_LARGE,
                     ER_WRITE_CONFLICT, CodedError)


class WriteConflictError(CodedError):
    """Another txn committed to a key after our start_ts (optimistic SI)."""

    errno = ER_WRITE_CONFLICT


class TxnTooLargeError(CodedError):
    """Encoded mutation bytes crossed performance.txn-total-size-limit
    (reference: kv.ErrTxnTooLarge / txn-total-size-limit, config.go) —
    a runaway txn must fail BEFORE prewrite floods the region tier,
    not after it has half-committed a gigabyte."""

    errno = ER_TXN_TOO_LARGE


def _make_engine(path: Optional[str] = None, sync_log: str = "off",
                 sync_interval_ms: int = 100):
    """C++ ordered-KV engine when buildable, pure-python twin otherwise.
    With `path`, either engine opens WAL+snapshot files there (shared
    format, native/kvstore.cpp) and honors the sync-log policy."""
    try:
        from ..kv.native import NativeOrderedKV, native_available
        if native_available():
            return NativeOrderedKV(path, sync_log=sync_log,
                                   sync_interval_ms=sync_interval_ms)
    except Exception:
        pass
    if path is not None:
        from ..kv.mvcc import PyOrderedKV
        return PyOrderedKV(path, sync_log=sync_log,
                           sync_interval_ms=sync_interval_ms)
    return None


# TSO lease horizon persisted ahead of issued timestamps (~2 min of
# physical time); restart floors the oracle at the lease so ts never repeat
_TSO_LEASE_MS = 120_000


class Storage:
    def __init__(self, path: Optional[str] = None,
                 shared: bool = False, remote=None,
                 rpc_listen=None, rpc_options=None,
                 sync_log: str = "off",
                 sync_interval_ms: int = 100) -> None:
        """`path=None`: ephemeral in-memory store (tests, benches).
        `path=dir`: durable — KV WAL+snapshot under dir/kv, columnar epoch
        snapshots under dir/epochs, catalog/stats/DDL state in the meta
        keyspace of the same KV; reopening the directory recovers
        everything committed (reference: unistore's badger persistence,
        go.mod:34 + bootstrap-from-KV, session/session.go:2090,
        meta/meta.go:59).

        `shared=True` (requires path): MULTI-PROCESS mode — several
        server processes over one directory, coordinated by
        store/coordinator.py (shared WAL with flock'd mutation sections,
        cross-process schema reload + fence, shared TSO, kill mailbox).
        The reference's many-tidb-servers-one-cluster shape.

        `rpc_listen='host:port'|'unix:/path'` (leader; implies shared):
        also serve the coordination services over the socket RPC tier
        (rpc/server.py) so followers can join WITHOUT sharing the disk.

        `remote='host:port'` (follower): join a leader's cluster over
        the socket — `path` is this server's PRIVATE working dir (epoch
        cache/scratch), the KV truth mirrors the leader's WAL via RPC.
        A `path` of the form 'rpc://host:port' selects this mode with a
        throwaway working dir (the store-URL shape of the reference's
        tikv:// store paths, store/store.go).

        `sync_log` (storage.sync-log): when the KV WAL reaches disk —
        'commit' fsyncs at every commit boundary (no acked commit can
        die with the machine), 'interval' group-commits at most one
        fsync per `sync_interval_ms`, 'off' leaves flushing to the OS
        (process death loses nothing, power loss may). The EMBEDDED
        default is 'off' (tests/benches construct stores by the
        thousand); the SERVER config default is 'commit'
        (config.py StorageConfig — production pays for durability)."""
        import os

        from ..stats import StatsHandle

        if isinstance(path, str) and path.startswith("rpc://"):
            remote, path = path[len("rpc://"):], None
        self._owns_tmp_dir = remote is not None and path is None
        if self._owns_tmp_dir:
            import tempfile
            path = tempfile.mkdtemp(prefix="titpu-follower-")
        import time as _time

        self.path = path
        self.remote = remote is not None
        self.shared = bool((shared or self.remote) and path is not None)
        if sync_log not in ("off", "commit", "interval"):
            raise ValueError(
                f"sync_log must be off|commit|interval, got {sync_log!r}")
        self.sync_log = sync_log
        self.sync_interval_ms = sync_interval_ms
        self.coord = None
        self.rpc_server = None
        self._rpc_client = None
        self._rpc_options = rpc_options
        self._start_time = _time.time()
        self.diag_listener = None
        self.failover = None
        # range-sharded write leadership (rpc/ranged.py RangePlane);
        # None until [ranges] arms it — the statement path never reads
        # this attribute, so disabled costs exactly nothing
        self.ranges = None
        # True while promote_to_leader is mid-flight: diag_election
        # reports the transitional role so peer voters HOLD their
        # election open instead of dropping us from the electorate
        # (dropping the winner mid-promotion elects a second leader)
        self._promoting = False
        # diag fan-out state, owned here so concurrent first queries
        # never race a lazy init (rpc/diag.py uses these)
        self._diag_clients: dict = {}
        self._diag_clients_lock = threading.Lock()
        self._last_members = None
        self._last_members_ts = -1e9
        # follower read tier (rpc/apply.py + rpc/replica.py): per-
        # storage routing/serving knobs, the follower's continuous
        # apply engine (started at the end of __init__ for socket
        # followers; arm_replica_read re-evaluates after config seeds),
        # and the pooled internal sessions replica reads execute on
        from ..rpc.replica import ReplicaReadState
        self.replica_read = ReplicaReadState()
        self.apply_engine = None
        self._replica_pool: list = []
        self._replica_pool_lock = threading.Lock()
        if self.remote:
            from ..rpc.client import RpcClient, RpcOptions
            from ..rpc.diag import DiagListener
            from ..rpc.remote import RemoteCoordinator
            opts = self._rpc_options = rpc_options or RpcOptions()
            self._rpc_client = RpcClient(remote, opts)
            self._rpc_client.call("hello")  # fail fast on a dead leader
            # the diagnostics endpoint peers query for cluster_* rows;
            # registered with the leader now and re-announced on every
            # heartbeat (a restarted leader relearns the cluster shape)
            try:
                self.diag_listener = DiagListener(self, opts.diag_listen)
                self._rpc_client.ping_params = {
                    "diag_addr": self.diag_listener.address,
                    "role": "follower"}
                from ..rpc.errors import RPCError as _RPCError
                try:
                    self._rpc_client.call(
                        "diag_register",
                        addr=self.diag_listener.address,
                        role="follower", _budget_ms=1000)
                except _RPCError:
                    pass  # the next heartbeat re-registers
                self._rpc_client.start_heartbeat()
                self.coord = RemoteCoordinator(self._rpc_client, opts)
                # heartbeats also carry our node id so a leader elected
                # AFTER we joined (or restarted) rebuilds an id-accurate
                # membership registry from the beats alone
                self._rpc_client.ping_params["node_id"] = \
                    self.coord.node_id
            except BaseException:
                # a failed join must not leak the accept thread, the
                # bound socket, or the connected coordination client
                # (callers have no Storage to close)
                if self.diag_listener is not None:
                    self.diag_listener.close()
                self._rpc_client.close()
                raise
        elif self.shared:
            from .coordinator import SharedDirCoordinator
            self.coord = SharedDirCoordinator(path)
        self.catalog = Catalog()
        # per-server observability (metrics/slow log/statement digests);
        # module-global singletons clobbered each other when two servers
        # shared a process (round-2 verdict weak #6)
        from .. import obs as _obs
        from ..obs import Observability
        self.obs = Observability()
        # per-server diagnostics service (the diag/* RPC plane answers
        # from it; local stores query it directly for cluster_* tables)
        from ..rpc.diag import DiagService
        if self.diag_listener is not None:
            self.diag = self.diag_listener.service
        else:
            self.diag = DiagService(self)
        # server-wide overload protection (util/governor.py): the global
        # memory ledger + kill policy, and the execution admission gate.
        # Both disabled by default (limit 0 / tokens 0) — the server
        # entry point arms them from the [performance] config knobs.
        # Metrics ride this server's registry, so governor kills and
        # admission queue depth show up in /metrics, cluster_load and
        # the metrics history without extra plumbing.
        from ..util.governor import AdmissionGate, MemoryGovernor
        self.governor = MemoryGovernor(self.obs.metrics)
        self.admission = AdmissionGate(self.obs.metrics)
        # commit-time cap over a txn's ENCODED mutation bytes
        # (performance.txn-total-size-limit seeds it; 0 disables) —
        # enforced in commit() with ER_TXN_TOO_LARGE
        self.txn_total_size_limit = 100 * 1024 * 1024
        # bounded time-series of counter/gauge samples feeding
        # information_schema.metrics_summary + /debug/metrics/history.
        # The background thread starts with the serving Server (embedded
        # stores sample on demand), and Storage.close() always joins it.
        self.metrics_history = _obs.MetricsHistory(
            [self.obs.metrics, _obs.PROCESS_METRICS])
        # automated diagnosis plane (obs_inspect.py): per-storage
        # settings + edge-trigger memory, seeded from [diagnostics]
        # config by the server; embedded defaults enable it. The weak
        # tracking registry lets bench.py's flight child persist an
        # inspection snapshot of every live store when a flight dies.
        from .. import obs_inspect as _inspect
        self.diagnostics = _inspect.DiagnosticsState()
        _inspect.track(self)
        # workload-history plane (obs_history.py): per-digest
        # (sql_digest, plan_digest) plan/perf history, persisted under
        # <path>/history/ across restarts. Disabled by default (the Top
        # SQL zero-work contract); [history] config or embedded callers
        # arm it via history.configure(enabled=True).
        from ..obs_history import WorkloadHistory
        self.history = WorkloadHistory(path=path,
                                       metrics=self.obs.metrics,
                                       events=self.obs.events)
        # keyspace heat plane (obs_heat.py): per-range traffic matrix +
        # hot-range detection + split advisories. Same zero-work-while-
        # disabled contract as Top SQL / history; [heatmap] config or
        # embedded callers arm it via heat.configure(enabled=True).
        from ..obs_heat import RangeHeatRecorder
        self.heat = RangeHeatRecorder(metrics=self.obs.metrics,
                                      events=self.obs.events)
        self._tso_lease = 0
        # serializes lease-file persistence: concurrent committers both
        # crossing the extension threshold raced the SAME tmp+rename
        # pair (one replace unlinks the tmp the other is about to
        # rename — ENOENT), a race the group-commit throughput made
        # routine instead of theoretical
        self._lease_lock = lockcheck.lock("Storage._lease_lock")
        if path is not None:
            os.makedirs(os.path.join(path, "epochs"), exist_ok=True)
            self._tso_lease = self._read_tso_lease()
        self.stats = StatsHandle()
        self.tables: dict[int, TableStore] = {}
        # epoch-replacement listeners attached to every (current and
        # future) TableStore — the mesh plane registers its shared
        # client here so a folded epoch's device buffers free eagerly
        self._epoch_listeners: list = []
        # the transactional KV truth: percolator MVCC over regions
        if self.remote:
            # socket follower: the engine mirrors the leader's WAL over
            # RPC; its appends publish through the leased mutation
            # section (rpc/remote.py). The on-disk mirror under our
            # private dir is the promotion substrate: the byte-prefix
            # copy of the leader's (snapshot, WAL) pair an elected
            # follower re-opens as the authoritative store.
            from ..rpc.remote import RemoteKV
            engine = RemoteKV(self._rpc_client,
                              mirror_dir=os.path.join(path, "kv"),
                              sync_log=sync_log,
                              sync_interval_ms=sync_interval_ms)
            try:
                engine.bootstrap()
            except BaseException:
                # same no-leak contract as the join block above: a
                # failed WAL mirror leaves no listener/heartbeat behind
                engine.close()
                self.diag_listener.close()
                self._rpc_client.close()
                raise
            self.coord.engine = engine
        elif self.shared:
            # the shared-WAL refresh protocol lives in the Python engine;
            # the flock'd sections make its appends safe cross-process
            from ..kv.mvcc import PyOrderedKV
            engine = PyOrderedKV(os.path.join(path, "kv"), shared=True,
                                 sync_log=sync_log,
                                 sync_interval_ms=sync_interval_ms)
        else:
            engine = _make_engine(
                os.path.join(path, "kv") if path is not None else None,
                sync_log=sync_log, sync_interval_ms=sync_interval_ms)
        self.kv = MVCCStore(engine=engine, coord=self.coord)
        if path is not None and self._tso_lease == 0 and not self.remote:
            # lease file missing/corrupt: floor from the largest commit ts
            # in the reopened KV so timestamps still never repeat
            self._tso_lease = self.kv.max_commit_ts()
        if self.remote:
            # leader-allocated timestamps (the PD-client role); strict
            # SI because the ONE leader allocator issues every ts
            from ..kv.tso import RemoteTSO
            self.tso = RemoteTSO(
                self._rpc_client,
                allow_stale=self._rpc_client.options.stale_reads)
            # floor the stale-read fallback at the newest replicated
            # commit: a leader lost right after bootstrap must degrade
            # to "last replicated state", not to an empty ts-0 snapshot
            self.tso.observe(self.kv.max_commit_ts())
        elif self.shared:
            # ONE allocator for every process on this directory — strict
            # SI across servers (the PD TSO role, oracle/oracles/pd.go:77;
            # replaces the round-4 node-sliced oracle whose same-
            # millisecond interleavings were only bounded-staleness)
            from ..kv.tso import SharedTSO
            self.tso = SharedTSO(path, floor=self._tso_lease)
        else:
            self.tso = TimestampOracle(floor=self._tso_lease)
        self.rm = RegionManager(self.kv)
        self.committer = TwoPhaseCommitter(self.rm, self.tso,
                                           events=self.obs.events,
                                           heat=self.heat)
        # wire the structured event ring into its producers: governor
        # kills, admission sheds, rpc breaker trips, WAL fsync stalls —
        # the protective/durability actions PR 4/5 added become
        # queryable (information_schema.tidb_events) instead of only
        # being countable
        self.governor.events = self.obs.events
        self.admission.events = self.obs.events
        if self._rpc_client is not None:
            self._rpc_client.events = self.obs.events
        self._wire_fsync_stall(engine)
        # GLOBAL sysvar plane (mysql.global_variables analog) — rides the
        # meta keyspace, so durable stores keep SET GLOBAL across restarts
        from ..session.privileges import PrivilegeManager
        from ..session.sysvars import SysVarManager

        self.sysvars = SysVarManager(self)
        # grant tables (mysql.user analog) — same persistence plane
        self.privileges = PrivilegeManager(self)
        # SQL plan management bindings (mysql.bind_info analog)
        from ..session.bindinfo import BindingManager

        self.bindings = BindingManager(self)
        # GET_LOCK user locks (builtin_miscellaneous.go lock family)
        self.user_locks = UserLocks()
        # viewer-sensitive information_schema refresh+scan exclusion
        # (session._refresh_infoschema holds this for the statement)
        self.infoschema_lock = lockcheck.rlock(
            "Storage.infoschema_lock", hot=True)
        # DDL job queue + history (the meta-KV DDLJobList analog,
        # reference meta/meta.go:571) — lives on storage so a replacement
        # worker resumes pending jobs with their reorg checkpoints
        self.ddl_jobs: list = []
        self.ddl_history: list = []
        # owner election: DDL jobs and background GC run on the owner
        # only (reference: owner/manager.go etcd campaign; the mock at
        # owner/mock.go:35 for single-process; flock for processes
        # sharing this durable directory)
        if self.remote:
            # owner leases are cluster-wide, so a follower campaigns
            # through the leader (a local flock would elect everybody)
            from ..rpc.remote import RemoteOwnerManager
            self.ddl_owner = RemoteOwnerManager(self._rpc_client, "ddl")
            self.gc_owner = RemoteOwnerManager(self._rpc_client, "gc")
        else:
            from ..owner import owner_manager
            self.ddl_owner = owner_manager(path, "ddl")
            self.gc_owner = owner_manager(path, "gc")
        self._commit_lock = lockcheck.rlock(
            "Storage._commit_lock", hot=True)
        # cross-commit group fsync telemetry throttle (the batch-size
        # histogram records every batch; the event ring gets at most
        # one group_commit note per window with cumulative counts).
        # Locked: TWO SyncPolicy instances (engine + leader-side RPC
        # append) invoke the hook from unrelated leader threads.
        self._gc_lock = threading.Lock()
        self._gc_event_last = 0.0
        self._gc_batches = 0
        self._gc_commits = 0
        # seqlock generation for snapshot/fold consistency: odd while a
        # commit/refresh fold is in flight inside _commit_lock, even when
        # quiescent. Readers snapshot lock-free and retry on movement;
        # only a reader racing an active fold falls back to the lock.
        self._fold_seq = 0
        self._fold_depth = 0  # reentrancy: only the outermost bumps seq
        # active snapshot ts registry -> GC/compaction safepoint
        self._active_snapshots: dict[int, int] = {}
        self._snap_lock = threading.Lock()
        self._maintenance = None
        # waits-for edges for pessimistic deadlock detection
        # (reference: TiKV's deadlock detector service; util/deadlock)
        self._waits_for: dict[int, int] = {}
        self._waits_lock = threading.Lock()
        # sequence allocation cursors (runtime); the catalog's
        # SequenceInfo.next_value is the DURABLE high-water persisted
        # ahead of handed-out values, so a crash skips at most one cache
        # batch (reference: ddl/sequence.go cache allocation)
        self._seq_cursors: dict[int, int] = {}
        self._seq_lock = threading.Lock()
        if path is not None:
            self._recover()
            if not self.remote:
                self._extend_tso_lease()
            # persist schema on every catalog version bump from here on
            self.catalog.on_change = lambda: self.persist_catalog()
        if rpc_listen is not None:
            # leader: serve TSO/WAL/KILL coordination over the socket
            # so followers can join without sharing this directory
            if not self.shared or self.remote:
                raise ValueError(
                    "rpc_listen needs shared=True on the store-owning "
                    "server (a follower cannot re-serve the store)")
            from ..rpc.client import RpcOptions
            from ..rpc.server import CoordRPCServer
            opts = self._rpc_options = rpc_options or RpcOptions()
            self.rpc_server = CoordRPCServer(self, listen=rpc_listen,
                                             lease_ms=opts.lease_ms,
                                             tail_chunk=opts.tail_chunk)
        if self.remote and \
                (self._rpc_options.election_timeout_ms or 0) > 0:
            # automatic failover: watch the heartbeat, elect on leader
            # loss, promote or repoint (rpc/failover.py). The voter
            # roll is seeded NOW: a leader that dies before the first
            # healthy-tick refresh must not leave this follower with an
            # empty electorate (it would elect itself unopposed while
            # its unseen peers do the same — split brain)
            from ..rpc.diag import cluster_members
            try:
                cluster_members(self, budget_ms=1000)
            except Exception:  # noqa: BLE001 — seeding is best-effort
                pass
            from ..rpc.failover import FailoverManager
            self.failover = FailoverManager(self, self._rpc_options)
            self.failover.start()
        if self.remote:
            # follower read tier: fold the mirror continuously and
            # advertise the closed/applied ts on every heartbeat
            # (rpc/apply.py). Env knobs cover embedded/test stores the
            # config seeds never reach.
            interval = os.environ.get("TIDB_TPU_REPLICA_APPLY_MS")
            if interval:
                try:
                    self.replica_read.apply_interval_ms = int(interval)
                except ValueError:
                    pass
            if os.environ.get("TIDB_TPU_REPLICA_READ", "").lower() \
                    in ("0", "false", "off"):
                self.replica_read.enabled = False
            self.arm_replica_read()

    # ---- schema ------------------------------------------------------------
    def register_table(self, info: TableInfo) -> TableStore:
        part = getattr(info, "partition", None)
        if part is not None:
            return self._register_partitioned(info, part)
        store = TableStore(info)
        self.tables[info.id] = store
        self.adopt_table_store(store)
        # one region per table (reference: split-table-region on create,
        # ddl/split_region.go) — multi-table commits become multi-region
        try:
            self.rm.split(tablecodec.table_prefix(info.id))
        except ValueError:
            pass  # split point already a region boundary
        return store

    def _register_partitioned(self, info: TableInfo, part) -> TableStore:
        """Each partition is a full physical TableStore under its own
        table id/region (reference: partitions ARE tables,
        table/tables/partition.go); they share the parent's string
        dictionaries so cross-partition unions need no code remapping.
        Returns the first partition's store (the shared allocator)."""
        first: Optional[TableStore] = None
        shared_dicts = None
        for d in part.defs:
            child = self.child_table_info(info, d)
            store = TableStore(child)
            if shared_dicts is None:
                shared_dicts = store.dictionaries
            else:
                store.dictionaries = shared_dicts
            self.tables[d.id] = store
            self.adopt_table_store(store)
            try:
                self.rm.split(tablecodec.table_prefix(d.id))
            except ValueError:
                pass
            if first is None:
                first = store
        assert first is not None
        return first

    def adopt_table_store(self, store: TableStore) -> None:
        """Wire a (possibly externally constructed) TableStore into this
        storage's epoch plumbing: the durable-snapshot hook and the
        eager-eviction listeners. EVERY TableStore that lands in
        self.tables must pass through here (register_table, partition
        registration, TRUNCATE PARTITION's fresh store) or the mesh
        plane would never see that table's epoch folds."""
        if self.path is not None:
            store.on_epoch = self._on_epoch_changed
        for fn in self._epoch_listeners:
            if fn not in store.evict_hooks:
                store.evict_hooks.append(fn)

    def add_epoch_listener(self, fn) -> None:
        """Attach `fn(store)` to fire after every base-epoch
        replacement of every table (current and future); idempotent
        per listener. The mesh plane's eager device-buffer eviction."""
        if fn in self._epoch_listeners:
            return
        self._epoch_listeners.append(fn)
        for store in list(self.tables.values()):
            if fn not in store.evict_hooks:
                store.evict_hooks.append(fn)

    @staticmethod
    def child_table_info(info: TableInfo, d) -> TableInfo:
        """A partition's physical TableInfo: parent schema, own id."""
        import dataclasses
        return dataclasses.replace(info, id=d.id,
                                   name=f"{info.name}#{d.name}",
                                   partition=None)


    # ---- durability plane ---------------------------------------------------
    def _lease_file(self) -> str:
        import os
        return os.path.join(self.path, "tso.lease")

    def _read_tso_lease(self) -> int:
        try:
            with open(self._lease_file()) as f:
                return int(f.read().strip() or 0)
        except OSError:
            return 0

    def _extend_tso_lease(self) -> None:
        """Persist a ts horizon ahead of anything issued; cheap (runs only
        when current() nears the lease). Restart floors the oracle here,
        so commit timestamps stay monotonic across restarts even if the
        wall clock steps backwards."""
        lease = self.tso.current() + (_TSO_LEASE_MS << 18)
        tmp = self._lease_file() + ".tmp"
        import os

        from ..kv.mvcc import fsync_dir
        with open(tmp, "w") as f:
            f.write(str(lease))
            f.flush()
            if self.sync_log != "off":
                os.fsync(f.fileno())
        os.replace(tmp, self._lease_file())
        if self.sync_log != "off":
            # a lease bump lost to power loss would let a restarted
            # oracle re-issue timestamps the pre-crash process already
            # handed out; under sync-log=off the whole store accepts
            # the power-loss window, so the lease does too
            fsync_dir(self.path)
        self._tso_lease = lease

    def _maybe_extend_lease(self) -> None:
        if self.remote:
            return  # the leader persists the TSO horizon
        if self.path is not None and \
                self.tso.current() >= self._tso_lease - (
                    (_TSO_LEASE_MS // 2) << 18):
            with self._lease_lock:
                # re-check: a concurrent committer may have extended
                # while we waited (the lease covers everyone)
                if self.tso.current() >= self._tso_lease - (
                        (_TSO_LEASE_MS // 2) << 18):
                    self._extend_tso_lease()

    def persist_catalog(self) -> None:
        """Whole-catalog snapshot into the meta keyspace (reference: the
        m-prefix schema records, meta/meta.go:59-64,145-158). DDL-rate
        writes, so a full pickle beats incremental encoding complexity."""
        if self.path is None:
            return
        import pickle

        payload = pickle.dumps({
            "schemas": self.catalog.schemas,
            "next_id": self.catalog._next_id,
            "version": self.catalog.version,
        })
        self.put_meta(b"catalog", payload)

    def persist_ddl_jobs(self) -> None:
        """Pending DDL job queue (with reorg checkpoints) into meta-KV so a
        restart resumes interrupted jobs (reference: DDLJobList,
        meta/meta.go:571 + resumable reorg handles, ddl/reorg.go:263)."""
        if self.path is None:
            return
        import pickle

        self.put_meta(b"ddl:jobs", pickle.dumps(self.ddl_jobs))

    def _on_epoch_changed(self, store: TableStore, required: bool) -> None:
        """required=True (bulk load / DDL rewrite): the epoch holds data
        the KV truth cannot rebuild — persist now. required=False
        (compaction): folded deltas are still in KV, so just mark dirty
        and let checkpoint()/GC write the snapshot off the commit path."""
        if required:
            self._persist_epoch(store)
            store.epoch_dirty = False
        else:
            store.epoch_dirty = True

    def _epoch_file(self, table_id: int) -> str:
        import os
        return os.path.join(self.path, "epochs", f"t{table_id}.npz")

    def _persist_epoch(self, store: TableStore) -> None:
        """Columnar epoch snapshot (atomic tmp+rename). Fired on every
        base-epoch replacement — bulk_load, compaction, DDL reorg — the
        TiFlash-style checkpoint of the fold; KV WAL covers everything
        with commit_ts > fold_ts."""
        import os

        import numpy as np

        epoch = store.epoch
        payload: dict = {
            "handles": epoch.handles,
            "fold_ts": np.int64(epoch.fold_ts),
            "next_handle": np.int64(store._next_handle),
            "ncols": np.int64(len(epoch.columns)),
        }
        for ci, (data, valid) in enumerate(zip(epoch.columns, epoch.valids)):
            payload[f"col{ci}"] = data
            if valid is not None:
                payload[f"valid{ci}"] = valid
            d = store.dictionaries[ci]
            if d is not None:
                payload[f"dict{ci}"] = np.array(list(d.values), dtype=object)
        path = self._epoch_file(store.table.id)
        tmp = path + ".tmp"
        from ..kv.mvcc import fsync_dir
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            if self.sync_log != "off":
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if self.sync_log != "off":
            # full crash-atomic sequence (tmp + fsync + rename + dir
            # fsync): a half-written epoch must never shadow the
            # previous good one — recovery treats the epoch as the fold
            # floor and skips the WAL below its fold_ts. sync-log=off
            # keeps the atomic rename but accepts the power-loss window
            # (epoch snapshots can be a bulk load's multi-MB fsync).
            fsync_dir(os.path.dirname(path))

    def _load_epoch(self, store: TableStore) -> None:
        import os

        import numpy as np

        from ..chunk.column import Dictionary
        from .table_store import ColumnEpoch, _epoch_ids

        path = self._epoch_file(store.table.id)
        if not os.path.exists(path):
            return
        try:
            z_ctx = np.load(path, allow_pickle=True)
        except Exception:  # noqa: BLE001 — torn/corrupt archive
            # an unreadable epoch snapshot (crash mid-write on a
            # filesystem without atomic rename, bit rot) must degrade
            # to a full refold from the KV truth, never to a crash at
            # open — drop it so the next checkpoint rewrites it
            try:
                os.remove(path)
            except OSError:
                pass
            return
        with z_ctx as z:
            ncols = int(z["ncols"])
            if ncols != store.table.num_columns:
                return  # schema moved past this snapshot; refold from KV
            handles = z["handles"]
            columns = [z[f"col{ci}"] for ci in range(ncols)]
            valids = [
                z[f"valid{ci}"] if f"valid{ci}" in z else None
                for ci in range(ncols)
            ]
            dicts: list = []
            for ci in range(ncols):
                cft = store.table.columns[ci].ftype
                if getattr(cft, "elems", ()) and cft.is_string:
                    # ENUM: the fixed validating dictionary, rebuilt from
                    # the schema (codes are definition positions)
                    from .table_store import _column_dictionary
                    dicts.append(_column_dictionary(cft))
                elif f"dict{ci}" in z:
                    d = Dictionary()
                    for s in z[f"dict{ci}"]:
                        d.encode(str(s))
                    dicts.append(d)
                else:
                    dicts.append(None)
            epoch = ColumnEpoch(
                epoch_id=next(_epoch_ids),
                fold_ts=int(z["fold_ts"]),
                handles=handles,
                columns=columns,
                valids=valids,
            )
            store.restore_epoch(epoch, dicts, int(z["next_handle"]))

    def _kv_row(self, store: Optional[TableStore], row) -> list:
        """Physical row -> KV value encoding. String dictionary codes are
        decoded to the actual strings so the KV truth is self-contained
        (recovery re-encodes through the rebuilt dictionary)."""
        if store is None:
            return list(row)
        out = []
        for v, d in zip(row, store.dictionaries):
            if d is not None and v is not None:
                out.append(d.decode(int(v)))
            else:
                out.append(v)
        return out

    def _fold_row(self, store: TableStore, values: list) -> tuple:
        """KV value -> physical row (inverse of _kv_row). Rows written
        before an ADD COLUMN carry the old arity: pad with the new
        columns' defaults (the instant-add-column read path; reference:
        rows keep origin version, defaults fill at decode,
        table/tables/tables.go DecodeRawRowData)."""
        cols = store.table.columns
        if len(values) < len(cols):
            from ..ddl.ddl import _phys_default
            values = list(values) + [
                None if c.default is None
                else _phys_default(c.ftype, c.default)
                for c in cols[len(values):]]
        out = []
        for v, col, d in zip(values, store.table.columns,
                             store.dictionaries):
            if v is None:
                out.append(None)
            elif d is not None:
                s = v.decode("utf-8") if isinstance(v, bytes) else str(v)
                out.append(d.encode(s))
            elif isinstance(v, bytes):
                out.append(v.decode("utf-8"))
            else:
                out.append(v)
        return tuple(out)

    def _recover(self) -> None:
        """Bootstrap from the reopened KV + epoch snapshots: catalog, table
        stores, committed rows newer than each epoch's fold, stats, pending
        DDL. Orphaned percolator locks are resolved first (the restarted
        process has no live transactions)."""
        import pickle

        raw = self.get_meta(b"catalog")
        if raw is None:
            return  # fresh directory
        if not self.remote:
            # a JOINING follower must not touch locks: siblings may have
            # live transactions (the leader resolved true orphans at its
            # own startup)
            self._resolve_orphans()
        state = pickle.loads(raw)
        self.catalog.schemas = state["schemas"]
        self.catalog._next_id = state["next_id"]
        self.catalog.version = state["version"]
        for schema in self.catalog.schemas.values():
            for info in schema.tables.values():
                self.register_table(info)
                part = getattr(info, "partition", None)
                ids = [d.id for d in part.defs] if part is not None \
                    else [info.id]
                for tid in ids:
                    store = self.tables[tid]
                    self._load_epoch(store)
                    lo, hi = tablecodec.record_range(tid)
                    folds = []
                    for key, commit_ts, kind, val in self.kv.scan_latest(
                            lo, hi):
                        if commit_ts <= store.epoch.fold_ts:
                            continue
                        _, handle = tablecodec.decode_record_key(key)
                        if kind == OP_DEL:
                            if handle in store.epoch.handle_pos:
                                folds.append((commit_ts, handle, TOMBSTONE))
                        else:
                            row = self._fold_row(store,
                                                 codec.decode_key(val))
                            folds.append((commit_ts, handle, row))
                            store.note_handle(handle)
                    folds.sort(key=lambda t: t[0])
                    for commit_ts, handle, row in folds:
                        store.apply_commit(commit_ts, handle, row)
                if part is not None:
                    # the first partition's store allocates handles for
                    # the WHOLE table: its counter must cover handles
                    # living in every sibling partition
                    first = self.tables[ids[0]]
                    first._next_handle = max(
                        self.tables[tid]._next_handle for tid in ids)
        self.stats.load_from_kv(self, self.catalog)
        raw = self.get_meta(b"ddl:jobs")
        if raw:
            self.ddl_jobs = pickle.loads(raw)
        if self.ddl_jobs:
            # owner-takeover: drive interrupted jobs from their persisted
            # reorg checkpoints (reference: ddl_worker.go:419 + reorg.go:263).
            # A job that legitimately rolls back (e.g. unique validation
            # fails) is a normal outcome, not a reason to refuse to open.
            from ..ddl import DDL, DDLError

            ddl = DDL(self, self.catalog)
            while self.ddl_jobs:
                try:
                    ddl.run_job(self.ddl_jobs[0])
                except DDLError:
                    pass

    def _resolve_orphans(self) -> None:
        """Roll crashed transactions forward or back from their primary's
        fate (reference: lock_resolver.go at restart; every pre-crash lock
        is orphaned by definition)."""
        from ..kv.mvcc import KVError as _KVError

        far_future = self.tso.next_ts() + (1 << 62)
        for lock in self.kv.all_locks():
            try:
                commit_ts, _ = self.kv.check_txn_status(
                    lock.primary, lock.start_ts, far_future)
                self.kv.resolve_lock(lock.key, lock.start_ts, commit_ts)
            except _KVError:
                pass

    def checkpoint(self, dirty_only: bool = False) -> None:
        """Fold the KV WAL into a snapshot file and persist table epochs
        (clean-shutdown / periodic maintenance entry). dirty_only skips
        epochs whose snapshot is already current (the background loop's
        mode); the WAL always folds."""
        if self.path is None:
            return
        import time as _time

        from ..util import failpoint
        t0 = _time.perf_counter()
        self._flush_sequence_cursors()
        for store in list(self.tables.values()):  # DDL may race the daemon
            if dirty_only and not getattr(store, "epoch_dirty", False):
                continue
            self._persist_epoch(store)
            store.epoch_dirty = False
            # crash-injection site: the torture harness kills here with
            # some epochs persisted and the KV WAL not yet folded —
            # recovery must treat the half-finished checkpoint as noise
            failpoint.inject("storage/mid-checkpoint")
        self.kv.checkpoint()
        dt = _time.perf_counter() - t0
        if dt >= 1.0:
            # a slow checkpoint competes with the commit path for the
            # WAL/fsync — surface it in the event ring so a latency
            # spike is explainable after the fact
            self.obs.events.record(
                "checkpoint_stall", severity="warn",
                detail=f"checkpoint took {dt * 1e3:.0f}ms "
                       f"({len(self.tables)} tables, "
                       f"dirty_only={dirty_only})")

    @property
    def maintenance(self):
        """The storage's background worker (GC / lock-TTL / auto-analyze /
        checkpoint); created lazily, started by the server or tests
        (reference: gcworker started by the tikv store, gc_worker.go:95)."""
        if self._maintenance is None:
            from .daemon import MaintenanceWorker
            self._maintenance = MaintenanceWorker(self, self.catalog)
        return self._maintenance

    @property
    def diag_address(self) -> str:
        """Where THIS server's diag service answers: the leader serves
        it on the coordination port, a follower on its diag listener."""
        if self.rpc_server is not None:
            return self.rpc_server.address
        if self.diag_listener is not None:
            return self.diag_listener.address
        return ""

    def transport_health(self) -> dict:
        """Multi-process transport state for the status port (reference:
        http_status.go exposes store health the same way). Socket modes
        include the membership view — peer id, diag address, role,
        last-heartbeat age — so operators see the cluster shape without
        SQL (the same registry the cluster_* tables fan out over)."""
        if self.remote:
            h = self._rpc_client.health()
            h["mode"] = "socket-follower"
            h["node_id"] = self.coord.node_id
            h["diag_address"] = self.diag_address
            h["term"] = self._rpc_client.term
            if self.failover is not None:
                h["failover"] = self.failover.describe()
            if self.apply_engine is not None:
                h["replica_apply"] = self.apply_engine.info()
            from ..rpc.diag import cluster_members
            h["members"] = cluster_members(self, budget_ms=500)
            return h
        if self.rpc_server is not None:
            return {"mode": "socket-leader",
                    "address": self.rpc_server.address,
                    "term": self.rpc_server.term,
                    "clients": self.rpc_server.client_count(),
                    "members": self.rpc_server.members()}
        if self.shared:
            return {"mode": "shared-dir", "node_id": self.coord.node_id}
        return {"mode": "local"}

    # ---- leader failover (rpc/failover.py drives these) ---------------------
    def _wire_fsync_stall(self, engine) -> None:
        """Point the engine's SyncPolicy stall hook at this server's
        event ring. Called from __init__ AND from promotion — the
        promoted leader swaps in a brand-new engine, and losing the
        hook there would blind the event log on exactly the node (and
        scenario: post-failover latency spike) it exists to explain."""
        syncer = getattr(engine, "_syncer", None) or \
            getattr(engine, "_mirror_sync", None)
        if syncer is None:
            return
        _ev = self.obs.events

        def _fsync_stall(dt_s: float) -> None:
            _ev.record("fsync_stall", severity="warn",
                       detail=f"wal fsync took {dt_s * 1e3:.1f}ms "
                              f"(policy {syncer.policy})")

        syncer.on_stall = _fsync_stall
        syncer.on_batch = self._note_group_commit

    def _note_group_commit(self, batch: int) -> None:
        """Group-fsync batch telemetry: every batch lands in the
        tidb_group_commit_batch_size histogram; the event ring gets a
        throttled group_commit note (cumulative since the last one) so
        fsync amortization is visible in metrics_schema + tidb_events
        without flooding the ring at thousands of commits/s."""
        import time as _time
        self.obs.group_commit_batch.observe(batch)
        self.obs.group_commit_fsyncs.inc()
        self.obs.group_commit_commits.inc(batch)
        emit = None
        with self._gc_lock:
            self._gc_batches += 1
            self._gc_commits += batch
            now = _time.monotonic()
            if batch > 1 and now - self._gc_event_last >= 5.0:
                self._gc_event_last = now
                emit = (self._gc_commits, self._gc_batches)
                self._gc_batches = 0
                self._gc_commits = 0
        if emit is not None:
            commits, batches = emit
            self.obs.events.record(
                "group_commit",
                detail=f"{commits} commits over {batches} wal fsyncs "
                       f"({commits / max(batches, 1):.1f} avg batch) "
                       "since the last note")

    def configure_group_commit(self, max_batch: Optional[int] = None,
                               max_wait_us: Optional[int] = None) -> None:
        """Apply the storage.group-commit-* knobs to the engine's
        SyncPolicy (server startup + SIGHUP hot reload)."""
        syncer = getattr(self.kv.kv, "_syncer", None)
        if syncer is None:
            return
        if max_batch is not None:
            syncer.group_max_batch = max(int(max_batch), 1)
        if max_wait_us is not None:
            syncer.group_max_wait_us = max(int(max_wait_us), 0)

    def promote_to_leader(self, listen: str = "127.0.0.1:0") -> str:
        """Promote this socket FOLLOWER to the cluster leader in place.

        The on-disk WAL mirror (rpc/remote.py RemoteKV) is a byte-prefix
        of the dead leader's (snapshot, WAL) pair, so it re-opens as the
        authoritative store and surviving followers keep tailing from
        their own offsets. The fencing term bumps and persists BEFORE
        the new coordination server answers anything, so a zombie of
        the old epoch is rejected from the first request (reference
        analog: raft term bump on election, Ongaro & Ousterhout §5.2).
        Returns the new coordination address."""
        if not self.remote:
            return self.rpc_server.address if self.rpc_server else ""
        from ..rpc.client import RpcOptions

        client = self._rpc_client
        opts = self._rpc_options or RpcOptions()
        new_term = int(client.term) + 1
        # the transitional flag keeps peer voters from dropping us from
        # the electorate mid-promotion (they hold their election open
        # until we answer as a leader)
        self._promoting = True
        try:
            # the apply engine folds the mirror this promotion is about
            # to re-open as the authoritative engine: stop it first
            if self.apply_engine is not None:
                self.apply_engine.close()
                self.apply_engine = None
            addr = self._promote_locked(client, opts, new_term, listen)
            self.obs.events.record(
                "leader_promoted", severity="warn",
                detail=f"promoted in place at {addr} "
                       f"(fencing term {new_term})")
            return addr
        finally:
            self._promoting = False

    def _promote_locked(self, client, opts, new_term: int,
                        listen: str) -> str:
        import os

        from ..kv.mvcc import PyOrderedKV
        from ..kv.tso import SharedTSO
        from ..kv.twopc import TwoPhaseCommitter as _TPC
        from ..owner import owner_manager
        from ..rpc.server import CoordRPCServer, write_term
        from .coordinator import SharedDirCoordinator

        with self._commit_lock:
            old_engine = self.kv.kv
            mirror_dir = getattr(old_engine, "mirror_dir", None) or \
                os.path.join(self.path, "kv")
            # 1. seal the mirror: everything replicated is on disk
            mw = getattr(old_engine, "_mirror_wal", None)
            if mw is not None:
                mw.flush()
                os.fsync(mw.fileno())
            old_engine.close()
            # 2. the bumped fencing term, durable beside the WAL
            write_term(os.path.join(mirror_dir, "term"), new_term)
            # 3. the mirror becomes the authoritative engine (replayed
            #    exactly like a leader restart; shared mode so local and
            #    remote mutators coexist through the flock)
            engine = PyOrderedKV(mirror_dir, shared=True,
                                 sync_log=self.sync_log,
                                 sync_interval_ms=self.sync_interval_ms)
            self.kv.kv = engine
            self._wire_fsync_stall(engine)
            # 4. coordination over OUR directory now
            self.coord = SharedDirCoordinator(self.path)
            self.kv.coord = self.coord
            # 5. ONE timestamp allocator, floored a full lease horizon
            #    above anything witnessed: the dead leader may have
            #    issued timestamps nobody replicated, and a commit_ts
            #    reuse would corrupt MVCC visibility
            floor = max(self.tso.current(), self.kv.max_commit_ts()) \
                + (_TSO_LEASE_MS << 18)
            self.tso = SharedTSO(self.path, floor=floor)
            self.committer = _TPC(self.rm, self.tso,
                                  events=self.obs.events,
                                  heat=self.heat)
            # 6. owner elections are kernel flocks on our dir
            self.ddl_owner = owner_manager(self.path, "ddl")
            self.gc_owner = owner_manager(self.path, "gc")
            # 7. identity flip BEFORE serving: diag answers as leader
            self.remote = False
            self.shared = True
            self._rpc_client = None
            # 8. the old client (and its heartbeat thread) dies with the
            #    old epoch; stragglers re-resolve via diag_election
            client.ping_params = {}
            client.close()
            self.rpc_server = CoordRPCServer(
                self, listen=listen, lease_ms=opts.lease_ms,
                tail_chunk=opts.tail_chunk, term=new_term)
            self._extend_tso_lease()
            # 9. the dead leader's in-flight prewrites replicated as
            #    orphan locks; resolve them exactly like a restart does
            self._resolve_orphans()
        return self.rpc_server.address

    def repoint_leader(self, addr: str, term: int = 0) -> None:
        """Re-resolve this follower to a newly promoted leader: swap
        the client's address, adopt the bumped term, and re-register
        the diag endpoint so the new membership registry fills without
        waiting a heartbeat interval. The WAL tail position carries
        over unchanged — the new leader's log is a byte-superset of
        ours (it won the election on length)."""
        client = self._rpc_client
        if client is None:
            return
        client.repoint(addr, int(term))
        self.obs.events.record(
            "leader_repointed",
            detail=f"following new leader at {addr} (term {term})")
        from ..rpc.errors import RPCError as _RPCError
        try:
            if self.diag_listener is not None:
                client.call("diag_register",
                            addr=self.diag_listener.address,
                            role="follower", _budget_ms=1000)
        except _RPCError:
            pass  # the next heartbeat re-registers

    def close(self) -> None:
        # the failover watcher first: a leader-loss election must not
        # fire (or promote!) halfway through our own teardown
        if self.failover is not None:
            self.failover.close()
        # the apply engine next: its tick path runs RPC + fold against
        # the structures torn down below
        if self.apply_engine is not None:
            self.apply_engine.close()
            self.apply_engine = None
        # diagnostics plane next: the history sampler and the follower
        # diag listener are joined here so no thread outlives the store
        # (the profiler-lifecycle contract tests/test_trace.py pins)
        self.metrics_history.stop()
        # rotate + persist the live workload-history window so a clean
        # shutdown keeps the newest partial window too (no-op while
        # history is disabled; kill -9 keeps everything already rotated)
        try:
            self.history.flush()
        except Exception:  # noqa: BLE001 — teardown must not fail
            pass
        if self.diag_listener is not None:
            if self._rpc_client is not None:
                from ..rpc.errors import RPCError as _RPCError
                # stop announcing BEFORE deregistering: a heartbeat
                # firing between the unregister and the client teardown
                # below would re-register the closed address for a
                # lease horizon
                self._rpc_client.ping_params = {}
                try:
                    # best-effort deregistration so peers stop fanning
                    # out to the closed address (otherwise they pay the
                    # diag budget per query until the lease horizon
                    # passes)
                    self._rpc_client.call("diag_unregister",
                                          _budget_ms=500)
                except _RPCError:
                    pass
            self.diag_listener.close()
        from ..rpc.diag import close_peer_clients
        close_peer_clients(self)
        if self._maintenance is not None:
            self._maintenance.stop()
        if self.ranges is not None:
            self.ranges.close()
            self.ranges = None
        if self.rpc_server is not None:
            self.rpc_server.close()
        self.ddl_owner.close()
        self.gc_owner.close()
        if self.path is None:
            return
        if self.remote:
            from ..kv.backoff import BackoffExhausted
            from ..rpc.errors import RPCError
            try:
                # a follower's checkpoint writes through the leader; a
                # dead leader must not turn shutdown into a hang
                self.checkpoint()
            except (RPCError, BackoffExhausted):
                pass
            self._rpc_client.close()
            close = getattr(self.kv.kv, "close", None)
            if close is not None:
                close()  # the WAL mirror handles
            if self._owns_tmp_dir:
                # rpc:// shorthand: the throwaway scratch dir is ours
                import shutil
                shutil.rmtree(self.path, ignore_errors=True)
            return
        self.checkpoint()
        close = getattr(self.kv.kv, "close", None)
        if close is not None:
            close()

    def unregister_table(self, table_id: int) -> None:
        self.tables.pop(table_id, None)

    def destroy_table_data(self, table_id: int) -> None:
        """Physically drop a table's KV range + epoch snapshot (DROP/
        TRUNCATE path; reference: UnsafeDestroyRange driven by the GC
        worker for dropped objects, ddl/delete_range.go +
        store/tikv/gcworker). Without this, restart recovery would
        resurrect dropped rows from the KV truth."""
        lo, hi = tablecodec.table_range(table_id)
        self.kv.unsafe_destroy_range(lo, hi)
        if self.path is not None:
            import os
            try:
                os.remove(self._epoch_file(table_id))
            except OSError:
                pass

    def table_store(self, table_id: int) -> TableStore:
        return self.tables[table_id]

    # ---- range-sharded write leadership (rpc/ranged.py) ---------------------
    def arm_ranges(self, enabled: bool = False, count: int = 1,
                   split_points=(), lease_ms: int = 1000,
                   resolve_ttl_ms: int = 3000,
                   listen: str = "127.0.0.1:0",
                   auto_split: bool = False,
                   split_cooldown_ms: int = 10000,
                   max_auto_splits: int = 4) -> None:
        """Start the range plane to match the [ranges] settings (called
        from Config.seed_ranges on startup/SIGHUP). lease-ms,
        resolve-ttl-ms and the auto-split actuator knobs reload live;
        enabling/disabling or reshaping the table needs a restart (the
        table is durable, first writer wins). Only a durable local
        store can host range leaders — followers and in-memory stores
        route to one that does."""
        if self.ranges is not None:
            if enabled:
                self.ranges.set_knobs(
                    lease_ms=lease_ms, resolve_ttl_ms=resolve_ttl_ms,
                    auto_split=auto_split,
                    split_cooldown_ms=split_cooldown_ms,
                    max_auto_splits=max_auto_splits)
            return
        if not enabled or self.remote or self.path is None:
            return
        from ..rpc.ranged import RangePlane
        self.ranges = RangePlane(self, count=count,
                                 split_points=split_points,
                                 lease_ms=lease_ms,
                                 resolve_ttl_ms=resolve_ttl_ms,
                                 listen=listen,
                                 auto_split=auto_split,
                                 split_cooldown_ms=split_cooldown_ms,
                                 max_auto_splits=max_auto_splits)
        # the heat matrix resolves against the authoritative table the
        # plane just bootstrapped (first writer wins; re-seed adopts)
        self.heat.set_specs(self.ranges.server.specs)

    # ---- follower read tier (rpc/apply.py + rpc/replica.py) -----------------
    def arm_replica_read(self) -> None:
        """Start or stop the continuous apply engine to match the
        replica-read settings (called from __init__ and from
        Config.seed_replica_read on startup/SIGHUP). Leaders and
        local stores never run one — the engine folds a MIRROR."""
        if not self.remote:
            return
        from ..rpc.apply import ApplyEngine
        if self.replica_read.enabled and self.apply_engine is None:
            self.apply_engine = ApplyEngine(
                self, interval_ms=self.replica_read.apply_interval_ms)
        elif self.replica_read.enabled:
            # a reseed with a new cadence adjusts the running engine
            self.apply_engine.interval_ms = max(
                10, int(self.replica_read.apply_interval_ms))
        elif self.apply_engine is not None:
            eng, self.apply_engine = self.apply_engine, None
            eng.close()
            # the heartbeat must stop advertising a serving replica
            # (atomic dict REPLACEMENT — the heartbeat thread unpacks
            # ping_params concurrently)
            client = self._rpc_client
            if client is not None:
                client.ping_params = {**client.ping_params,
                                      "serving": False,
                                      "applied_ts": 0,
                                      "apply_lag_ms": 0.0}

    def pin_snapshot_ts(self, ts: int) -> None:
        """Register an EXTERNALLY chosen snapshot ts (a routed replica
        read at the router's read_ts) with the compaction safepoint;
        released through release_snapshot_ts like any acquired one."""
        with self._snap_lock:
            self._active_snapshots[ts] = \
                self._active_snapshots.get(ts, 0) + 1

    def _tso_commit_done(self) -> None:
        """Retire this storage's pending-commit ledger entry (socket
        followers; rpc/server.py closed_info). No-op on local oracles.
        Called OUTSIDE the commit lock — it is an RPC."""
        done = getattr(self.tso, "commit_done", None)
        if done is not None:
            try:
                done()
            except Exception:  # noqa: BLE001 — best-effort retire
                pass

    # ---- snapshot registry (compaction safepoint) ---------------------------
    def acquire_snapshot_ts(self) -> int:
        ts = self.tso.next_ts()
        with self._snap_lock:
            self._active_snapshots[ts] = self._active_snapshots.get(ts, 0) + 1
        return ts

    def release_snapshot_ts(self, ts: int) -> None:
        with self._snap_lock:
            n = self._active_snapshots.get(ts, 0) - 1
            if n <= 0:
                self._active_snapshots.pop(ts, None)
            else:
                self._active_snapshots[ts] = n

    def safe_ts(self) -> int:
        """Newest ts that every active snapshot is at or above."""
        with self._snap_lock:
            if self._active_snapshots:
                return min(self._active_snapshots) - 1
        return self.tso.current()

    # ---- transactions ------------------------------------------------------
    def begin(self, pessimistic: bool = False) -> "Transaction":
        txn = Transaction(self, self.acquire_snapshot_ts(),
                          pessimistic=pessimistic)
        # a snapshot ts at/below the oracle's stale watermark was
        # re-issued while the leader was unreachable: reads are fine
        # (bounded staleness), writes must fail typed (_check_writable)
        wm = getattr(self.tso, "stale_watermark", None)
        txn.degraded = wm is not None and txn.start_ts <= wm
        return txn

    def _check_writable(self, txn: "Transaction") -> None:
        if getattr(txn, "degraded", False):
            from ..rpc.errors import LeaderUnavailable
            raise LeaderUnavailable(
                "store leader unreachable: this server is serving "
                "stale reads only; writes are rejected until the "
                "leader lease is renewed")

    class DeadlockError(CodedError):
        errno = 1213  # ER_LOCK_DEADLOCK
        sqlstate = "40001"

    class LockWaitTimeout(CodedError):
        errno = 1205  # ER_LOCK_WAIT_TIMEOUT

    def pessimistic_lock_keys(self, txn: "Transaction", keys: list[bytes],
                              timeout_s: float = 50.0) -> bool:
        """Acquire pessimistic locks with wait + deadlock detection
        (reference: executor/adapter.go:533 handlePessimisticDML ->
        pessimistic.go lock-wait; deadlock detection is TiKV's detector
        service, here a local waits-for graph).

        WriteConflictError (a commit newer than txn.for_update_ts)
        propagates to the caller, which retries its whole statement at a
        fresh for_update_ts — the same retry the reference drives via
        ErrWriteConflict in pessimistic mode (adapter.go:623)."""
        import time as _time

        if not keys:
            return False
        self._check_writable(txn)
        keys = sorted(keys)
        if txn.pessimistic_primary is None:
            txn.pessimistic_primary = keys[0]
        deadline = _time.monotonic() + timeout_s
        backoff = 0.001
        waited = False
        while True:
            try:
                self.kv.pessimistic_lock(keys, txn.pessimistic_primary,
                                         txn.start_ts, txn.for_update_ts)
                with self._waits_lock:
                    self._waits_for.pop(txn.start_ts, None)
                txn.locked_keys.update(keys)
                txn.start_heartbeat()
                # True = we blocked on someone: the caller's read view may
                # predate whatever that someone committed and needs a
                # refresh before constraint checks
                return waited
            except KVError as e:
                from ..kv.mvcc import KeyIsLockedError
                if not isinstance(e, KeyIsLockedError):
                    with self._waits_lock:
                        self._waits_for.pop(txn.start_ts, None)
                    raise
                holder = e.lock.start_ts
                with self._waits_lock:
                    # cycle check before we block on `holder`
                    self._waits_for[txn.start_ts] = holder
                    seen = {txn.start_ts}
                    cur = holder
                    while cur in self._waits_for:
                        cur = self._waits_for[cur]
                        if cur in seen:
                            self._waits_for.pop(txn.start_ts, None)
                            raise Storage.DeadlockError(
                                "Deadlock found when trying to get lock; "
                                "try restarting transaction")
                        seen.add(cur)
                # the holder may be dead: TTL-expired locks resolve now
                from ..kv.twopc import LockResolver
                try:
                    LockResolver(self.rm, self.tso).resolve(e.lock)
                except KVError:
                    pass
                if _time.monotonic() >= deadline:
                    with self._waits_lock:
                        self._waits_for.pop(txn.start_ts, None)
                    raise Storage.LockWaitTimeout(
                        "Lock wait timeout exceeded; try restarting "
                        "transaction") from None
                waited = True
                _time.sleep(backoff)
                backoff = min(backoff * 2, 0.05)

    def commit(self, txn: "Transaction") -> int:
        """THE commit path: schema fence -> percolator 2PC through the
        region tier -> columnar fold. One source of truth (the KV write
        records), one fold (the epochs the coprocessor reads)."""
        mutations = txn.memdb.mutations()
        if mutations:
            self._check_writable(txn)
        if not mutations:
            if txn.locked_keys:
                # lock-only txn (SELECT FOR UPDATE with no writes): the
                # guards served their purpose; drop them
                self.kv.pessimistic_rollback(sorted(txn.locked_keys),
                                             txn.start_ts)
            return txn.start_ts
        self._maybe_extend_lease()
        # fence + encode happen OUTSIDE the commit lock: prewrite can
        # block on other txns' row locks for the whole lock-wait budget,
        # and holding the commit lock there would stall every other
        # commit — including the lock holder's, a guaranteed deadlock.
        # The fence re-check inside the lock stays authoritative.
        self._check_schema_fence(txn)
        kv_muts = []
        written = set()
        try:
            for (table_id, handle), row in mutations.items():
                key = tablecodec.record_key(table_id, handle)
                written.add(key)
                if row is TOMBSTONE:
                    kv_muts.append(Mutation(OP_DEL, key))
                else:
                    kv_muts.append(Mutation(OP_PUT, key, codec.encode_key(
                        self._kv_row(self.tables.get(table_id), row))))
        except (IndexError, KeyError):
            # dictionary codes no longer decode: DDL rewrote the column
            # between our buffering and this encode
            raise WriteConflictError(
                "Information schema is changed during the execution "
                "of the statement; try again",
                errno=ER_SCHEMA_CHANGED) from None
        # pessimistic guards on unwritten keys commit as lock-only
        # records so 2PC clears them atomically (reference: OP_LOCK
        # mutations through prewrite; kv/memdb lock-only entries)
        from ..kv.mvcc import OP_LOCK
        for key in sorted((txn.locked_keys | txn.guard_keys) - written):
            kv_muts.append(Mutation(OP_LOCK, key))
        # performance.txn-total-size-limit over the ENCODED bytes —
        # measured here (post-encode, pre-prewrite) so the limit means
        # what hits the region tier, and an oversized txn fails before
        # prewriting a single lock
        limit = self.txn_total_size_limit
        if limit > 0:
            total = sum(len(m.key) + len(m.value) for m in kv_muts)
            if total > limit:
                # clear pessimistic locks/guards already written to the
                # KV (same courtesy as every failed-commit sibling path)
                # — an orphaned OP_LOCK would stall writers on those
                # rows for the full lock TTL
                self._best_effort_rollback(kv_muts, txn.start_ts)
                raise TxnTooLargeError(
                    f"Transaction is too large, size: {total} "
                    f"(txn-total-size-limit: {limit})")
        try:
            state = self.committer.prewrite_phase(kv_muts, txn.start_ts)
        except KVWriteConflict as e:
            self.obs.conflicts.inc()
            self._best_effort_rollback(kv_muts, txn.start_ts)
            raise WriteConflictError(str(e)) from None
        except (KVError, CommitError) as e:
            self._best_effort_rollback(kv_muts, txn.start_ts)
            raise WriteConflictError(f"commit failed: {e}") from None
        try:
            with self._commit_lock, self._fold_section():
                if self.shared:
                    # fold sibling commits observed during prewrite and
                    # adopt any schema change BEFORE the authoritative
                    # fence check
                    self.kv.refresh()
                    self._drain_refresh()
                try:
                    self._check_schema_fence(txn)
                except WriteConflictError:
                    self._best_effort_rollback(kv_muts, txn.start_ts)
                    raise
                try:
                    commit_ts = self.committer.commit_phase(
                        state, txn.start_ts)
                except (KVError, CommitError) as e:
                    self._best_effort_rollback(kv_muts, txn.start_ts)
                    raise WriteConflictError(
                        f"commit failed: {e}") from None
                # columnar fold of the committed mutations (the
                # coprocessor's read view) — inside the lock so no
                # snapshot can observe the KV commit without the fold
                from ..util import failpoint
                failpoint.inject("storage/before-fold")
                for (table_id, handle), row in mutations.items():
                    store = self.tables.get(table_id)
                    if store is not None:
                        store.apply_commit(commit_ts, handle, row)
        finally:
            # pending-commit ledger retire (socket followers): by now
            # the commit records are published or never will be, so the
            # leader's closed ts may advance past our commit_ts
            self._tso_commit_done()
        # durability BEFORE the ack, AFTER the commit lock: under
        # sync-log=commit the engine deferred the boundary fsync out of
        # the mutation sections, so concurrent committers rendezvous
        # here on ONE in-flight fsync (cross-commit group commit) —
        # durable throughput scales with concurrency instead of
        # serializing N x 17ms behind the commit lock. A failed fsync
        # must not ack — but the commit IS already applied and visible
        # (as it was when the in-section fsync failed at commit-phase
        # exit), so the error must NOT read as a retryable write
        # conflict: a client retrying a "failed" increment would
        # double-apply it. KVError propagates untyped ("result
        # unknown"), and _run_in_txn's autocommit retry ignores it.
        try:
            self.kv.commit_sync()
        except OSError as e:
            raise KVError(
                "commit durability unknown: WAL fsync failed after the "
                f"commit was applied ({e}); do not blindly retry"
            ) from e
        self.obs.commits.inc()
        # opportunistic compaction at the GC-safe ts
        safe = self.safe_ts()
        for (table_id, _), _ in mutations.items():
            store = self.tables.get(table_id)
            if store is not None:
                store.maybe_compact(min(safe, commit_ts - 1) if safe else 0)
        return commit_ts

    SEQ_CACHE = 1000

    def sequence_next(self, seq) -> int:
        """Allocate the next value; persists the durable high-water a
        cache batch ahead (clamped at the exhaustion sentinel) so a
        CRASH never re-issues a handed-out non-cycle value; a clean
        checkpoint writes the exact cursor back, so clean restarts
        waste nothing (reference: ddl/sequence.go + meta autoid-style
        batching)."""
        with self._seq_lock:
            cur = self._seq_cursors.get(seq.id, seq.next_value)
            v = cur
            wrapped = False
            if v > seq.max_value or v < seq.min_value:
                if not seq.cycle:
                    raise ValueError(
                        f"sequence {seq.name} has run out")
                v = seq.start
                wrapped = True
            nxt = v + seq.increment
            self._seq_cursors[seq.id] = nxt
            if wrapped or (seq.increment > 0 and nxt > seq.next_value) \
                    or (seq.increment < 0 and nxt < seq.next_value):
                high = nxt + seq.increment * self.SEQ_CACHE
                if seq.increment > 0:
                    # never persist past "just exhausted": restart must
                    # still hand out the values below max_value
                    high = min(high, seq.max_value + seq.increment)
                else:
                    high = max(high, seq.min_value + seq.increment)
                seq.next_value = high
                self.persist_catalog()
            return v

    def sequence_set(self, seq, value: int) -> None:
        with self._seq_lock:
            self._seq_cursors[seq.id] = value + seq.increment
            seq.next_value = value + seq.increment * (self.SEQ_CACHE + 1)
            if seq.increment > 0:
                seq.next_value = min(seq.next_value,
                                     seq.max_value + seq.increment)
            self.persist_catalog()

    def _flush_sequence_cursors(self) -> None:
        """Write exact cursors into the catalog so a clean shutdown
        loses no sequence values (crash recovery falls back to the
        batched high-water)."""
        dirty = False
        with self._seq_lock:
            for schema in self.catalog.schemas.values():
                for seq in (getattr(schema, "sequences", {}) or {}
                            ).values():
                    cur = self._seq_cursors.get(seq.id)
                    if cur is not None and cur != seq.next_value:
                        seq.next_value = cur
                        dirty = True
        if dirty:
            self.persist_catalog()

    # ---- multi-process refresh (shared mode) ---------------------------
    def refresh(self) -> None:
        """Catch up with sibling processes sharing this directory: tail
        the WAL, fold their committed rows into our columnar epochs, and
        reload the catalog when the meta plane moved. The domain-reload
        loop of the reference (domain/domain.go:352) collapsed into an
        on-demand call — sessions invoke it per statement, and every
        mutation section refreshes implicitly (kv/mvcc._MutationSection)."""
        if not self.shared:
            return
        from .. import obs
        with obs.span("domain.refresh"):
            self.kv.refresh()
            self._drain_refresh()
        # sibling CREATE/DROP BINDING lands in the meta plane; drop the
        # cache so the next match reloads (bindinfo load loop analog)
        self.bindings.invalidate()

    def _drain_refresh(self) -> None:
        from ..kv.mvcc import (
            CF_DATA,
            CF_WRITE,
            OP_DEL,
            OP_PUT,
            _dkey,
            _split_vkey,
            _write_dec,
        )
        from ..kv import codec
        from .table_store import TOMBSTONE as TS

        eng = self.kv.kv
        pending = self.kv.drain_pending()
        if not pending:
            return
        catalog_moved = False
        meta_catalog = tablecodec.meta_key(b"catalog")
        with self._commit_lock, self._fold_section():
            for op, cf, key, val in pending:
                if cf != CF_WRITE or op != 1:
                    continue
                try:
                    ukey, commit_ts = _split_vkey(key)
                except Exception:
                    continue
                self.tso.observe(commit_ts)
                if ukey == meta_catalog:
                    catalog_moved = True
                    continue
                try:
                    table_id, handle = tablecodec.decode_record_key(ukey)
                except Exception:
                    continue  # non-row key (meta/stats/index planes)
                store = self.tables.get(table_id)
                if store is None:
                    continue
                start_ts, kind = _write_dec(val)
                if kind == OP_DEL:
                    store.apply_commit(commit_ts, handle, TS)
                elif kind == OP_PUT:
                    data = eng.get(CF_DATA, _dkey(ukey, start_ts))
                    if data is not None:
                        store.apply_commit(
                            commit_ts, handle,
                            self._fold_row(store, codec.decode_key(data)))
        if catalog_moved:
            self._reload_catalog()

    def _reload_catalog(self) -> None:
        """Adopt a sibling's schema change: rebuild the stores of tables
        whose definition moved (their schema_token changes, so in-flight
        local transactions abort at the fence — the reference's schema
        validator behavior, domain/schema_validator.go) and register new
        tables. Unchanged tables keep their stores and epochs."""
        import pickle

        raw = self.get_meta(b"catalog")
        if raw is None:
            return
        state = pickle.loads(raw)
        if state["version"] == self.catalog.version:
            return
        old_infos = {}
        for schema in self.catalog.schemas.values():
            for info in schema.tables.values():
                old_infos[info.id] = pickle.dumps(info)
        self.catalog.schemas = state["schemas"]
        self.catalog._next_id = max(self.catalog._next_id,
                                    state["next_id"])
        self.catalog.version = state["version"]
        for schema in self.catalog.schemas.values():
            for info in schema.tables.values():
                part = getattr(info, "partition", None)
                ids = [d.id for d in part.defs] if part is not None \
                    else [info.id]
                changed = pickle.dumps(info) != old_infos.get(info.id)
                if info.id in old_infos and not changed and \
                        all(tid in self.tables for tid in ids):
                    continue
                old_tokens = {tid: self.tables[tid].schema_token
                              for tid in ids if tid in self.tables}
                self.register_table(info)
                for tid in ids:
                    # a rebuilt store must present a NEW schema token so
                    # in-flight local transactions that buffered against
                    # the old layout abort at the commit fence
                    self.tables[tid].schema_token = \
                        old_tokens.get(tid, 0) + 1
                    self._refold_table(self.tables[tid])
        live = set()
        for schema in self.catalog.schemas.values():
            for info in schema.tables.values():
                part = getattr(info, "partition", None)
                live.update(d.id for d in part.defs) \
                    if part is not None else live.add(info.id)
        for tid in [t for t in self.tables if t not in live]:
            del self.tables[tid]

    def _refold_table(self, store: TableStore) -> None:
        """Rebuild a store's rows from the KV truth (epoch snapshot when
        current, committed deltas above its fold)."""
        self._load_epoch(store)
        lo, hi = tablecodec.record_range(store.table.id)
        folds = []
        for key, commit_ts, kind, val in self.kv.scan_latest(lo, hi):
            if commit_ts <= store.epoch.fold_ts:
                continue
            from ..kv import codec
            from .table_store import TOMBSTONE as TS
            _, handle = tablecodec.decode_record_key(key)
            if kind == b"D":
                folds.append((commit_ts, handle, TS))
            else:
                folds.append((commit_ts, handle, self._fold_row(
                    store, codec.decode_key(val))))
        for commit_ts, handle, row in folds:
            store.apply_commit(commit_ts, handle, row)
            store._next_handle = max(store._next_handle, handle + 1)

    def _check_schema_fence(self, txn: "Transaction") -> None:
        """Fail txns whose buffered rows target a superseded table layout
        (reference: schema validator, domain/schema_validator.go)."""
        for table_id, token in txn.schema_tokens.items():
            store = self.tables.get(table_id)
            if store is not None and store.schema_token != token:
                raise WriteConflictError(
                    "Information schema is changed during the execution "
                    "of the statement; try again",
                    errno=ER_SCHEMA_CHANGED)

    @contextmanager
    def _fold_section(self):
        """Marks a fold in flight for the snapshot seqlock. Must be
        entered while holding _commit_lock. Reentrant: the commit path
        nests _drain_refresh's section inside its own — only the
        outermost transition flips the seq, or the inner exit would
        advertise quiescence mid-fold and let a lock-free snapshot read
        a half-applied sibling commit."""
        if self._fold_depth == 0:
            self._fold_seq += 1  # odd: writer active
        self._fold_depth += 1
        try:
            yield
        finally:
            self._fold_depth -= 1
            if self._fold_depth == 0:
                self._fold_seq += 1  # even: quiescent

    # ---- meta KV (schema/stats persistence plane) ----------------------
    @contextmanager
    def ddl_section(self):
        """Critical section for direct catalog DDL (CREATE/DROP TABLE
        and friends). The whole-catalog persist is last-writer-wins, so
        {fold sibling catalog -> mutate -> persist} must be atomic
        against sibling DDL — otherwise two servers' concurrent CREATE
        TABLEs either conflict at the meta commit (9007 to the client)
        or silently drop one table. Gated on the DDL OWNER lock — the
        same lock ALTER-family jobs take in ddl.run_job — so the lock
        order everywhere is owner -> mutation/coordinator (taking the
        coordinator flock here instead would invert against background
        owners that hold owner-then-commit and deadlock)."""
        owner = getattr(self, "ddl_owner", None)
        if owner is None:
            yield
            return
        with owner:
            self.refresh()  # adopt sibling catalog inside the gate
            yield

    def put_meta(self, name: bytes, value: bytes) -> None:
        """Durable metadata write through the SAME percolator path as row
        data (reference: meta/meta.go over the m-prefix keyspace).

        Non-catalog keys are last-writer-wins snapshots, so a cross-
        process conflict (sibling wrote the same key between our ts
        allocation and prewrite) just retries with a fresh ts. The
        CATALOG key never blind-retries: its payload is a whole-catalog
        pickle built BEFORE the conflict, and replaying it would erase
        the sibling's DDL — catalog writers serialize via ddl_section()
        and any residual conflict must stay loud."""
        from ..kv.backoff import BO_META, Backoffer, BackoffExhausted

        key = tablecodec.meta_key(name)
        retriable = name != b"catalog"
        bo = Backoffer(budget_ms=2000)
        while True:
            # .ts() is the STRICT allocator interface: on a degraded
            # follower it raises typed instead of re-issuing a stale
            # timestamp that a WRITE would then carry
            start_ts = self.tso.ts()
            try:
                try:
                    with self._commit_lock:
                        self.committer.commit(
                            [Mutation(OP_PUT, key, value)], start_ts)
                finally:
                    self._tso_commit_done()
                # meta writes are acked durable like row commits: join
                # the group-fsync rendezvous outside the commit lock.
                # Same post-visibility typing as Storage.commit — not a
                # retryable conflict.
                try:
                    self.kv.commit_sync()
                except OSError as e:
                    raise KVError(
                        f"meta write on {name!r}: WAL fsync failed "
                        f"after the commit was applied ({e})"
                    ) from e
                return
            except KVWriteConflict:
                if not retriable:
                    raise
                if self.shared:
                    self.kv.refresh()
                try:
                    bo.sleep(BO_META)
                except BackoffExhausted as e:
                    raise WriteConflictError(
                        f"meta write on {name!r}: {e}") from None

    def get_meta(self, name: bytes) -> Optional[bytes]:
        from ..kv.twopc import Snapshot
        snap = Snapshot(self.rm, self.tso, self.tso.next_ts())
        return snap.get(tablecodec.meta_key(name))

    def _best_effort_rollback(self, kv_muts, start_ts: int) -> None:
        """Clear any prewrite locks a failed commit left behind (the lock
        resolver would also reclaim them by TTL — this is just prompt)."""
        try:
            self.committer.rollback(kv_muts, start_ts)
        except Exception:
            pass

    def flush(self) -> None:
        """Fold all committed deltas into base epochs (test/bench helper)."""
        safe = self.safe_ts()
        for store in self.tables.values():
            store.compact(safe)


class UserLocks:
    """Named advisory locks for GET_LOCK/RELEASE_LOCK (reference:
    builtin_miscellaneous.go lockFunc family). Reentrant per holder,
    released explicitly, en masse, or on connection close."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self._held: dict[str, tuple[Any, int]] = {}  # name -> (who, depth)

    def acquire(self, name: str, who, timeout_s: float) -> bool:
        import time as _t

        from ..util import interrupt
        infinite = timeout_s < 0  # MySQL: negative timeout waits forever
        deadline = _t.monotonic() + timeout_s
        with self._cv:
            while True:
                cur = self._held.get(name)
                if cur is None or cur[0] == who:
                    depth = cur[1] + 1 if cur else 1
                    self._held[name] = (who, depth)
                    return True
                interrupt.check()  # KILL QUERY cancels a blocked wait
                remain = 0.5 if infinite else deadline - _t.monotonic()
                if remain <= 0:
                    return False
                self._cv.wait(min(remain, 0.5))

    def release(self, name: str, who) -> Optional[int]:
        """1 released, 0 held by someone else, None not held (MySQL)."""
        with self._cv:
            cur = self._held.get(name)
            if cur is None:
                return None
            if cur[0] != who:
                return 0
            if cur[1] > 1:
                self._held[name] = (who, cur[1] - 1)
            else:
                del self._held[name]
                self._cv.notify_all()
            return 1

    def release_all(self, who) -> int:
        with self._cv:
            mine = [k for k, (w, _) in self._held.items() if w == who]
            n = sum(self._held[k][1] for k in mine)
            for k in mine:
                del self._held[k]
            if mine:
                self._cv.notify_all()
            return n

    def holder(self, name: str) -> Optional[Any]:
        with self._cv:
            cur = self._held.get(name)
            return cur[0] if cur else None


class Transaction:
    """A snapshot-isolation transaction; optimistic by default.

    Pessimistic mode (reference: session/txn pessimistic flag +
    store/tikv/pessimistic.go): DML acquires OP_LOCK guards at execution
    time via Storage.pessimistic_lock_keys, reads for DML happen at
    for_update_ts (latest), and commit converts the guards through the
    normal 2PC prewrite."""

    def __init__(self, storage: Storage, start_ts: int,
                 pessimistic: bool = False) -> None:
        self.storage = storage
        self.start_ts = start_ts
        self.memdb = MemDB()
        self._finished = False
        # table_id -> schema_token observed at first buffered write
        self.schema_tokens: dict[int, int] = {}
        self.pessimistic = pessimistic
        # set by Storage.begin: ts re-issued while the leader was
        # unreachable — transaction may read (stale) but never write
        self.degraded = False
        self.for_update_ts = start_ts
        self.pessimistic_primary: Optional[bytes] = None
        self.locked_keys: set[bytes] = set()
        # unique-index guard keys claimed by OPTIMISTIC DML: committed
        # as lock-only mutations so two concurrent claims of the same
        # unique value collide in 2PC prewrite (the index-KV write
        # conflict the reference gets for free from table/tables/index.go
        # entries; this engine's indexes are permutations with no KV row)
        self.guard_keys: set[bytes] = set()
        # per-statement read-ts override (FOR UPDATE / pessimistic DML
        # read latest; plain SELECT keeps the start_ts snapshot)
        self.stmt_read_ts: Optional[int] = None
        self._heartbeat_stop: Optional[threading.Event] = None

    def start_heartbeat(self) -> None:
        """TTL keepalive for the pessimistic primary lock (reference:
        2pc.go ttlManager goroutine -> TiKV TxnHeartBeat): without it an
        idle txn's locks expire after the initial TTL and contenders
        roll the txn back, failing its eventual COMMIT."""
        if self._heartbeat_stop is not None or \
                self.pessimistic_primary is None:
            return
        stop = threading.Event()
        self._heartbeat_stop = stop
        primary = self.pessimistic_primary
        start_physical = self.start_ts >> 18

        def beat() -> None:
            import time as _time
            while not stop.wait(5.0):
                elapsed_ms = int(_time.time() * 1000) - start_physical
                if not self.storage.kv.txn_heart_beat(
                        primary, self.start_ts, elapsed_ms + 20000):
                    return  # lock gone: resolved or finished
        threading.Thread(target=beat, name="titpu-txn-ttl",
                         daemon=True).start()

    def refresh_for_update_ts(self) -> int:
        """New for_update_ts for a (re)tried pessimistic statement
        (reference: session tells the txn to refresh forUpdateTS on
        each pessimistic DML, executor/adapter.go:533)."""
        self.for_update_ts = self.storage.tso.next_ts()
        return self.for_update_ts

    # ---- writes ------------------------------------------------------------
    def set_row(self, table_id: int, handle: int, row: tuple) -> None:
        self._note_schema(table_id)
        self.memdb.set((table_id, handle), row)

    def delete_row(self, table_id: int, handle: int) -> None:
        self._note_schema(table_id)
        self.memdb.set((table_id, handle), TOMBSTONE)

    def _note_schema(self, table_id: int) -> None:
        if table_id not in self.schema_tokens:
            store = self.storage.tables.get(table_id)
            if store is not None:
                self.schema_tokens[table_id] = store.schema_token

    # ---- reads -------------------------------------------------------------
    def snapshot(self, table_id: int) -> TableSnapshot:
        """Snapshot at start_ts (or the statement's read-ts override)
        unioned with our own uncommitted writes.

        Built under the storage commit lock: a sibling's commit releases
        its KV row locks in commit_phase but appends the columnar fold a
        moment later (both inside _commit_lock). A pessimistic lock-wait
        retry resumes the instant the KV lock clears and re-snapshots at
        a for_update_ts ABOVE that commit — without this fence it could
        read the pre-commit columnar state while its lock validation
        says the commit is covered, and overwrite it (lost update; found
        by tests/test_race_harness.py bank-transfer conservation). Any
        commit still unfolded once we hold the lock necessarily gets a
        commit_ts later than our read-ts (TSO order), so it is correctly
        invisible.

        Seqlock fast path: when no fold is in flight (_fold_seq even and
        unchanged across the build) the snapshot is lock-free, so
        concurrent readers never serialize on the commit lock; only a
        reader racing an active fold retries and then waits — that wait
        is the fence."""
        store = self.storage.table_store(table_id)
        overlay = {h: v for h, v in self.memdb.iter_table(table_id)}
        ts = self.stmt_read_ts if self.stmt_read_ts is not None \
            else self.start_ts
        for _ in range(4):
            seq = self.storage._fold_seq
            if seq & 1:
                break  # fold active: wait on the lock
            snap = store.snapshot(ts, overlay or None)
            if self.storage._fold_seq == seq:
                return snap
        with self.storage._commit_lock:
            return store.snapshot(ts, overlay or None)

    # ---- lifecycle ---------------------------------------------------------
    def commit(self) -> int:
        assert not self._finished, "transaction already finished"
        try:
            return self.storage.commit(self)
        finally:
            self._finish()

    def rollback(self) -> None:
        if not self._finished:
            if self.locked_keys:
                self.storage.kv.pessimistic_rollback(
                    sorted(self.locked_keys), self.start_ts)
            self._finish()

    def _finish(self) -> None:
        self._finished = True
        if self._heartbeat_stop is not None:
            self._heartbeat_stop.set()
            self._heartbeat_stop = None
        self.storage.release_snapshot_ts(self.start_ts)
