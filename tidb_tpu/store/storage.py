"""Storage: the transactional store — percolator KV truth + columnar cache.

Plays the role of the reference's `kv.Storage` + embedded unistore
(reference: kv/kv.go:462, store/mockstore/unistore.go). There is ONE
transaction path: commits run the percolator two-phase protocol through
the region tier (TwoPhaseCommitter over RegionManager over MVCCStore,
mirroring session/session.go:573 -> store/tikv/2pc.go:78), with the C++
ordered-KV engine as the substrate when available. Each table owns its
region (register_table splits at the table prefix, the create-table
split-region analog, ddl/split_region.go), so multi-table transactions
exercise region-grouped batches and RegionError retries for real.

The per-table column epochs (TableStore) are the COPROCESSOR-FACING fold
of the same committed data — applied under the commit lock immediately
after the percolator commit lands, the way TiFlash folds the raft log into
its delta tree. Snapshots read the columnar fold; the KV tier holds the
write-ahead truth (locks, write records, versioned values).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ..catalog.schema import Catalog, TableInfo
from ..kv import codec, tablecodec
from ..kv.memdb import MemDB, TOMBSTONE
from ..kv.mvcc import (
    KVError,
    MVCCStore,
    Mutation,
    OP_DEL,
    OP_PUT,
    WriteConflictError as KVWriteConflict,
)
from ..kv.region import RegionManager
from ..kv.tso import TimestampOracle
from ..kv.twopc import CommitError, TwoPhaseCommitter
from .table_store import TableSnapshot, TableStore


class WriteConflictError(Exception):
    """Another txn committed to a key after our start_ts (optimistic SI)."""


def _make_engine():
    """C++ ordered-KV engine when buildable, pure-python twin otherwise."""
    try:
        from ..kv.native import NativeOrderedKV, native_available
        if native_available():
            return NativeOrderedKV()
    except Exception:
        pass
    return None


class Storage:
    def __init__(self) -> None:
        from ..stats import StatsHandle

        self.catalog = Catalog()
        self.tso = TimestampOracle()
        self.stats = StatsHandle()
        self.tables: dict[int, TableStore] = {}
        # the transactional KV truth: percolator MVCC over regions
        self.kv = MVCCStore(engine=_make_engine())
        self.rm = RegionManager(self.kv)
        self.committer = TwoPhaseCommitter(self.rm, self.tso)
        # DDL job queue + history (the meta-KV DDLJobList analog,
        # reference meta/meta.go:571) — lives on storage so a replacement
        # worker resumes pending jobs with their reorg checkpoints
        self.ddl_jobs: list = []
        self.ddl_history: list = []
        self._commit_lock = threading.Lock()
        # active snapshot ts registry -> GC/compaction safepoint
        self._active_snapshots: dict[int, int] = {}
        self._snap_lock = threading.Lock()

    # ---- schema ------------------------------------------------------------
    def register_table(self, info: TableInfo) -> TableStore:
        store = TableStore(info)
        self.tables[info.id] = store
        # one region per table (reference: split-table-region on create,
        # ddl/split_region.go) — multi-table commits become multi-region
        try:
            self.rm.split(tablecodec.table_prefix(info.id))
        except ValueError:
            pass  # split point already a region boundary
        return store

    def unregister_table(self, table_id: int) -> None:
        self.tables.pop(table_id, None)

    def table_store(self, table_id: int) -> TableStore:
        return self.tables[table_id]

    # ---- snapshot registry (compaction safepoint) ---------------------------
    def acquire_snapshot_ts(self) -> int:
        ts = self.tso.next_ts()
        with self._snap_lock:
            self._active_snapshots[ts] = self._active_snapshots.get(ts, 0) + 1
        return ts

    def release_snapshot_ts(self, ts: int) -> None:
        with self._snap_lock:
            n = self._active_snapshots.get(ts, 0) - 1
            if n <= 0:
                self._active_snapshots.pop(ts, None)
            else:
                self._active_snapshots[ts] = n

    def safe_ts(self) -> int:
        """Newest ts that every active snapshot is at or above."""
        with self._snap_lock:
            if self._active_snapshots:
                return min(self._active_snapshots) - 1
        return self.tso.current()

    # ---- transactions ------------------------------------------------------
    def begin(self) -> "Transaction":
        return Transaction(self, self.acquire_snapshot_ts())

    def commit(self, txn: "Transaction") -> int:
        """THE commit path: schema fence -> percolator 2PC through the
        region tier -> columnar fold. One source of truth (the KV write
        records), one fold (the epochs the coprocessor reads)."""
        mutations = txn.memdb.mutations()
        if not mutations:
            return txn.start_ts
        kv_muts = []
        for (table_id, handle), row in mutations.items():
            key = tablecodec.record_key(table_id, handle)
            if row is TOMBSTONE:
                kv_muts.append(Mutation(OP_DEL, key))
            else:
                kv_muts.append(Mutation(OP_PUT, key,
                                        codec.encode_key(list(row))))
        with self._commit_lock:
            for table_id, token in txn.schema_tokens.items():
                store = self.tables.get(table_id)
                if store is not None and store.schema_token != token:
                    # rows were buffered against an older layout (reference:
                    # schema validator fails the txn, domain/schema_validator.go)
                    raise WriteConflictError(
                        "Information schema is changed during the execution "
                        "of the statement; try again")
            try:
                commit_ts = self.committer.commit(kv_muts, txn.start_ts)
            except KVWriteConflict as e:
                from .. import obs
                obs.CONFLICTS.inc()
                self._best_effort_rollback(kv_muts, txn.start_ts)
                raise WriteConflictError(str(e)) from None
            except (KVError, CommitError) as e:
                self._best_effort_rollback(kv_muts, txn.start_ts)
                raise WriteConflictError(f"commit failed: {e}") from None
            # columnar fold of the committed mutations (the coprocessor's
            # read view) — inside the lock so no snapshot can observe the
            # KV commit without the fold
            for (table_id, handle), row in mutations.items():
                store = self.tables.get(table_id)
                if store is not None:
                    store.apply_commit(commit_ts, handle, row)
        from .. import obs
        obs.COMMITS.inc()
        # opportunistic compaction at the GC-safe ts
        safe = self.safe_ts()
        for (table_id, _), _ in mutations.items():
            store = self.tables.get(table_id)
            if store is not None:
                store.maybe_compact(min(safe, commit_ts - 1) if safe else 0)
        return commit_ts

    # ---- meta KV (schema/stats persistence plane) ----------------------
    def put_meta(self, name: bytes, value: bytes) -> None:
        """Durable metadata write through the SAME percolator path as row
        data (reference: meta/meta.go over the m-prefix keyspace)."""
        key = tablecodec.meta_key(name)
        start_ts = self.tso.next_ts()
        with self._commit_lock:
            self.committer.commit([Mutation(OP_PUT, key, value)], start_ts)

    def get_meta(self, name: bytes) -> Optional[bytes]:
        from ..kv.twopc import Snapshot
        snap = Snapshot(self.rm, self.tso, self.tso.next_ts())
        return snap.get(tablecodec.meta_key(name))

    def _best_effort_rollback(self, kv_muts, start_ts: int) -> None:
        """Clear any prewrite locks a failed commit left behind (the lock
        resolver would also reclaim them by TTL — this is just prompt)."""
        try:
            self.committer.rollback(kv_muts, start_ts)
        except Exception:
            pass

    def flush(self) -> None:
        """Fold all committed deltas into base epochs (test/bench helper)."""
        safe = self.safe_ts()
        for store in self.tables.values():
            store.compact(safe)


class Transaction:
    """An optimistic snapshot-isolation transaction."""

    def __init__(self, storage: Storage, start_ts: int) -> None:
        self.storage = storage
        self.start_ts = start_ts
        self.memdb = MemDB()
        self._finished = False
        # table_id -> schema_token observed at first buffered write
        self.schema_tokens: dict[int, int] = {}

    # ---- writes ------------------------------------------------------------
    def set_row(self, table_id: int, handle: int, row: tuple) -> None:
        self._note_schema(table_id)
        self.memdb.set((table_id, handle), row)

    def delete_row(self, table_id: int, handle: int) -> None:
        self._note_schema(table_id)
        self.memdb.set((table_id, handle), TOMBSTONE)

    def _note_schema(self, table_id: int) -> None:
        if table_id not in self.schema_tokens:
            store = self.storage.tables.get(table_id)
            if store is not None:
                self.schema_tokens[table_id] = store.schema_token

    # ---- reads -------------------------------------------------------------
    def snapshot(self, table_id: int) -> TableSnapshot:
        """Snapshot at start_ts unioned with our own uncommitted writes."""
        store = self.storage.table_store(table_id)
        overlay = {h: v for h, v in self.memdb.iter_table(table_id)}
        return store.snapshot(self.start_ts, overlay or None)

    # ---- lifecycle ---------------------------------------------------------
    def commit(self) -> int:
        assert not self._finished, "transaction already finished"
        try:
            return self.storage.commit(self)
        finally:
            self._finish()

    def rollback(self) -> None:
        if not self._finished:
            self._finish()

    def _finish(self) -> None:
        self._finished = True
        self.storage.release_snapshot_ts(self.start_ts)
