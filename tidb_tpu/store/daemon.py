"""Background maintenance: GC, lock-TTL resolution, auto-analyze, checkpoints.

Counterpart of the reference's background loops: the GC worker
(reference: store/tikv/gcworker/gc_worker.go:95 leader-elected tick,
:241 resolve-locks-then-GC ordering), lock TTL expiry via the resolver
(store/tikv/lock_resolver.go), auto-analyze (statistics/handle/
update.go:860), and periodic engine checkpointing.

The worker is tick-driven so tests call `tick()` deterministically;
`start()` wraps it in a daemon thread for servers. The GC safepoint is
`min(now - gc_life, oldest active snapshot)` — active snapshots are
registered on Storage (storage.py safe_ts), which is exactly the
safepoint-vs-active-txn protection the reference gets from PD's
safepoint service + the MinStartTS reports.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Optional

from ..util import failpoint


def parse_duration(s: str, default_s: float = 600.0) -> float:
    """'10m', '1h30m', '45s', '500ms' -> seconds (Go duration subset,
    the format tidb_gc_life_time uses)."""
    if not s:
        return default_s
    s = str(s).strip()
    try:
        return float(s)  # bare number = seconds
    except ValueError:
        pass
    total = 0.0
    found = False
    for num, unit in re.findall(r"([0-9.]+)(ms|s|m|h|d)", s):
        total += float(num) * {"ms": 1e-3, "s": 1, "m": 60, "h": 3600,
                               "d": 86400}[unit]
        found = True
    return total if found else default_s


class MaintenanceWorker:
    """One tick = resolve expired locks -> GC at the safepoint ->
    compact + checkpoint -> auto-analyze. Owned by a Storage."""

    def __init__(self, storage, catalog=None) -> None:
        self.storage = storage
        self.catalog = catalog
        self.last_safepoint = 0
        self.gc_removed_total = 0
        self.locks_resolved_total = 0
        self.auto_analyzed: list[str] = []
        # auto-analyze cadence floor (performance.stats-lease seeds
        # it; 0 = analyze on every tick, the embedded/test default)
        self.stats_lease_s = 0.0
        self._last_analyze = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- components (also individually test-callable) -----------------
    def resolve_expired_locks(self) -> int:
        """Roll expired orphan locks forward/back from the primary's fate
        (reference: gc_worker.go:241 resolveLocks phase before DoGC —
        GC must not run under locks older than the safepoint)."""
        from ..kv.twopc import LockResolver

        resolver = LockResolver(self.storage.rm, self.storage.tso)
        n = 0
        now = self.storage.tso.next_ts()
        for lock in self.storage.kv.all_locks():
            expired = now - lock.start_ts > (lock.ttl << 18)
            if not expired:
                continue
            try:
                if resolver.resolve(lock):
                    n += 1
            except Exception:
                continue  # lock owner raced us; next tick sweeps again
        self.locks_resolved_total += n
        return n

    def _duration_var(self, name: str, default: str) -> float:
        v = self.storage.sysvars.get_global(name)
        return parse_duration(default if v is None else str(v))

    def gc_safepoint(self) -> int:
        """min(now - tidb_gc_life_time, oldest active snapshot)."""
        life_s = self._duration_var("tidb_gc_life_time", "10m")
        horizon = self.storage.tso.current() - (int(life_s * 1000) << 18)
        return max(0, min(horizon, self.storage.safe_ts()))

    def run_gc(self) -> int:
        """MVCC version GC + columnar compaction at the safepoint
        (reference: gc_worker.go DoGC). Never moves backwards."""
        sp = self.gc_safepoint()
        if sp <= self.last_safepoint:
            return 0
        failpoint.inject("daemon/before-gc")
        removed = self.storage.kv.gc(sp)
        for store in list(self.storage.tables.values()):  # DDL may race
            store.maybe_compact(sp)
        self.last_safepoint = sp
        self.gc_removed_total += removed
        return removed

    def run_auto_analyze(self) -> list[str]:
        if self.catalog is None:
            return []
        if self.stats_lease_s > 0:
            now = time.monotonic()
            if now - self._last_analyze < self.stats_lease_s:
                return []
            self._last_analyze = now
        names = self.storage.stats.auto_analyze(self.storage, self.catalog)
        self.auto_analyzed.extend(names)
        return names

    def run_checkpoint(self) -> None:
        """Persist dirty epochs + fold the KV WAL (durable stores only).
        The WAL folds unconditionally: meta-plane writes (sysvars, stats,
        DDL jobs) dirty no epoch but still grow it, and crash recovery
        replays whatever is left unfolded."""
        self.storage.checkpoint(dirty_only=True)

    def tick(self) -> dict:
        # GC runs on the elected owner only (reference: the GC worker is
        # leader-elected, gc_worker.go:95); lock resolution,
        # auto-analyze and checkpointing of THIS process's dirty state
        # are per-process work and never skip
        owner = getattr(self.storage, "gc_owner", None)
        locks = self.resolve_expired_locks()
        removed = 0
        if owner is None or owner.try_campaign():
            try:
                removed = self.run_gc()
            finally:
                if owner is not None:
                    owner.resign()
        analyzed = self.run_auto_analyze()
        self.run_checkpoint()
        return {"locks_resolved": locks, "gc_removed": removed,
                "auto_analyzed": analyzed}

    # ---- thread lifecycle ----------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> None:
        """interval_s=None re-reads tidb_gc_run_interval every cycle, so
        SET GLOBAL takes effect without a restart (reference: gc_worker
        re-reads its interval each tick)."""
        if self._thread is not None:
            return

        def interval() -> float:
            if interval_s is not None:
                return interval_s
            return max(1.0, self._duration_var("tidb_gc_run_interval",
                                               "10m"))

        def loop() -> None:
            while not self._stop.wait(interval()):
                try:
                    self.tick()
                except Exception:
                    # a wounded maintenance pass must not kill the loop
                    # (reference: gc_worker logs and continues)
                    pass

        self._stop.clear()
        self._thread = threading.Thread(target=loop, name="titpu-maint",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None


__all__ = ["MaintenanceWorker", "parse_duration"]
