"""Shared-directory multi-process coordination.

The reference runs many stateless tidb-servers against a shared TiKV
cluster: schema changes propagate by lease (reference:
domain/domain.go:352 Reload loop, ddl/util/syncer.go schema-version
etcd watch), transactions from a server holding a superseded schema
abort at commit (domain/schema_validator.go), and a connection on one
server can be killed from another (server/server.go:548 Kill +
tests/globalkilltest, 32-bit conn ids carrying the server id).

This framework's storage is an embedded percolator KV over a durable
directory, so the multi-server shape is N processes sharing that
directory:

* one shared WAL, appended under an flock'd critical section (the
  percolator lock/write RECORDS carry the concurrency safety; the flock
  only serializes file appends and conflict checks against a fresh
  view);
* every process tails the WAL (`refresh`) before statements and inside
  every mutation section, folding other processes' commits into its own
  columnar epochs and reloading the catalog when the meta plane moved —
  the domain-reload equivalent, with the schema fence aborting stale
  in-flight transactions exactly like the reference's schema validator;
* timestamps come from ONE shared allocator (`kv/tso.py SharedTSO`:
  mmap'd counter + flock + fsync'd allocation window — the PD TSO role,
  reference oracle/oracles/pd.go:77), so snapshot isolation is STRICT
  across processes: any sibling commit_ts is below every later snapshot
  ts, and a refresh can never surface a commit inside an open
  transaction (the round-4 node-sliced TSO admitted a same-millisecond
  anomaly here; tests/test_multiproc.py::test_strict_si_same_millisecond
  pins the fix);
* a `procs/` registry + `kill/` mailbox implement cross-process KILL:
  global connection ids embed the server id (reference's
  globalconn.GCID layout), and each server's daemon polls its mailbox.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
import time
from typing import Optional

# size of the procs/ node-slot table (node ids feed global connection
# ids and the kill mailbox; timestamps come from the ONE SharedTSO
# allocator in kv/tso.py, not from per-node slicing)
TSO_NODE_SLICES = 32


class SharedDirCoordinator:
    """flock'd mutation sections + process/kill registry for N processes
    sharing one durable store directory."""

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.join(path, "procs"), exist_ok=True)
        os.makedirs(os.path.join(path, "kill"), exist_ok=True)
        self._lock_file = open(os.path.join(path, "store.lock"), "a+b")
        self._tlock = threading.RLock()  # in-process serialization
        self._depth = 0
        self.node_id = self._claim_node_id()

    # ---- node identity ----------------------------------------------------
    def _claim_node_id(self) -> int:
        """Smallest free slot in procs/ (flock'd probe): the slot file
        stays flock'd by this process for its lifetime, so a crashed
        process frees its slot automatically."""
        self._slots = []
        for nid in range(TSO_NODE_SLICES):
            f = open(os.path.join(self.path, "procs", f"node{nid}.lock"),
                     "a+b")
            try:
                fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                f.close()
                continue
            self._slots.append(f)  # hold for process lifetime
            return nid
        raise RuntimeError("no free node slots in shared store dir")

    def register_server(self, port: int, status_port: Optional[int]
                        ) -> None:
        info = {"pid": os.getpid(), "port": port,
                "status_port": status_port, "started": time.time()}
        p = os.path.join(self.path, "procs", f"node{self.node_id}.json")
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, p)

    def servers(self) -> dict[int, dict]:
        out = {}
        for name in os.listdir(os.path.join(self.path, "procs")):
            if not (name.startswith("node") and name.endswith(".json")):
                continue
            nid = int(name[4:-5])
            try:
                with open(os.path.join(self.path, "procs", name)) as f:
                    out[nid] = json.load(f)
            except (OSError, ValueError):
                continue
        return out

    # ---- mutation critical section ---------------------------------------
    def acquire(self) -> None:
        self._tlock.acquire()
        self._depth += 1
        if self._depth == 1:
            fcntl.flock(self._lock_file, fcntl.LOCK_EX)

    def release(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            fcntl.flock(self._lock_file, fcntl.LOCK_UN)
        self._tlock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # ---- cross-process kill mailbox ---------------------------------------
    @staticmethod
    def global_conn_id(node_id: int, local_id: int) -> int:
        """serverID:local layout of the reference's global connection ids
        (reference: tests/globalkilltest, util/globalconn)."""
        return (node_id << 24) | (local_id & 0xFFFFFF)

    @staticmethod
    def split_conn_id(conn_id: int) -> tuple[int, int]:
        return conn_id >> 24, conn_id & 0xFFFFFF

    def post_kill(self, conn_id: int, query_only: bool) -> None:
        nid, local = self.split_conn_id(conn_id)
        name = f"{nid}_{local}_{'q' if query_only else 'c'}_{time.time()}"
        p = os.path.join(self.path, "kill", name)
        with open(p + ".tmp", "w") as f:
            f.write(str(conn_id))
        os.replace(p + ".tmp", p)

    def poll_kills(self, node_id: Optional[int] = None
                   ) -> list[tuple[int, bool]]:
        """(local_conn_id, query_only) requests addressed to `node_id`
        (default: this node); consumed on read. The RPC tier polls on
        behalf of socket followers, so the target node is a parameter."""
        target = self.node_id if node_id is None else node_id
        out = []
        d = os.path.join(self.path, "kill")
        for name in os.listdir(d):
            parts = name.split("_")
            if len(parts) < 3 or name.endswith(".tmp"):
                continue
            try:
                nid, local = int(parts[0]), int(parts[1])
            except ValueError:
                continue
            if nid != target:
                continue
            out.append((local, parts[2] == "q"))
            try:
                os.unlink(os.path.join(d, name))
            except OSError:
                pass
        return out
