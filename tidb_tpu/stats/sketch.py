"""Count-Min and Flajolet-Martin sketches, built vectorized.

Counterparts of the reference's statistics/cmsketch.go (CM sketch with an
exact TopN carve-out) and statistics/fmsketch.go (FM sketch for NDV). The
reference builds these row-at-a-time while scanning samples; here the whole
column is already a flat array, so builds are numpy reductions (np.unique /
np.add.at) — the same shape a jnp/segment_sum device build would take, and
trivially portable there when ANALYZE pushdown moves on-device (SURVEY.md
§2.3 P13).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# splitmix64 constants — cheap vectorized 64-bit mixing
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_SHIFT = np.uint64(30)
_SHIFT2 = np.uint64(27)
_SHIFT3 = np.uint64(31)


def hash64(values: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over an int64/uint64 array."""
    with np.errstate(over="ignore"):
        x = values.astype(np.uint64, copy=True)
        x ^= x >> _SHIFT
        x *= _M1
        x ^= x >> _SHIFT2
        x *= _M2
        x ^= x >> _SHIFT3
    return x


def hash_any(values: np.ndarray) -> np.ndarray:
    """Hash a column's physical values to uint64 (floats via bit pattern)."""
    if np.issubdtype(values.dtype, np.floating):
        v = values.astype(np.float64).view(np.uint64)
    else:
        v = values.astype(np.int64).view(np.uint64)
    return hash64(v)


class CMSketch:
    """Count-Min sketch with exact TopN (reference: statistics/cmsketch.go).

    Point-frequency estimation for equality predicates. The TopN (most
    frequent values) is stored exactly and subtracted from the sketch,
    which keeps heavy hitters from inflating everything else's estimate.
    """

    DEPTH = 5
    WIDTH = 2048
    TOPN = 20

    def __init__(self) -> None:
        self.table = np.zeros((self.DEPTH, self.WIDTH), dtype=np.int64)
        self.topn: dict[int, int] = {}  # raw value -> exact count
        self.default = 0  # estimate for values never seen

    @classmethod
    def build(cls, values: np.ndarray, scale: float = 1.0) -> "CMSketch":
        """values: non-null physical column (ints/floats). scale: inverse
        sampling rate to extrapolate counts."""
        sk = cls()
        if len(values) == 0:
            return sk
        uniq, counts = np.unique(values, return_counts=True)
        if len(uniq) > cls.TOPN:
            kth = np.argpartition(counts, -cls.TOPN)[-cls.TOPN:]
            # only counts clearly above average qualify as heavy hitters
            avg = len(values) / len(uniq)
            top_idx = kth[counts[kth] > 2 * avg]
        else:
            top_idx = np.arange(len(uniq))
        top_mask = np.zeros(len(uniq), dtype=bool)
        top_mask[top_idx] = True
        for i in top_idx:
            # .item(): exact python int/float key (floats must NOT be
            # truncated — distinct heavy hitters would collide)
            sk.topn[uniq[i].item()] = int(round(counts[i] * scale))
        rest_u, rest_c = uniq[~top_mask], counts[~top_mask]
        if len(rest_u):
            h = hash_any(rest_u)
            scaled = np.round(rest_c * scale).astype(np.int64)
            for d in range(cls.DEPTH):
                idx = ((h >> np.uint64((d + 1) * 12)) ^ h) % np.uint64(cls.WIDTH)
                np.add.at(sk.table[d], idx.astype(np.int64), scaled)
            sk.default = max(1, int(round(float(rest_c.mean()) * scale / 2)))
        return sk

    def query(self, value) -> int:
        if hasattr(value, "item"):
            value = value.item()  # numpy scalar -> python
        if value in self.topn:
            return self.topn[value]
        arr = np.array([value])
        h = hash_any(arr)
        est = None
        for d in range(self.DEPTH):
            idx = int(((h >> np.uint64((d + 1) * 12)) ^ h)[0]
                      % np.uint64(self.WIDTH))
            c = int(self.table[d][idx])
            est = c if est is None else min(est, c)
        return est if est and est > 0 else self.default


class FMSketch:
    """Flajolet-Martin NDV sketch (reference: statistics/fmsketch.go).

    The reference keeps a bounded hash set with a doubling mask; the
    vectorized equivalent: find the smallest k such that the count of
    distinct hashes divisible by 2^k fits the bound, then NDV ~= count<<k.
    """

    MAX_SIZE = 10000

    def __init__(self, ndv: int) -> None:
        self.ndv = ndv

    @classmethod
    def build(cls, values: np.ndarray) -> "FMSketch":
        """NDV of the given values (sample extrapolation is the caller's
        job — see StatsHandle.build_table's GEE-style scale-up)."""
        if len(values) == 0:
            return cls(0)
        h = np.unique(hash_any(np.unique(values)))
        k = 0
        while len(h) > cls.MAX_SIZE:
            k += 1
            h = h[(h & np.uint64((1 << k) - 1)) == 0]
        return cls(int(len(h) << k))
