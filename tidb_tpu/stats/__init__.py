"""Statistics subsystem: sketches, histograms, stats handle, selectivity.

Counterpart of the reference's statistics/ package (SURVEY.md §2:
histograms, CMSketch, FMSketch, selectivity, delta-driven auto-analyze).
"""

from .handle import (  # noqa: F401
    ColumnStats,
    PSEUDO_EQ_RATE,
    PSEUDO_RANGE_RATE,
    StatsHandle,
    TableStats,
)
from .histogram import Histogram  # noqa: F401
from .sketch import CMSketch, FMSketch  # noqa: F401
