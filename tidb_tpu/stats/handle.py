"""Stats handle: per-table statistics registry + cardinality estimation.

Counterpart of the reference's statistics/handle (handle.go load/save,
update.go delta-driven auto-analyze) and selectivity.go estimation entry.
Single-process: stats live in memory keyed by table id; the delta feed is
the TableStore's modify counter (the reference accumulates per-session
deltas into mysql.stats_meta).

Estimation hierarchy per predicate, mirroring the reference's order:
exact TopN -> CM sketch point query (eq) / histogram interpolation
(ranges) -> pseudo rates when stats are missing (the reference's
PseudoTable path, statistics/table.go).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..catalog.schema import TableInfo
from .histogram import Histogram
from .sketch import CMSketch, FMSketch

# pseudo rates for columns without stats (reference: statistics/table.go
# pseudoEqualRate / pseudoLessRate)
PSEUDO_EQ_RATE = 1.0 / 1000
PSEUDO_RANGE_RATE = 1.0 / 3
SAMPLE_CAP = 1 << 20  # build from at most ~1M rows, extrapolated




@dataclass
class ColumnStats:
    null_count: float
    ndv: int
    histogram: Optional[Histogram]  # numeric/temporal only
    cmsketch: Optional[CMSketch]
    total: float  # non-null rows (scaled)
    # string columns: the table's append-only dictionary (codes are stable
    # across epochs) — planner predicates carry raw strings, the sketch is
    # keyed on codes
    dictionary: Any = None
    # observed per-value row counts from actual executions, overriding
    # the sketch estimate (reference: feedback.go point feedback)
    eq_feedback: dict = field(default_factory=dict)

    MAX_EQ_FEEDBACK = 128

    def eq_rows(self, value) -> float:
        if value is None:
            return self.null_count
        if isinstance(value, str):
            if self.dictionary is None:
                return self.total / self.ndv if self.ndv else 0.0
            code = self.dictionary.lookup(value)
            if code < 0:
                return 0.0
            value = code
        fb = self.eq_feedback.get(_fb_key(value))
        if fb is not None:
            return fb
        if self.cmsketch is not None:
            return float(self.cmsketch.query(value))
        if self.ndv > 0:
            return self.total / self.ndv
        return 0.0

    def note_eq_feedback(self, value, actual: float) -> None:
        if value is None:
            return
        if isinstance(value, str):
            # key on the dictionary code, exactly as eq_rows looks up —
            # raw-string keys would never be hit and numeric-looking
            # strings would collide with codes
            if self.dictionary is None:
                return
            code = self.dictionary.lookup(value)
            if code < 0:
                return
            value = code
        key = _fb_key(value)
        if key not in self.eq_feedback and \
                len(self.eq_feedback) >= self.MAX_EQ_FEEDBACK:
            self.eq_feedback.pop(next(iter(self.eq_feedback)))
        self.eq_feedback[key] = float(actual)

    def range_rows(self, lo, hi, lo_incl: bool, hi_incl: bool) -> float:
        if self.histogram is None:
            return self.total * PSEUDO_RANGE_RATE
        return self.histogram.range_count(lo, hi, lo_incl, hi_incl)


def _fb_key(value):
    try:
        return float(value)
    except (TypeError, ValueError):
        return value


@dataclass
class TableStats:
    table_id: int
    row_count: float
    columns: dict[int, ColumnStats]  # keyed by column offset
    version: int = 0
    built_at: float = field(default_factory=time.time)


class StatsHandle:
    """All tables' stats + auto-analyze bookkeeping."""

    AUTO_ANALYZE_RATIO = 0.5  # reference: tidb_auto_analyze_ratio default

    def __init__(self) -> None:
        self.tables: dict[int, TableStats] = {}
        # bumped whenever stats materially change (ANALYZE/load/drop);
        # plan-cache entries key on it for invalidation
        self.generation = 0
        # modify counts at last ANALYZE, per table id
        self._analyzed_at_modify: dict[int, int] = {}
        # (table_id, condition digest) -> observed row count from actual
        # executions (reference: statistics/feedback.go — scan-count
        # feedback correcting the histogram-based estimate)
        self.feedback: dict[tuple[int, str], float] = {}

    # ---- build ------------------------------------------------------------
    # full-column device reductions replace the host scans above this
    # many rows (ANALYZE pushdown; copr/analyze.py)
    DEVICE_ANALYZE_MIN = 2_000_000

    def build_table(self, info: TableInfo, snap, cop=None) -> TableStats:
        """ANALYZE: build stats from a snapshot's visible rows
        (reference: executor/analyze.go over pushdown sample collectors).
        With a coprocessor client and a big table, the full-column pass
        (counts, min/max, NDV) runs as device reduction kernels over the
        query path's tiles; histograms/CM build from a host sample."""
        n = snap.num_visible_rows
        rng = np.random.default_rng(info.id)
        dev_stats = {}
        if cop is not None and n >= self.DEVICE_ANALYZE_MIN and \
                len(snap.overlay_handles) == 0:
            try:
                from ..copr.analyze import device_column_stats
                dev_stats = device_column_stats(
                    cop, snap, list(range(info.num_columns)))
            except Exception:
                dev_stats = {}  # any device issue -> host path
        cols: dict[int, ColumnStats] = {}
        for off in range(info.num_columns):
            col = snap.column(off)
            data, valid = col.data, col.validity
            nn = data[valid] if valid is not None else data
            scale = 1.0
            if len(nn) > SAMPLE_CAP:
                scale = len(nn) / SAMPLE_CAP
                nn = rng.choice(nn, SAMPLE_CAP, replace=False)
            null_count = float(n - (len(nn) * scale))
            ft = info.columns[off].ftype
            hist = None
            if not ft.is_string and len(nn):
                hist = Histogram.build(nn, scale)
            cm = CMSketch.build(nn, scale) if len(nn) else None
            if off in dev_stats:
                nonnull, _mn, _mx, ndv = dev_stats[off]
                null_count = float(n - nonnull)
            elif scale == 1.0:
                ndv = (int(len(np.unique(nn))) if len(nn) <= FMSketch.MAX_SIZE
                       * 16 else FMSketch.build(nn).ndv)
            else:
                # GEE-style scale-up: values seen once in the sample predict
                # the unseen mass (reference samples feed fmsketch merges,
                # statistics/builder.go)
                u, c = np.unique(nn, return_counts=True)
                f1 = int((c == 1).sum())
                ndv = min(int(len(u) + (scale - 1.0) * f1),
                          int(len(nn) * scale))
            cols[off] = ColumnStats(
                null_count, ndv, hist, cm, float(len(nn)) * scale,
                dictionary=snap.dictionaries[off] if ft.is_string else None)
        ts = TableStats(info.id, float(n), cols,
                        version=self.tables.get(info.id).version + 1
                        if info.id in self.tables else 1)
        self.tables[info.id] = ts
        return ts

    def analyze_one(self, info: TableInfo, store, storage,
                    cop=None) -> TableStats:
        """Analyze one table from a fresh snapshot and record the modify
        watermark — shared by ANALYZE TABLE and auto-analyze."""
        txn = storage.begin()
        try:
            ts = self.build_table(info, txn.snapshot(info.id), cop=cop)
            self.generation += 1  # invalidates cached plans (cache key)
            self._analyzed_at_modify[info.id] = store.modify_count
            # fresh stats supersede stale observation feedback
            self.clear_feedback(info.id)
            try:
                self.save_to_kv(storage, info.id)
            except Exception:
                pass  # persistence is best-effort; memory stats serve
            return ts
        finally:
            txn.rollback()

    # ---- persistence (reference: statistics/handle/handle.go saves to
    # mysql.stats_* tables; here the meta-KV plane) ----------------------
    def save_to_kv(self, storage, table_id: int) -> None:
        import pickle

        ts = self.tables.get(table_id)
        if ts is None:
            return
        payload = (ts, self._analyzed_at_modify.get(table_id, 0))
        storage.put_meta(b"stats:%d" % table_id, pickle.dumps(payload))

    def load_from_kv(self, storage, catalog) -> int:
        """Restore persisted stats for every known table; returns count.
        The analog of the stats handle's boot-time load
        (statistics/handle/bootstrap.go)."""
        import pickle

        n = 0
        for schema in catalog.schemas.values():
            for info in schema.tables.values():
                raw = storage.get_meta(b"stats:%d" % info.id)
                if raw is not None:
                    ts, watermark = pickle.loads(raw)
                    self.tables[info.id] = ts
                    # restore the analyze watermark too, else auto-analyze
                    # immediately rebuilds what the reload just restored
                    self._analyzed_at_modify[info.id] = watermark
                    n += 1
        return n

    # ---- execution feedback --------------------------------------------
    FEEDBACK_CAP = 4096  # distinct conjunct sets retained (process-wide)

    def record_condition_feedback(self, table_id: int,
                                  col_offsets: list[int],
                                  conditions, actual: float) -> None:
        """Merge an actual scan count back into column-level stats when
        the conjunct set is attributable to one column: a single
        equality updates the point-feedback table, an interval rescales
        the histogram buckets (reference: statistics/feedback.go +
        handle/update.go:551 merging range feedback)."""
        ts = self.tables.get(table_id)
        if ts is None:
            return
        from ..plan.expr import Call
        from ..plan.physical import _expr_cols
        from ..plan.ranger import _eq_values, extract_interval

        col_map = {i: off for i, off in enumerate(col_offsets)}
        if len(conditions) == 1:
            hit = _eq_values(conditions[0], col_map)
            if hit is not None and len(hit[1]) == 1:
                cs = ts.columns.get(hit[0])
                if cs is not None:
                    cs.note_eq_feedback(hit[1][0], actual)
                return
        # interval feedback is sound only when EVERY conjunct bounds the
        # same column (extra predicates would shrink `actual` and the
        # correction would wrongly deflate the histogram)
        offs: set[int] = set()
        for c in conditions:
            cols: set[int] = set()
            _expr_cols(c, cols)
            if not (isinstance(c, Call)
                    and c.op in ("lt", "le", "gt", "ge")):
                return
            offs.update(col_map.get(i, -1) for i in cols)
        if len(offs) != 1 or -1 in offs:
            return
        off = next(iter(offs))
        cs = ts.columns.get(off)
        if cs is None or cs.histogram is None:
            return
        interval = extract_interval(off, conditions, col_map)
        if interval is None:
            return
        lo, hi, lo_incl, hi_incl = interval
        cs.histogram.apply_range_feedback(lo, hi, lo_incl, hi_incl,
                                          actual)

    def record_feedback(self, table_id: int, digest: str,
                        actual_rows: float) -> None:
        if len(self.feedback) >= self.FEEDBACK_CAP:
            # drop the oldest observation (insertion-ordered dict)
            self.feedback.pop(next(iter(self.feedback)))
        self.feedback[(table_id, digest)] = actual_rows

    def feedback_rows(self, table_id: int, digest: str):
        return self.feedback.get((table_id, digest))

    def clear_feedback(self, table_id: int) -> None:
        for k in [k for k in self.feedback if k[0] == table_id]:
            del self.feedback[k]

    def drop_table(self, table_id: int) -> None:
        self.generation += 1
        self.clear_feedback(table_id)
        self.tables.pop(table_id, None)
        self._analyzed_at_modify.pop(table_id, None)

    # ---- estimation -------------------------------------------------------
    def table_stats(self, table_id: int) -> Optional[TableStats]:
        return self.tables.get(table_id)

    def est_eq_rows(self, table_id: int, offset: int, value,
                    fallback_rows: float) -> float:
        ts = self.tables.get(table_id)
        if ts is None or offset not in ts.columns:
            return fallback_rows * PSEUDO_EQ_RATE
        return ts.columns[offset].eq_rows(value)

    def est_range_rows(self, table_id: int, offset: int, lo, hi,
                       lo_incl: bool, hi_incl: bool,
                       fallback_rows: float) -> float:
        ts = self.tables.get(table_id)
        if ts is None or offset not in ts.columns:
            return fallback_rows * PSEUDO_RANGE_RATE
        return ts.columns[offset].range_rows(lo, hi, lo_incl, hi_incl)

    # ---- auto analyze -----------------------------------------------------
    def needs_auto_analyze(self, info: TableInfo, store,
                           ratio: Optional[float] = None) -> bool:
        """Delta-driven trigger (reference: handle/update.go:860
        HandleAutoAnalyze, ratio of modify count to row count)."""
        if ratio is None:
            ratio = self.AUTO_ANALYZE_RATIO
        modified = store.modify_count
        ts = self.tables.get(info.id)
        if ts is None:
            return modified > 0
        done = self._analyzed_at_modify.get(info.id, 0)
        delta = modified - done
        return delta > max(ts.row_count, 1) * ratio and delta >= 64

    def auto_analyze(self, storage, catalog) -> list[str]:
        """Run pending auto-analyzes; returns analyzed table names.
        The trigger ratio honors SET GLOBAL tidb_auto_analyze_ratio."""
        try:
            ratio = float(storage.sysvars.get_global(
                "tidb_auto_analyze_ratio"))
        except (TypeError, ValueError):
            ratio = self.AUTO_ANALYZE_RATIO
        out = []
        for schema in list(catalog.schemas.values()):
            for info in list(schema.tables.values()):
                part = getattr(info, "partition", None)
                if part is not None:
                    targets = [(storage.child_table_info(info, d), d.id)
                               for d in part.defs]
                else:
                    targets = [(info, info.id)]
                for tinfo, tid in targets:
                    try:
                        store = storage.table_store(tid)
                    except KeyError:
                        continue
                    if not self.needs_auto_analyze(tinfo, store, ratio):
                        continue
                    self.analyze_one(tinfo, store, storage)
                    out.append(info.name)
        return out
