"""Equal-depth histograms for range selectivity.

Counterpart of the reference's statistics/histogram.go: buckets hold
(lower, upper, cumulative count, repeats-of-upper); estimation walks
buckets with linear interpolation inside the boundary buckets. Built from
a (possibly sampled) sorted column in one vectorized pass.

Only numeric/temporal physical domains get histograms — string dictionary
codes are not value-ordered (chunk/column.py Dictionary), so string range
predicates are estimated with the pseudo rate, as the reference does for
columns lacking stats (statistics/selectivity.go pseudo paths).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

DEFAULT_BUCKETS = 256


class Histogram:
    def __init__(self, lowers: np.ndarray, uppers: np.ndarray,
                 counts: np.ndarray, repeats: np.ndarray,
                 total: float) -> None:
        self.lowers = lowers      # per-bucket lower bound (inclusive)
        self.uppers = uppers      # per-bucket upper bound (inclusive)
        self.counts = counts      # per-bucket row count (float, scaled)
        self.cum = np.cumsum(counts)  # cumulative
        self.repeats = repeats    # rows equal to upper bound
        self.total = total        # total rows covered (scaled)

    @classmethod
    def build(cls, values: np.ndarray, scale: float = 1.0,
              n_buckets: int = DEFAULT_BUCKETS) -> Optional["Histogram"]:
        """values: non-null numeric array (unsorted ok)."""
        n = len(values)
        if n == 0:
            return None
        v = np.sort(values.astype(np.float64))
        n_buckets = min(n_buckets, n)
        # equal-depth boundaries; snap to value edges so `repeats` is exact
        edges = np.linspace(0, n, n_buckets + 1).astype(np.int64)[1:]
        edges = np.clip(edges, 1, n)
        uppers = v[edges - 1]
        # extend each bucket to cover all duplicates of its upper bound
        ends = np.searchsorted(v, uppers, side="right")
        ends = np.unique(ends)  # strictly increasing bucket end offsets
        starts = np.concatenate([[0], ends[:-1]])
        lowers = v[starts]
        uppers = v[ends - 1]
        counts = (ends - starts).astype(np.float64) * scale
        rep_start = np.searchsorted(v, uppers, side="left")
        repeats = (ends - rep_start).astype(np.float64) * scale
        return cls(lowers, uppers, counts, repeats, float(n) * scale)

    # ---- estimation -------------------------------------------------------
    def _less_count(self, x: float, inclusive: bool) -> float:
        """Rows with value < x (or <= x when inclusive)."""
        side = "right" if inclusive else "left"
        b = int(np.searchsorted(self.uppers, x, side=side))
        if b >= len(self.uppers):
            return self.total
        before = float(self.cum[b - 1]) if b > 0 else 0.0
        lo, up = float(self.lowers[b]), float(self.uppers[b])
        cnt = float(self.counts[b])
        if x < lo or up == lo:
            inside = float(inclusive and x == lo) * cnt
        elif x == up:
            # bucket boundary: strict-less excludes the repeats mass
            inside = cnt if inclusive else cnt - float(self.repeats[b])
        else:
            frac = (x - lo) / (up - lo)
            inside = cnt * min(max(frac, 0.0), 1.0)
        return before + inside

    def range_count(self, lo, hi, lo_incl: bool, hi_incl: bool) -> float:
        """Estimated rows in the interval; None bounds are unbounded."""
        hi_c = self._less_count(float(hi), hi_incl) if hi is not None \
            else self.total
        lo_c = self._less_count(float(lo), not lo_incl) if lo is not None \
            else 0.0
        return max(hi_c - lo_c, 0.0)

    def apply_range_feedback(self, lo, hi, lo_incl: bool, hi_incl: bool,
                             actual: float) -> None:
        """Scale the buckets overlapping [lo, hi] so the interval's
        estimate matches the observed row count (reference:
        statistics/feedback.go merging actual scan counts back into
        histogram buckets). The correction factor is clamped so one
        noisy observation can't destroy the histogram."""
        est = self.range_count(lo, hi, lo_incl, hi_incl)
        if est <= 0 or actual < 0:
            return
        factor = max(0.1, min(actual / est, 10.0))
        if abs(factor - 1.0) < 0.05:
            return
        lo_f = -np.inf if lo is None else float(lo)
        hi_f = np.inf if hi is None else float(hi)
        # per-bucket overlap fraction (same linear interpolation the
        # estimator uses): only the in-interval mass gets corrected, so
        # a narrow observation can't inflate a whole wide bucket
        width = np.maximum(self.uppers - self.lowers, 0.0)
        cover_lo = np.maximum(self.lowers, lo_f)
        cover_hi = np.minimum(self.uppers, hi_f)
        with np.errstate(invalid="ignore", divide="ignore"):
            frac = np.where(
                width > 0,
                np.clip((cover_hi - cover_lo) / np.where(width > 0, width,
                                                         1.0), 0.0, 1.0),
                ((self.lowers >= lo_f) & (self.uppers <= hi_f))
                .astype(np.float64))
        frac = np.where(cover_hi < cover_lo, 0.0, frac)
        if not (frac > 0).any():
            return
        delta = self.counts * frac * (factor - 1.0)
        self.counts = np.maximum(self.counts + delta, 0.0)
        self.repeats = np.minimum(self.repeats, self.counts)
        self.cum = np.cumsum(self.counts)
        self.total = float(self.counts.sum())

    def eq_count(self, x: float) -> float:
        b = int(np.searchsorted(self.uppers, x, side="left"))
        if b >= len(self.uppers):
            return 0.0
        if x == float(self.uppers[b]):
            return float(self.repeats[b])
        if x < float(self.lowers[b]):
            return 0.0
        # inside the bucket: assume uniform over its distinct values
        return float(self.counts[b]) / max(float(self.counts[b]) ** 0.5, 1.0)
