"""TiTPU — a TPU-native distributed SQL (HTAP) framework.

A from-scratch framework with the capabilities of the surveyed reference
(TiDB, see SURVEY.md): SQL frontend, cost-based planner, transactional
storage with MVCC, and a coprocessor tier ("TiTPU") that executes pushed-down
plan DAGs as JAX/XLA kernels over columnar chunks sharded across a TPU mesh.

Control plane (sessions, planning, transactions, schema) is host-side;
the data plane is columnar and device-side end-to-end.

int64 is required for exact DECIMAL arithmetic (scaled fixed-point; see
tidb_tpu/types) and for row handles, so x64 is enabled globally before any
JAX computation is traced.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
