"""TiTPU — a TPU-native distributed SQL (HTAP) framework.

A from-scratch framework with the capabilities of the surveyed reference
(TiDB, see SURVEY.md): SQL frontend, cost-based planner, transactional
storage with MVCC, and a coprocessor tier ("TiTPU") that executes pushed-down
plan DAGs as JAX/XLA kernels over columnar chunks sharded across a TPU mesh.

Control plane (sessions, planning, transactions, schema) is host-side;
the data plane is columnar and device-side end-to-end.

The device programs are 64-bit-free by design: TPUs have no native
int64/float64 (JAX x64 mode emulates them as u32 pairs, doubling transfer
bytes and parameter counts), so JAX's default 32-bit mode is kept and
exactness is carried by interval analysis + limb-exact summation
(tidb_tpu/copr/bounds.py, sumexact.py). Host-side columns remain numpy
int64/float64 — numpy is unaffected by the JAX dtype mode.
"""

__version__ = "0.2.0"
