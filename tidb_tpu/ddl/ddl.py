"""Online DDL: job queue, F1 schema-state machine, resumable reorg.

Counterpart of the reference's ddl/ package (ddl.go:522 doDDLJob enqueue,
ddl_worker.go:419 owner loop, index.go/column.go per-DDL state machines,
reorg.go:263 checkpointed backfill; F1 protocol per
docs/design/2018-10-08-online-DDL.md). TPU-first differences:

* Indexes are sorted permutations computed lazily from the epoch
  (store/index.py), so ADD INDEX has no row-at-a-time backfill — the
  write-reorg phase is the *uniqueness validation* scan for UNIQUE
  indexes, done in checkpointed batches over the sorted permutation.
* ADD/DROP/MODIFY COLUMN rewrite the columnar epoch in one vectorized
  pass (TableStore.apply_schema / cast_column) instead of per-row
  backfill transactions.

Jobs live on the Storage (the meta-KV job queue analog, meta/meta.go:571
DDLJobList): a worker that "crashes" mid-reorg leaves the job queued with
its reorg checkpoint; any new worker resumes from the checkpoint —
exercised by tests the way the reference tests resume via
GetDDLReorgHandle (ddl/reorg.go:627).

Each schema-state transition bumps the catalog version (meta.go:264
schema-version analog). While an index is delete-only/write-only/
write-reorg it is registered invisible: DML maintains (and unique-checks)
it, the planner will not read it.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..catalog.schema import ColumnInfo, IndexInfo, TableInfo
from ..types.field_type import FieldType, TypeKind


from ..errno import ER_DUP_ENTRY, ER_DUP_FIELDNAME, ER_DUP_KEYNAME, \
    CodedError


class DDLError(CodedError):
    """Schema-change error; duplicate-identity sites attach 106x codes."""


# job states (reference: model.JobState)
QUEUEING = "queueing"
RUNNING = "running"
DONE = "done"
ROLLED_BACK = "rolled back"

# schema states (reference: model.SchemaState, F1 protocol)
S_NONE = "none"
S_DELETE_ONLY = "delete only"
S_WRITE_ONLY = "write only"
S_WRITE_REORG = "write reorg"
S_PUBLIC = "public"

_job_ids = itertools.count(1)


@dataclass
class DDLJob:
    id: int
    kind: str  # add_index | drop_index | add_column | drop_column |
    #            modify_column | rename_table
    db: str
    table_id: int
    table_name: str
    args: dict[str, Any]
    state: str = QUEUEING
    schema_state: str = S_NONE
    # reorg checkpoint: position in the validation scan (resumable)
    reorg_pos: int = 0
    error: str = ""

    def row(self) -> tuple:
        """ADMIN SHOW DDL JOBS row."""
        return (self.id, self.db, self.table_name, self.kind,
                self.schema_state, self.state, self.error)


class DDL:
    """DDL worker. Synchronous by default (run_job drives a job to
    completion); step() exposes single transitions so tests can interleave
    concurrent DML and simulate worker crash/takeover mid-reorg."""

    REORG_BATCH = 20_000  # validation rows per step (reorg granularity)

    def __init__(self, storage, catalog) -> None:
        self.storage = storage
        self.catalog = catalog

    # ---- job api -----------------------------------------------------------
    def submit(self, kind: str, db: str, info: TableInfo,
               args: dict[str, Any]) -> DDLJob:
        job = DDLJob(next(_job_ids), kind, db, info.id, info.name, args)
        self.storage.ddl_jobs.append(job)
        self.storage.persist_ddl_jobs()
        return job

    def run_job(self, job: DDLJob) -> None:
        """Drive one job to completion as the DDL owner (reference: the
        owner-gated worker loop, ddl_worker.go:419; ownership comes from
        the election manager — mock in-process, flock across processes
        sharing a durable dir)."""
        owner = getattr(self.storage, "ddl_owner", None)
        if owner is None:
            self._run_job_steps(job)
            return
        with owner:
            # fold any sibling's schema changes BEFORE applying ours:
            # two servers altering different tables otherwise each
            # persist a full-catalog snapshot built from the other's
            # stale pre-image — a lost update whose colliding version
            # numbers also suppress the sibling reload (the reference
            # serializes on one owner AND reloads the schema at job
            # start, ddl_worker.go:419 + domain Reload)
            refresh = getattr(self.storage, "refresh", None)
            if refresh is not None:
                refresh()
            self._run_job_steps(job)

    def _run_job_steps(self, job: DDLJob) -> None:
        while not self.step(job):
            pass
        if job.state == ROLLED_BACK:
            raise DDLError(job.error)

    def resume_pending(self) -> None:
        """Owner-takeover path: drive any queued jobs to completion
        (reference: a new DDL owner picks the queue up, ddl_worker.go:419)."""
        while self.storage.ddl_jobs:
            self.run_job(self.storage.ddl_jobs[0])

    # ---- state machine -----------------------------------------------------
    def step(self, job: DDLJob) -> bool:
        """One transition (or one reorg batch). Returns True when the job
        left the queue (done or rolled back)."""
        from ..util import failpoint
        # simulated owner crash between persisted transitions (reference
        # failpoint pattern in ddl_worker tests); job state on storage is
        # the recovery truth
        failpoint.inject("ddl/before-step")
        job.state = RUNNING
        try:
            handler = getattr(self, "_on_" + job.kind)
            finished = handler(job)
        except DDLError as e:
            job.state = ROLLED_BACK
            job.error = str(e)
            self._rollback(job)
            self._finish(job)
            return True
        if finished:
            job.state = DONE
            job.schema_state = S_PUBLIC
            self._finish(job)
            return True
        # reorg checkpoint (job.reorg_pos / schema_state) survives a crash;
        # catalog persistence rides the bump_version hook in the handlers
        self.storage.persist_ddl_jobs()
        return False

    def _rollback(self, job: DDLJob) -> None:
        """Undo partially-applied schema state (reference:
        ddl/rollingback.go). Column/rename jobs apply atomically in their
        final step, so only the staged index states need unwinding."""
        info = self.catalog.try_table(job.db, job.table_name)
        if info is None:
            return
        if job.kind == "add_index" and "index_id" in job.args:
            info.indices = [ix for ix in info.indices
                            if ix.id != job.args["index_id"]]
        elif job.kind == "drop_index":
            name = job.args["name"].lower()
            for ix in info.indices:
                if ix.name.lower() == name:
                    ix.visible = True

    def _finish(self, job: DDLJob) -> None:
        if job in self.storage.ddl_jobs:
            self.storage.ddl_jobs.remove(job)
        self.storage.ddl_history.append(job)
        self.storage.persist_ddl_jobs()
        self.catalog.bump_version()

    def _info(self, job: DDLJob) -> TableInfo:
        info = self.catalog.try_table(job.db, job.table_name)
        if info is None or info.id != job.table_id:
            raise DDLError(f"table {job.table_name} is gone")
        return info

    # ---- ADD INDEX ---------------------------------------------------------
    def _on_add_index(self, job: DDLJob) -> bool:
        info = self._info(job)
        store = self.storage.table_store(info.id)
        a = job.args
        if job.schema_state == S_NONE:
            if any(ix.name.lower() == a["name"].lower()
                   for ix in info.indices):
                raise DDLError(f"Duplicate key name '{a['name']}'",
                               errno=ER_DUP_KEYNAME)
            offs = []
            for cname in a["columns"]:
                c = info.column_by_name(cname)
                if c is None:
                    raise DDLError(f"key column {cname} doesn't exist")
                offs.append(c.offset)
            index = IndexInfo(self.catalog.alloc_id(), a["name"], offs,
                              a.get("unique", False), False, visible=False)
            info.indices.append(index)
            a["index_id"] = index.id
            job.schema_state = S_DELETE_ONLY
            self.catalog.bump_version()
            return False
        index = next(ix for ix in info.indices if ix.id == a["index_id"])
        if job.schema_state == S_DELETE_ONLY:
            job.schema_state = S_WRITE_ONLY
            self.catalog.bump_version()
            return False
        if job.schema_state == S_WRITE_ONLY:
            job.schema_state = S_WRITE_REORG
            self.catalog.bump_version()
            return False
        if job.schema_state == S_WRITE_REORG:
            if index.unique:
                done = self._validate_unique_batch(job, info, store, index)
                if not done:
                    return False
                # publish race: a txn that buffered rows BEFORE the index
                # was registered can commit between the last validation
                # snapshot and the token bump — it was never unique-checked.
                # Close the window under the commit lock: no commit can land
                # while we re-validate the overlay and bump the fence
                # (reference: schema-version sync gates publication,
                # ddl/util/syncer.go + domain/schema_validator.go).
                with self.storage._commit_lock:
                    txn = self.storage.begin()
                    try:
                        snap = txn.snapshot(info.id)
                        # an empty epoch needs no batched scan (and set no
                        # reorg_epoch); otherwise the epoch must still be
                        # the one the batches validated — a compaction in
                        # between folded unvalidated commits into a fresh
                        # epoch, so restart the scan on it
                        if snap.epoch.num_rows > 0 and \
                                snap.epoch.epoch_id != \
                                job.args.get("reorg_epoch"):
                            job.args["reorg_epoch"] = None
                            job.reorg_pos = 0
                            return False
                        self._validate_overlay(snap, index, info)
                    finally:
                        txn.rollback()
                    index.visible = True
                    store.schema_token += 1
                    # NOTE: no bump_version here — the durable on_change
                    # hook writes meta-KV under _commit_lock, which this
                    # block already holds; _finish bumps outside the lock
                return True
            index.visible = True
            # fence txns that buffered writes before the index existed —
            # they never unique-checked it (schema_validator analog)
            store.schema_token += 1
            return True
        raise DDLError(f"bad state {job.schema_state}")

    def _validate_unique_batch(self, job: DDLJob, info: TableInfo,
                               store, index: IndexInfo) -> bool:
        """One checkpointed batch of the unique-validation scan: walk the
        sorted permutation looking for adjacent equal keys (reference:
        backfill worker batches + reorg handle checkpoints,
        ddl/backfilling.go:139, reorg.go:263). New writes are already
        unique-checked by DML (index registered in write-only)."""
        from ..store.index import epoch_index_order

        txn = self.storage.begin()
        try:
            snap = txn.snapshot(info.id)
            epoch = snap.epoch
            n = epoch.num_rows
            if n == 0:
                self._validate_overlay(snap, index, info)
                return True
            order = epoch_index_order(store, epoch, index)
            # a compaction between batches replaces the epoch and reshuffles
            # the permutation — positions below the checkpoint would escape
            # validation; restart on the new epoch (reference re-runs reorg
            # from the persisted element on owner change, reorg.go:708)
            if job.args.get("reorg_epoch") != epoch.epoch_id:
                job.args["reorg_epoch"] = epoch.epoch_id
                job.reorg_pos = 0
            start = job.reorg_pos
            stop = min(start + self.REORG_BATCH, n)
            # overlap back to the nearest VISIBLE row before the batch so
            # cross-batch neighbors are compared even when deleted rows sit
            # at the boundary
            lo = start
            while lo > 0:
                lo -= 1
                if snap.base_visible[order[lo]]:
                    break
            rows = order[lo:stop]
            vis = snap.base_visible[rows]
            rows = rows[vis]
            if len(rows) > 1:
                dup = np.ones(len(rows) - 1, dtype=bool)
                for off in index.col_offsets:
                    data = epoch.columns[off][rows]
                    dup &= data[1:] == data[:-1]
                    valid = epoch.valids[off]
                    if valid is not None:
                        v = valid[rows]
                        dup &= v[1:] & v[:-1]  # NULL keys never collide
                if dup.any():
                    i = int(np.nonzero(dup)[0][0])
                    key = "-".join(
                        str(epoch.columns[off][rows[i + 1]])
                        for off in index.col_offsets)
                    raise DDLError(
                        f"Duplicate entry '{key}' for key '{index.name}'",
                        errno=ER_DUP_ENTRY)
            # overlay rows (small): checked against whole key space via the
            # DML-time unique checker; validate among themselves + epoch
            self._validate_overlay(snap, index, info)
            job.reorg_pos = stop
            return stop >= n
        finally:
            txn.rollback()

    def _validate_overlay(self, snap, index: IndexInfo,
                          info: TableInfo) -> None:
        from ..store.index import IndexSearcher

        m = len(snap.overlay_handles)
        if m == 0:
            return
        searcher = IndexSearcher(snap.store, snap, index)
        seen: dict[tuple, int] = {}
        for i in range(m):
            key = []
            ok = True
            for off in index.col_offsets:
                valid = snap.overlay_valids[off]
                if valid is not None and not valid[i]:
                    ok = False
                    break
                key.append(snap.overlay_columns[off][i].item())
            if not ok:
                continue
            key_t = tuple(key)
            h = int(snap.overlay_handles[i])
            if seen.get(key_t, h) != h:
                raise DDLError(
                    f"Duplicate entry '{'-'.join(map(str, key_t))}' "
                    f"for key '{index.name}'", errno=ER_DUP_ENTRY)
            seen[key_t] = h
            hits = [x for x in searcher.eq(key_t) if int(x) != h]
            if hits:
                raise DDLError(
                    f"Duplicate entry '{'-'.join(map(str, key_t))}' "
                    f"for key '{index.name}'", errno=ER_DUP_ENTRY)

    # ---- DROP INDEX --------------------------------------------------------
    def _on_drop_index(self, job: DDLJob) -> bool:
        info = self._info(job)
        name = job.args["name"].lower()
        hit = next((ix for ix in info.indices
                    if ix.name.lower() == name), None)
        if job.schema_state == S_NONE:
            if hit is None:
                raise DDLError(f"check that index {job.args['name']} exists")
            if hit.primary:
                raise DDLError("cannot drop primary key")
            hit.visible = False  # write-only: planner stops reading it
            job.schema_state = S_WRITE_ONLY
            self.catalog.bump_version()
            return False
        if job.schema_state == S_WRITE_ONLY:
            if hit is not None:
                info.indices.remove(hit)
            self.storage.table_store(info.id).schema_token += 1
            return True
        raise DDLError(f"bad state {job.schema_state}")

    # ---- ADD COLUMN --------------------------------------------------------
    def _on_add_column(self, job: DDLJob) -> bool:
        info = self._info(job)
        store = self.storage.table_store(info.id)
        a = job.args
        if info.column_by_name(a["name"]) is not None:
            raise DDLError(f"Duplicate column name '{a['name']}'",
                           errno=ER_DUP_FIELDNAME)
        ft: FieldType = a["ftype"]
        default = a.get("default")
        if default is None and not ft.nullable:
            raise DDLError(f"column {a['name']} needs a default or NULL")
        new_cols = [ColumnInfo(c.id, c.name, c.ftype, c.offset, c.default,
                               c.is_primary, c.auto_increment)
                    for c in info.columns]
        off = len(new_cols)
        new_cols.append(ColumnInfo(self.catalog.alloc_id(), a["name"], ft,
                                   off, default))
        new_info = TableInfo(info.id, info.name, new_cols,
                             list(info.indices), info.pk_handle_offset)
        column_map: list = list(range(len(info.columns))) + [None]
        phys = _phys_default(ft, a.get("phys_default", default))
        store.apply_schema(new_info, column_map,
                           {off: (phys, default is not None)})
        self.catalog.replace_table(job.db, info.name, new_info)
        self.storage.stats.drop_table(info.id)
        return True

    # ---- DROP COLUMN -------------------------------------------------------
    def _on_drop_column(self, job: DDLJob) -> bool:
        info = self._info(job)
        store = self.storage.table_store(info.id)
        c = info.column_by_name(job.args["name"])
        if c is None:
            raise DDLError(f"column {job.args['name']} doesn't exist")
        if info.pk_handle_offset == c.offset:
            raise DDLError("cannot drop the primary key column")
        if len(info.columns) == 1:
            raise DDLError("cannot drop the only column")
        old_off = c.offset
        new_cols = []
        column_map: list = []
        remap: dict[int, int] = {}
        for oc in info.columns:
            if oc.offset == old_off:
                continue
            remap[oc.offset] = len(new_cols)
            new_cols.append(ColumnInfo(oc.id, oc.name, oc.ftype,
                                       len(new_cols), oc.default,
                                       oc.is_primary, oc.auto_increment))
            column_map.append(oc.offset)
        # indexes covering the column are dropped (MySQL drops multi-col
        # index parts; single behavior kept simple: whole index goes)
        new_indices = []
        for ix in info.indices:
            if old_off in ix.col_offsets:
                continue
            new_indices.append(IndexInfo(
                ix.id, ix.name, [remap[o] for o in ix.col_offsets],
                ix.unique, ix.primary, ix.visible))
        pk = info.pk_handle_offset
        if pk is not None:
            pk = remap[pk]
        new_info = TableInfo(info.id, info.name, new_cols, new_indices, pk)
        store.apply_schema(new_info, column_map, {})
        self.catalog.replace_table(job.db, info.name, new_info)
        self.storage.stats.drop_table(info.id)
        return True

    # ---- MODIFY COLUMN -----------------------------------------------------
    def _on_modify_column(self, job: DDLJob) -> bool:
        info = self._info(job)
        store = self.storage.table_store(info.id)
        a = job.args
        c = info.column_by_name(a["name"])
        if c is None:
            raise DDLError(f"column {a['name']} doesn't exist")
        new_ft: FieldType = a["ftype"]
        old_ft = c.ftype
        cast_fn = _column_cast(old_ft, new_ft)
        if cast_fn is None:
            raise DDLError(
                f"unsupported column type change {old_ft!r} -> {new_ft!r}")
        if not _is_lossless_cast(old_ft, new_ft):
            # a narrowing cast can collapse distinct values (0.9 and 1.1
            # both round to 1), leaving duplicate keys in a unique index
            # with no error — the reference re-validates uniqueness during
            # modify-column reorg (ddl/column.go); until that scan exists
            # here, reject the lossy change on uniquely-keyed columns
            for ix in info.indices:
                if ix.unique and c.offset in ix.col_offsets:
                    raise DDLError(
                        f"unsupported lossy type change {old_ft!r} -> "
                        f"{new_ft!r} on column '{c.name}' covered by "
                        f"unique key '{ix.name}'")
            if info.pk_handle_offset == c.offset:
                raise DDLError(
                    f"unsupported lossy type change {old_ft!r} -> "
                    f"{new_ft!r} on primary key column '{c.name}'")
        new_cols = [ColumnInfo(oc.id, oc.name,
                               new_ft if oc.offset == c.offset else oc.ftype,
                               oc.offset, oc.default, oc.is_primary,
                               oc.auto_increment)
                    for oc in info.columns]
        new_info = TableInfo(info.id, info.name, new_cols,
                             list(info.indices), info.pk_handle_offset)
        # data rewrite + TableInfo swap are one atomic step under the store
        # lock: a snapshot must never pair rescaled values with the old type
        err = store.cast_column(c.offset, cast_fn, new_info)
        if err is not None:
            raise DDLError(f"data truncated: {err}")
        self.catalog.replace_table(job.db, info.name, new_info)
        self.storage.stats.drop_table(info.id)
        return True

    # ---- RENAME TABLE ------------------------------------------------------
    def _on_rename_table(self, job: DDLJob) -> bool:
        info = self._info(job)
        new_name = job.args["new_name"]
        new_db = job.args.get("new_db", job.db)
        if self.catalog.try_table(new_db, new_name) is not None:
            raise DDLError(f"table {new_name} already exists")
        old_name = info.name
        new_info = TableInfo(info.id, new_name, info.columns,
                             info.indices, info.pk_handle_offset)
        store = self.storage.table_store(info.id)
        store.table = new_info
        store.schema_token += 1
        schema = self.catalog.schema(job.db)
        schema.tables.pop(old_name.lower(), None)
        self.catalog.replace_table(new_db, new_name, new_info)
        return True


_INT_DIGITS = {TypeKind.TINYINT: 3, TypeKind.SMALLINT: 5, TypeKind.INT: 10,
               TypeKind.BIGINT: 19, TypeKind.BOOLEAN: 1, TypeKind.YEAR: 4}


def _is_lossless_cast(old: FieldType, new: FieldType) -> bool:
    """True when the MODIFY COLUMN conversion can never collapse two
    distinct stored values into one (safe on uniquely-indexed columns)."""
    if old.kind == new.kind and not old.is_decimal:
        return True
    if old.is_string and new.is_string:
        return True
    if old.is_integer and new.is_integer:
        return _INT_DIGITS.get(new.kind, 0) >= _INT_DIGITS.get(old.kind, 99)
    if old.is_integer and new.is_decimal:
        return (new.flen - new.scale) >= _INT_DIGITS.get(old.kind, 99)
    if old.is_decimal and new.is_decimal:
        # scale must not shrink (rounding collapses) and integer-digit
        # capacity must not shrink (conservative: overflow raises rather
        # than collapses, but keep the declared capacity honest)
        return (new.scale >= old.scale
                and (new.flen - new.scale) >= (old.flen - old.scale))
    # float targets round to ~15 digits; decimal/float -> int truncates —
    # all potentially value-collapsing
    return False


def _phys_default(ft: FieldType, default):
    """Physical fill value; string defaults stay raw — apply_schema encodes
    them into the column's fresh dictionary."""
    return 0 if default is None else default


def _column_cast(old: FieldType, new: FieldType):
    """cast_fn(data, valid) -> (data, valid) for supported MODIFY COLUMN
    conversions (numeric widening/narrowing with range check, decimal
    rescale, int<->decimal, ->double, varchar widen)."""
    if old.is_string and new.is_string:
        return lambda d, v: (d, v)  # dictionary codes unchanged
    if old.is_string or new.is_string:
        return None
    if old.is_temporal or new.is_temporal:
        if old.kind == new.kind:
            return lambda d, v: (d, v)
        return None

    def to_float(d, v):
        if old.is_decimal:
            return d.astype(np.float64) / (10 ** old.scale), v
        return d.astype(np.float64), v

    if new.kind == TypeKind.DOUBLE or new.kind == TypeKind.FLOAT:
        return to_float

    # int-family conversions stay in the int64 domain end-to-end — a
    # float64 round-trip would silently corrupt values above 2^53
    def to_int_like(d, v):
        if old.is_float:
            return _range_checked_float(np.round(d.astype(np.float64)), v,
                                        new)
        x = d.astype(np.int64)
        if old.is_decimal:
            x = _div_round_half_up(x, 10 ** old.scale)
        return _range_checked_int(x, v, new)

    def to_decimal(d, v):
        if old.is_float:
            return _range_checked_float(
                np.round(d.astype(np.float64) * 10 ** new.scale), v, new)
        x = d.astype(np.int64)
        if old.is_decimal:
            if new.scale >= old.scale:
                x = _mul_checked(x, v, 10 ** (new.scale - old.scale))
            else:
                x = _div_round_half_up(x, 10 ** (old.scale - new.scale))
        else:
            x = _mul_checked(x, v, 10 ** new.scale)
        return _range_checked_int(x, v, new)

    if new.is_decimal:
        return to_decimal
    return to_int_like


_INT_RANGES = {
    TypeKind.TINYINT: (-128, 127),
    TypeKind.SMALLINT: (-32768, 32767),
    TypeKind.INT: (-2**31, 2**31 - 1),
    TypeKind.BIGINT: (-2**63, 2**63 - 1),
    TypeKind.DECIMAL: (-2**63, 2**63 - 1),
    TypeKind.BOOLEAN: (0, 1),
    TypeKind.YEAR: (1901, 2155),
}


def _div_round_half_up(x: np.ndarray, f: int) -> np.ndarray:
    """Exact int64 division rounding half away from zero."""
    half = f // 2
    return np.where(x >= 0, (x + half) // f, -((-x + half) // f))


def _mul_checked(x: np.ndarray, valid: np.ndarray, f: int) -> np.ndarray:
    limit = (2**63 - 1) // f
    bad = valid & (np.abs(x) > limit)
    if bad.any():
        raise ValueError(f"value {x[bad][0]} overflows at scale factor {f}")
    return x * f


def _range_checked_int(vals: np.ndarray, valid: np.ndarray, ft: FieldType):
    lo, hi = _INT_RANGES.get(ft.kind, (-2**63, 2**63 - 1))
    bad = valid & ((vals < lo) | (vals > hi))
    if bad.any():
        raise ValueError(f"value {vals[bad][0]} out of range for {ft!r}")
    return vals, valid


def _range_checked_float(vals: np.ndarray, valid: np.ndarray, ft: FieldType):
    lo, hi = _INT_RANGES.get(ft.kind, (-2**63, 2**63 - 1))
    live = valid & np.isfinite(vals)
    # strict float compare is safe here: inputs came from float storage
    bad = live & ((vals < float(lo)) | (vals > float(hi)))
    if bad.any():
        raise ValueError(f"value {vals[bad][0]} out of range for {ft!r}")
    return vals.astype(np.int64), valid
