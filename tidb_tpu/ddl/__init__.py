"""Online DDL subsystem (SURVEY.md §2 L9: ddl/ job queue + state machine)."""

from .ddl import DDL, DDLError, DDLJob  # noqa: F401
