"""Catalog: schema metadata and name resolution.

Counterpart of the reference's `infoschema.InfoSchema` + `model.TableInfo`
(reference: infoschema/infoschema.go:39; model types from the external
parser module). The catalog is an immutable-ish snapshot consumed by the
planner; DDL produces new versions (schema_version bumps mirror the
reference's meta schema-version, meta/meta.go:264).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..errno import (
    ER_BAD_DB,
    ER_DB_CREATE_EXISTS,
    ER_NO_SUCH_TABLE,
    ER_TABLE_EXISTS,
    CodedError,
)
from ..types.field_type import FieldType


@dataclass
class ColumnInfo:
    id: int
    name: str
    ftype: FieldType
    offset: int = 0  # position in the table
    default: Any = None
    is_primary: bool = False
    auto_increment: bool = False

    @property
    def nullable(self) -> bool:
        return self.ftype.nullable and not self.is_primary


@dataclass
class IndexInfo:
    id: int
    name: str
    col_offsets: list[int]
    unique: bool = False
    primary: bool = False
    # False while the index is being built online (delete-only/write-only/
    # write-reorg states, reference ddl/index.go): writes maintain it, the
    # planner must not read it yet
    visible: bool = True


@dataclass
class FKInfo:
    """Foreign-key metadata (reference: model.FKInfo; the v5.0 reference
    PARSES and stores FK constraints but does not enforce them —
    ddl/foreign_key.go builds metadata only, foreign_key_checks defaults
    off. Same here: catalog + information_schema surface, no runtime
    enforcement)."""

    name: str
    col_offsets: list[int]
    ref_db: str
    ref_table: str
    ref_cols: list[str]
    on_delete: str = "RESTRICT"  # RESTRICT|CASCADE|SET NULL|NO ACTION
    on_update: str = "RESTRICT"


@dataclass
class SequenceInfo:
    """CREATE SEQUENCE state (reference: model.SequenceInfo +
    ddl/sequence.go; TiDB's MariaDB-compatible sequences)."""

    id: int
    name: str
    start: int = 1
    increment: int = 1
    min_value: int = 1
    max_value: int = (1 << 63) - 1
    cycle: bool = False
    next_value: int = 1


@dataclass
class PartitionDef:
    """One partition: own table id = own physical TableStore + KV range
    (reference: model.PartitionDefinition — each partition is a physical
    table, table/tables/partition.go)."""

    name: str
    id: int
    # RANGE: exclusive upper bound; None = MAXVALUE. HASH: unused.
    less_than: Optional[int] = None


@dataclass
class PartitionInfo:
    """PARTITION BY metadata (reference: model.PartitionInfo;
    ddl/partition.go builds it, planner prunes on it)."""

    kind: str  # 'hash' | 'range'
    col_offset: int
    defs: list[PartitionDef] = field(default_factory=list)

    def route(self, value) -> PartitionDef:
        """Partition for a column value (reference: partitionedTable
        locatePartition, table/tables/partition.go)."""
        if value is None:
            if self.kind == "hash":
                return self.defs[0]  # MySQL: NULL hashes to partition 0
            # RANGE: NULL sorts below every bound -> first partition
            return self.defs[0]
        v = int(value)
        if self.kind == "hash":
            return self.defs[v % len(self.defs)]
        for d in self.defs:
            if d.less_than is None or v < d.less_than:
                return d
        raise ValueError(
            f"Table has no partition for value {v}")

    def by_name(self, name: str) -> Optional[PartitionDef]:
        lname = name.lower()
        for d in self.defs:
            if d.name.lower() == lname:
                return d
        return None


@dataclass
class TableInfo:
    id: int
    name: str
    columns: list[ColumnInfo]
    indices: list[IndexInfo] = field(default_factory=list)
    # offset of an integer PRIMARY KEY column used directly as the row
    # handle (reference: pk-is-handle tables, table/tables.go); None means
    # rows get auto-allocated internal handles.
    pk_handle_offset: Optional[int] = None
    # PARTITION BY metadata; None = unpartitioned. Access via
    # getattr(info, 'partition', None) where old pickled catalogs may
    # lack the field.
    partition: Optional[PartitionInfo] = None
    # foreign-key constraints (metadata only; see FKInfo)
    foreign_keys: list = field(default_factory=list)

    def column_by_name(self, name: str) -> Optional[ColumnInfo]:
        lname = name.lower()
        for c in self.columns:
            if c.name.lower() == lname:
                return c
        return None

    @property
    def num_columns(self) -> int:
        return len(self.columns)


class CatalogError(CodedError, KeyError):
    """Schema lookup/namespace error. Subclasses KeyError so existing
    `except KeyError` callers keep working; __str__ stays Exception's
    (KeyError would repr-quote the message)."""

    def __str__(self) -> str:  # noqa: D105
        return Exception.__str__(self)


@dataclass
class SchemaInfo:
    name: str
    tables: dict[str, TableInfo] = field(default_factory=dict)  # lower-name keyed
    sequences: dict[str, SequenceInfo] = field(default_factory=dict)
    views: dict[str, "ViewInfo"] = field(default_factory=dict)


@dataclass
class ViewInfo:
    """A named stored SELECT, expanded at plan-build time (reference:
    ddl/ddl_api.go CreateView; planner/core/logical_plan_builder.go
    BuildDataSourceFromView re-parses the stored SELECT). Column aliases
    (when given) rename the underlying SELECT's output columns."""

    name: str
    sql: str            # the SELECT text
    columns: tuple = ()  # optional explicit column-name list
    definer: str = "root@%"


class Catalog:
    """All schemas + id allocation + versioning. Single-node, in-memory.

    Name lookups are case-insensitive (MySQL default on most platforms).
    """

    def __init__(self) -> None:
        self.schemas: dict[str, SchemaInfo] = {}
        self.version = 0
        self._next_id = 1
        # durable storage installs a persistence hook here; fired on every
        # version bump (the schema-version write of meta/meta.go:264)
        self.on_change = None
        self.create_schema("test")  # convenience default, like test setups

    # ---- id / version ------------------------------------------------------
    def alloc_id(self) -> int:
        i = self._next_id
        self._next_id += 1
        return i

    def bump_version(self) -> int:
        self.version += 1
        if self.on_change is not None:
            self.on_change()
        return self.version

    # ---- schema ops --------------------------------------------------------
    def create_schema(self, name: str, if_not_exists: bool = False) -> SchemaInfo:
        key = name.lower()
        if key in self.schemas:
            if if_not_exists:
                return self.schemas[key]
            raise CatalogError(f"database exists: {name}", errno=ER_DB_CREATE_EXISTS)
        info = SchemaInfo(name)
        self.schemas[key] = info
        self.bump_version()
        return info

    def drop_schema(self, name: str, if_exists: bool = False) -> list[TableInfo]:
        key = name.lower()
        if key not in self.schemas:
            if if_exists:
                return []
            raise CatalogError(f"unknown database: {name}", errno=ER_BAD_DB)
        dropped = list(self.schemas.pop(key).tables.values())
        self.bump_version()
        return dropped

    def schema(self, name: str) -> SchemaInfo:
        key = name.lower()
        if key not in self.schemas:
            raise CatalogError(f"unknown database: {name}", errno=ER_BAD_DB)
        return self.schemas[key]

    # ---- table ops ---------------------------------------------------------
    def add_table(self, db: str, tbl: TableInfo, if_not_exists: bool = False) -> bool:
        schema = self.schema(db)
        key = tbl.name.lower()
        if key in schema.tables:
            if if_not_exists:
                return False
            raise CatalogError(f"table exists: {db}.{tbl.name}", errno=ER_TABLE_EXISTS)
        schema.tables[key] = tbl
        self.bump_version()
        return True

    def drop_table(self, db: str, name: str, if_exists: bool = False) -> Optional[TableInfo]:
        schema = self.schema(db)
        key = name.lower()
        if key not in schema.tables:
            if if_exists:
                return None
            raise CatalogError(f"unknown table: {db}.{name}", errno=ER_NO_SUCH_TABLE)
        info = schema.tables.pop(key)
        self.bump_version()
        return info

    def table(self, db: str, name: str) -> TableInfo:
        schema = self.schema(db)
        key = name.lower()
        if key not in schema.tables:
            raise CatalogError(f"unknown table: {db}.{name}", errno=ER_NO_SUCH_TABLE)
        return schema.tables[key]

    def try_table(self, db: str, name: str) -> Optional[TableInfo]:
        try:
            return self.table(db, name)
        except KeyError:
            return None

    def replace_table(self, db: str, old_name: str, info: TableInfo) -> None:
        """Swap in a new TableInfo object (DDL publishes new schema versions
        as fresh immutable-ish objects so in-flight snapshots keep the old
        one — the schema-version delta apply of infoschema/builder.go)."""
        schema = self.schema(db)
        schema.tables.pop(old_name.lower(), None)
        schema.tables[info.name.lower()] = info
        self.bump_version()
