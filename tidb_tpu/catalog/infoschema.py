"""INFORMATION_SCHEMA virtual tables, materialized on demand.

Counterpart of the reference's infoschema memtables (reference:
infoschema/tables.go — SCHEMATA/TABLES/COLUMNS/... served straight from
the InfoSchema snapshot by executor/infoschema_reader.go). Here the
tables are ordinary columnar TableStores rebuilt from the live catalog
right before a query touches them: the coprocessor then scans them like
any other table, so filters/joins/aggregations over metadata need no
special executor.

The information_schema stores never persist (derived data) and never ride
the KV plane — refresh replaces the whole store in place.
"""

from __future__ import annotations

import numpy as np

from ..types.field_type import FieldType, TypeKind
from .schema import Catalog, ColumnInfo, SchemaInfo, TableInfo

DB_NAME = "information_schema"


def _vc(n: int = 64) -> FieldType:
    return FieldType(TypeKind.VARCHAR, flen=n)


def _bigint() -> FieldType:
    return FieldType(TypeKind.BIGINT)


# table name -> [(column name, ftype)]
_DEFS: dict[str, list[tuple[str, FieldType]]] = {
    "schemata": [
        ("catalog_name", _vc()), ("schema_name", _vc()),
        ("default_character_set_name", _vc(32)),
        ("default_collation_name", _vc(32)), ("sql_path", _vc()),
    ],
    "tables": [
        ("table_catalog", _vc()), ("table_schema", _vc()),
        ("table_name", _vc()), ("table_type", _vc(32)),
        ("engine", _vc(32)), ("version", _bigint()),
        ("row_format", _vc(16)), ("table_rows", _bigint()),
        ("avg_row_length", _bigint()), ("data_length", _bigint()),
        ("index_length", _bigint()), ("auto_increment", _bigint()),
        ("table_collation", _vc(32)), ("create_options", _vc()),
        ("table_comment", _vc(128)),
    ],
    "columns": [
        ("table_catalog", _vc()), ("table_schema", _vc()),
        ("table_name", _vc()), ("column_name", _vc()),
        ("ordinal_position", _bigint()), ("column_default", _vc(128)),
        ("is_nullable", _vc(8)), ("data_type", _vc(32)),
        ("character_maximum_length", _bigint()),
        ("numeric_precision", _bigint()), ("numeric_scale", _bigint()),
        ("character_set_name", _vc(32)), ("collation_name", _vc(32)),
        ("column_type", _vc(64)), ("column_key", _vc(8)),
        ("extra", _vc(32)), ("column_comment", _vc(128)),
    ],
    "statistics": [
        ("table_catalog", _vc()), ("table_schema", _vc()),
        ("table_name", _vc()), ("non_unique", _bigint()),
        ("index_schema", _vc()), ("index_name", _vc()),
        ("seq_in_index", _bigint()), ("column_name", _vc()),
        ("cardinality", _bigint()), ("index_type", _vc(16)),
    ],
    "engines": [
        ("engine", _vc(32)), ("support", _vc(8)), ("comment", _vc(128)),
        ("transactions", _vc(8)), ("xa", _vc(8)), ("savepoints", _vc(8)),
    ],
    "collations": [
        ("collation_name", _vc(32)), ("character_set_name", _vc(32)),
        ("id", _bigint()), ("is_default", _vc(8)), ("is_compiled", _vc(8)),
        ("sortlen", _bigint()),
    ],
    "character_sets": [
        ("character_set_name", _vc(32)), ("default_collate_name", _vc(32)),
        ("description", _vc(64)), ("maxlen", _bigint()),
    ],
    # aggregated statement digests (reference: util/stmtsummary feeding
    # infoschema statements_summary, statement_summary.go)
    "statements_summary": [
        ("digest", _vc(32)), ("schema_name", _vc()),
        ("digest_text", _vc(512)), ("query_sample_text", _vc(512)),
        ("exec_count", _bigint()), ("sum_errors", _bigint()),
        ("sum_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("avg_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("max_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("sum_result_rows", _bigint()),
        # per-digest working-set high-water / spill totals (reference:
        # stmtsummary MAX_MEM / SUM_DISK) — governor-kill forensics
        ("max_mem_bytes", _bigint()), ("sum_spill_count", _bigint()),
        ("first_seen", _vc(20)), ("last_seen", _vc(20)),
    ],
    # workload-history plane (reference: util/stmtsummary's windowed
    # persistence behind STATEMENTS_SUMMARY_HISTORY): one row per
    # rotated window x (sql_digest, plan_digest) — wall/stage split,
    # engine tags + fragment strategy, rows, mesh skew — read back
    # from <path>/history/ across restarts. Empty (zero work) while
    # history.enabled is false.
    "statements_summary_history": [
        ("summary_begin_time", _vc(20)), ("summary_end_time", _vc(20)),
        ("digest", _vc(32)), ("schema_name", _vc()),
        ("digest_text", _vc(512)), ("plan_digest", _vc(32)),
        ("engines", _vc(256)), ("plan_strategy", _vc(64)),
        ("exec_count", _bigint()), ("sum_errors", _bigint()),
        ("avg_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("max_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("sum_rows", _bigint()), ("stages", _vc(256)),
        ("mesh_skew", FieldType(TypeKind.DOUBLE)),
    ],
    # per-(digest, plan) rollup of the whole retained history — the
    # "which plan won" view the plan-regression rule and ROADMAP item
    # 5's adaptive fragment-strategy choice read
    "tidb_plan_history": [
        ("digest", _vc(32)), ("plan_digest", _vc(32)),
        ("digest_text", _vc(512)), ("engines", _vc(256)),
        ("plan_strategy", _vc(64)), ("windows", _bigint()),
        ("exec_count", _bigint()), ("sum_errors", _bigint()),
        ("avg_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("p50_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("max_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("first_seen", _vc(20)), ("last_seen", _vc(20)),
        ("current_plan", _bigint()),
    ],
    # the queryable slow log (reference: executor/slow_query.go parsing
    # the slow-log file back into INFORMATION_SCHEMA.SLOW_QUERY)
    "slow_query": [
        ("time", _vc(20)), ("db", _vc()),
        ("query_time_ms", FieldType(TypeKind.DOUBLE)),
        ("query", _vc(4096)),
        ("plan_digest", _vc(32)), ("stages", _vc(256)),
        # statement working-set peak + spills (reference: slow_query's
        # Mem_max / Disk_max columns)
        ("mem_max", _bigint()), ("spill_count", _bigint()),
        # per-operator exclusive wall split ('join:42ms scan:7ms ...')
        # — which operator of this digest spent the time
        ("operators", _vc(256)),
        # worst max/mean shard-row ratio of the statement's sharded
        # dispatches (0 = no sharded dispatch) — mesh flight recorder
        ("mesh_skew", FieldType(TypeKind.DOUBLE)),
        # typed exclusive wait split ('prewrite:8.2ms tso_wait:1.1ms
        # ...') — where this statement BLOCKED, heaviest state first;
        # empty while performance.wait-profile-enabled is off
        ("wait_profile", _vc(256)),
    ],
    # continuous per-digest resource attribution (reference: TiDB's
    # Top SQL / util/topsql): one '(stmt)' summary row per (window,
    # digest) plus one row per plan operator with its exclusive wall
    # time, stage split, and host->device transfer bytes. Fed on every
    # statement completion while performance.topsql-enabled is on.
    "tidb_top_sql": [
        ("window_start", _vc(20)), ("digest", _vc(32)),
        ("digest_text", _vc(512)), ("operator", _vc(64)),
        ("exec_count", _bigint()),
        ("sum_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("op_time_ms", FieldType(TypeKind.DOUBLE)),
        ("op_transfer_bytes", _bigint()), ("stages", _vc(256)),
        ("sum_rows", _bigint()), ("admission_sheds", _bigint()),
        ("governor_kills", _bigint()),
        # worst max-shard share of the operator's sharded dispatches
        # (1/shards = balanced, 1.0 = one device did everything)
        ("max_shard_share", FieldType(TypeKind.DOUBLE)),
        # dominant typed wait state of the (window, digest) as
        # 'state:frac' ('backoff.txnLock:0.73'); empty on operator
        # rows and while the wait profile is off
        ("dominant_wait", _vc(64)),
    ],
    # per-(window, digest, wait-state) exclusive wait attribution —
    # the SQL face of the WaitProfile ring (one row per typed state a
    # digest spent blocked in, newest window first). Empty (zero
    # ledger work) while performance.wait-profile-enabled is false.
    "tidb_wait_profile": [
        ("window_start", _vc(20)), ("digest", _vc(32)),
        ("digest_text", _vc(512)), ("schema_name", _vc()),
        ("exec_count", _bigint()),
        ("sum_wall_ms", FieldType(TypeKind.DOUBLE)),
        ("state", _vc(32)),
        ("wait_ms", FieldType(TypeKind.DOUBLE)),
        ("wait_frac", FieldType(TypeKind.DOUBLE)),
    ],
    # mesh flight recorder: per-plan-digest per-shard dispatch
    # accounting (input rows, post-filter survivors, skew, exchange
    # routing bytes), bounded by mesh.shard-ring-cap
    "tidb_mesh_shards": [
        ("digest", _vc(32)), ("kind", _vc(16)), ("operator", _vc(64)),
        ("dispatches", _bigint()), ("shards", _bigint()),
        ("last_shard_rows", _vc(256)),
        ("last_skew", FieldType(TypeKind.DOUBLE)),
        ("max_skew", FieldType(TypeKind.DOUBLE)),
        ("in_rows", _bigint()), ("out_rows", _bigint()),
        ("routed_bytes", _bigint()), ("last_seen", _vc(20)),
    ],
    # per-device HBM provenance ledger: every cached placed array
    # classified by (table/epoch, kind), plus one '(device)' total row
    # per device with live + peak bytes (live totals equal
    # tidb_device_buffer_bytes{device})
    "tidb_mesh_storage": [
        ("device", _vc(64)), ("table_name", _vc(64)),
        ("epoch_id", _bigint()), ("kind", _vc(16)),
        ("arrays", _bigint()), ("bytes", _bigint()),
        ("peak_bytes", _bigint()),
    ],
    # structured server event ring: governor kills, admission sheds,
    # breaker trips, elections/promotions, checkpoint/fsync stalls —
    # with conn/digest attribution where the producer has it
    "tidb_events": [
        ("id", _bigint()), ("ts", _vc(20)), ("kind", _vc(32)),
        ("severity", _vc(8)), ("conn_id", _bigint()),
        ("digest", _vc(32)), ("detail", _vc(512)),
    ],
    # per-statement sampling-profiler frames of THIS session's
    # @@profiling ring (reference: INFORMATION_SCHEMA.PROFILING fed by
    # the session profile history)
    "profiling": [
        ("query_id", _bigint()), ("seq", _bigint()),
        ("state", _vc(256)),
        ("duration", FieldType(TypeKind.DOUBLE)),
        ("samples", _bigint()),
    ],
    # rules-driven automated diagnosis (reference: TiDB 4.0's
    # executor/inspection_result.go feeding
    # INFORMATION_SCHEMA.INSPECTION_RESULT / INSPECTION_SUMMARY):
    # every registered rule in tidb_tpu/obs_inspect.py evaluated over
    # the live telemetry planes. Empty — with ZERO rule work — while
    # diagnostics.enabled is false.
    "inspection_result": [
        ("rule", _vc(64)), ("item", _vc(128)), ("severity", _vc(16)),
        ("value", _vc(64)), ("reference", _vc(256)),
        ("details", _vc(512)),
    ],
    # one row per REGISTERED rule: finding count, worst observed
    # severity, sample items — the registry itself, SQL-queryable
    "inspection_summary": [
        ("rule", _vc(64)), ("severity", _vc(16)),
        ("findings", _bigint()), ("items", _vc(256)),
        ("reference", _vc(256)),
    ],
    # keyspace heat plane (obs_heat.py): one row per known range with
    # lifetime served traffic, the live hot ratio vs the fleet median,
    # and the load-based split advisory (reference: PD's hot-region
    # tables behind INFORMATION_SCHEMA.TIDB_HOT_REGIONS). Empty — with
    # zero recorder work — while [heatmap] is disabled.
    "tidb_hot_ranges": [
        ("range_id", _bigint()), ("start_key", _vc(64)),
        ("end_key", _vc(64)), ("read_rows", _bigint()),
        ("read_bytes", _bigint()), ("write_rows", _bigint()),
        ("write_bytes", _bigint()),
        ("hot_ratio", FieldType(TypeKind.DOUBLE)),
        ("hot", _bigint()), ("split_advisory", _vc(64)),
    ],
    # counter/gauge time-series rollup from the MetricsHistory ring
    # (reference: TiDB 4.0's metrics schema summarized into
    # INFORMATION_SCHEMA.METRICS_SUMMARY)
    "metrics_summary": [
        ("metric_name", _vc(160)), ("samples", _bigint()),
        ("min_value", FieldType(TypeKind.DOUBLE)),
        ("avg_value", FieldType(TypeKind.DOUBLE)),
        ("max_value", FieldType(TypeKind.DOUBLE)),
        ("last_value", FieldType(TypeKind.DOUBLE)),
    ],
    # cluster-wide memtables: one sub-request per live member over the
    # diag RPC plane (reference: infoschema/cluster.go CLUSTER_* tables
    # served by executor/memtable_reader.go fan-out). Every table leads
    # with the member's instance address and ends with an error column:
    # an unreachable peer contributes [instance, NULLs..., error] plus a
    # session warning instead of failing the query.
    "cluster_info": [
        ("instance", _vc()), ("type", _vc(16)), ("server_id", _bigint()),
        ("version", _vc()), ("pid", _bigint()), ("start_time", _vc(20)),
        ("uptime_s", FieldType(TypeKind.DOUBLE)),
        # follower read tier: the member's applied/closed timestamp,
        # how far behind the leader it runs, and whether it serves
        # routed replica reads (leaders: newest issued ts / 0 / 0)
        ("applied_ts", _bigint()),
        ("apply_lag_ms", FieldType(TypeKind.DOUBLE)),
        ("serving", _bigint()),
        # range-sharded write leadership: a member hosting range
        # leaders contributes one extra type='range' row per hosted
        # range with these filled (NULL on server rows, and no range
        # rows at all while [ranges] is disabled)
        ("range_id", _bigint()), ("range_leader", _vc()),
        ("range_term", _bigint()), ("range_closed_ts", _bigint()),
        # keyspace heat plane: lifetime traffic served by the hosted
        # range (NULL on server rows; zeros while [heatmap] disabled)
        ("range_read_rows", _bigint()), ("range_read_bytes", _bigint()),
        ("range_write_rows", _bigint()),
        ("range_write_bytes", _bigint()),
        ("error", _vc(256)),
    ],
    "cluster_processlist": [
        ("instance", _vc()), ("id", _bigint()), ("user", _vc()),
        ("host", _vc()), ("db", _vc()), ("command", _vc(16)),
        ("time", _bigint()), ("state", _vc(16)), ("info", _vc(512)),
        ("error", _vc(256)),
    ],
    "cluster_slow_query": [
        ("instance", _vc()), ("time", _vc(20)), ("db", _vc()),
        ("query_time_ms", FieldType(TypeKind.DOUBLE)),
        ("query", _vc(4096)), ("plan_digest", _vc(32)),
        ("stages", _vc(256)), ("mem_max", _bigint()),
        ("spill_count", _bigint()), ("operators", _vc(256)),
        ("mesh_skew", FieldType(TypeKind.DOUBLE)),
        ("wait_profile", _vc(256)),
        ("error", _vc(256)),
    ],
    # cluster-wide mesh flight recorder over the diag RPC fan-out
    "cluster_mesh_shards": [
        ("instance", _vc()), ("digest", _vc(32)), ("kind", _vc(16)),
        ("operator", _vc(64)), ("dispatches", _bigint()),
        ("shards", _bigint()), ("last_shard_rows", _vc(256)),
        ("last_skew", FieldType(TypeKind.DOUBLE)),
        ("max_skew", FieldType(TypeKind.DOUBLE)),
        ("in_rows", _bigint()), ("out_rows", _bigint()),
        ("routed_bytes", _bigint()), ("last_seen", _vc(20)),
        ("error", _vc(256)),
    ],
    "cluster_mesh_storage": [
        ("instance", _vc()), ("device", _vc(64)),
        ("table_name", _vc(64)), ("epoch_id", _bigint()),
        ("kind", _vc(16)), ("arrays", _bigint()), ("bytes", _bigint()),
        ("peak_bytes", _bigint()), ("error", _vc(256)),
    ],
    # cluster-wide Top SQL: every member's attribution windows under
    # one roof, degrading per-peer like the other cluster_* tables
    "cluster_top_sql": [
        ("instance", _vc()), ("window_start", _vc(20)),
        ("digest", _vc(32)), ("digest_text", _vc(512)),
        ("operator", _vc(64)), ("exec_count", _bigint()),
        ("sum_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("op_time_ms", FieldType(TypeKind.DOUBLE)),
        ("op_transfer_bytes", _bigint()), ("stages", _vc(256)),
        ("sum_rows", _bigint()), ("admission_sheds", _bigint()),
        ("governor_kills", _bigint()),
        ("max_shard_share", FieldType(TypeKind.DOUBLE)),
        ("dominant_wait", _vc(64)),
        ("error", _vc(256)),
    ],
    # cluster-wide typed wait attribution over the diag RPC fan-out
    "cluster_tidb_wait_profile": [
        ("instance", _vc()), ("window_start", _vc(20)),
        ("digest", _vc(32)), ("digest_text", _vc(512)),
        ("schema_name", _vc()), ("exec_count", _bigint()),
        ("sum_wall_ms", FieldType(TypeKind.DOUBLE)),
        ("state", _vc(32)),
        ("wait_ms", FieldType(TypeKind.DOUBLE)),
        ("wait_frac", FieldType(TypeKind.DOUBLE)),
        ("error", _vc(256)),
    ],
    "cluster_statements_summary": [
        ("instance", _vc()), ("digest", _vc(32)), ("schema_name", _vc()),
        ("digest_text", _vc(512)), ("query_sample_text", _vc(512)),
        ("exec_count", _bigint()), ("sum_errors", _bigint()),
        ("sum_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("max_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("sum_result_rows", _bigint()), ("last_seen", _vc(20)),
        ("error", _vc(256)),
    ],
    # cluster-wide workload history: every member's rotated windows /
    # plan rollups under one roof, degrading per peer
    "cluster_statements_summary_history": [
        ("instance", _vc()), ("summary_begin_time", _vc(20)),
        ("summary_end_time", _vc(20)), ("digest", _vc(32)),
        ("schema_name", _vc()), ("digest_text", _vc(512)),
        ("plan_digest", _vc(32)), ("engines", _vc(256)),
        ("plan_strategy", _vc(64)), ("exec_count", _bigint()),
        ("sum_errors", _bigint()),
        ("avg_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("max_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("sum_rows", _bigint()), ("stages", _vc(256)),
        ("mesh_skew", FieldType(TypeKind.DOUBLE)),
        ("error", _vc(256)),
    ],
    "cluster_plan_history": [
        ("instance", _vc()), ("digest", _vc(32)),
        ("plan_digest", _vc(32)), ("digest_text", _vc(512)),
        ("engines", _vc(256)), ("plan_strategy", _vc(64)),
        ("windows", _bigint()), ("exec_count", _bigint()),
        ("sum_errors", _bigint()),
        ("avg_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("p50_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("max_latency_ms", FieldType(TypeKind.DOUBLE)),
        ("first_seen", _vc(20)), ("last_seen", _vc(20)),
        ("current_plan", _bigint()), ("error", _vc(256)),
    ],
    # cluster-wide automated diagnosis: every member's inspection
    # findings under one roof, degrading per peer like the other
    # cluster_* tables
    "cluster_inspection_result": [
        ("instance", _vc()), ("rule", _vc(64)), ("item", _vc(128)),
        ("severity", _vc(16)), ("value", _vc(64)),
        ("reference", _vc(256)), ("details", _vc(512)),
        ("error", _vc(256)),
    ],
    # cluster-wide keyspace heat: every member's tidb_hot_ranges under
    # one roof, degrading per peer like the other cluster_* tables
    "cluster_hot_ranges": [
        ("instance", _vc()), ("range_id", _bigint()),
        ("start_key", _vc(64)), ("end_key", _vc(64)),
        ("read_rows", _bigint()), ("read_bytes", _bigint()),
        ("write_rows", _bigint()), ("write_bytes", _bigint()),
        ("hot_ratio", FieldType(TypeKind.DOUBLE)),
        ("hot", _bigint()), ("split_advisory", _vc(64)),
        ("error", _vc(256)),
    ],
    # device/host telemetry per member (live gauges + counters), for
    # correlating dispatch-latency regressions with device-memory
    # pressure across the whole cluster
    "cluster_load": [
        ("instance", _vc()), ("device_type", _vc(16)),
        ("name", _vc(160)), ("value", FieldType(TypeKind.DOUBLE)),
        ("error", _vc(256)),
    ],
    "key_column_usage": [
        ("constraint_catalog", _vc()), ("constraint_schema", _vc()),
        ("constraint_name", _vc()), ("table_catalog", _vc()),
        ("table_schema", _vc()), ("table_name", _vc()),
        ("column_name", _vc()), ("ordinal_position", _bigint()),
        ("position_in_unique_constraint", _bigint()),
        ("referenced_table_schema", _vc()),
        ("referenced_table_name", _vc()),
        ("referenced_column_name", _vc()),
    ],
    "referential_constraints": [
        ("constraint_catalog", _vc()), ("constraint_schema", _vc()),
        ("constraint_name", _vc()),
        ("unique_constraint_schema", _vc()),
        ("update_rule", _vc(16)), ("delete_rule", _vc(16)),
        ("table_name", _vc()), ("referenced_table_name", _vc()),
    ],
    "sequences": [
        ("sequence_schema", _vc()), ("sequence_name", _vc()),
        ("start_value", _bigint()), ("increment", _bigint()),
        ("min_value", _bigint()), ("max_value", _bigint()),
        ("cycle", _bigint()),
    ],
    "partitions": [
        ("table_catalog", _vc()), ("table_schema", _vc()),
        ("table_name", _vc()), ("partition_name", _vc()),
        ("partition_ordinal_position", _bigint()),
        ("partition_method", _vc(16)),
        ("partition_expression", _vc(64)),
        ("partition_description", _vc(32)), ("table_rows", _bigint()),
    ],
    # live connections (reference: infoschema_reader.go PROCESSLIST fed
    # by the server's client connections)
    "processlist": [
        ("id", _bigint()), ("user", _vc()), ("host", _vc()),
        ("db", _vc()), ("command", _vc(16)), ("time", _bigint()),
        ("state", _vc(16)), ("info", _vc(512)),
        # working-set peak of the live (else last) statement + its
        # spill count (reference: TiDB's PROCESSLIST MEM column) — how
        # an operator sees WHICH connection the governor would kill
        ("mem_max", _bigint()), ("spill_count", _bigint()),
    ],
    "views": [
        ("table_catalog", _vc()), ("table_schema", _vc()),
        ("table_name", _vc()), ("view_definition", _vc(1024)),
        ("check_option", _vc(8)), ("is_updatable", _vc(8)),
        ("definer", _vc()), ("security_type", _vc(16)),
    ],
    "user_privileges": [
        ("grantee", _vc()), ("table_catalog", _vc()),
        ("privilege_type", _vc(32)), ("is_grantable", _vc(8)),
    ],
}


def table_names() -> set[str]:
    return set(_DEFS)


def ensure_schema(storage) -> None:
    """Create the information_schema tables once (no data yet)."""
    cat: Catalog = storage.catalog
    if DB_NAME in cat.schemas and \
            all(t in cat.schemas[DB_NAME].tables for t in _DEFS):
        return
    if DB_NAME not in cat.schemas:
        cat.schemas[DB_NAME] = SchemaInfo(DB_NAME)
    schema = cat.schemas[DB_NAME]
    for tname, cols in _DEFS.items():
        if tname in schema.tables:
            continue
        info = TableInfo(
            id=cat.alloc_id(),
            name=tname,
            columns=[ColumnInfo(cat.alloc_id(), cn, ft, offset=i)
                     for i, (cn, ft) in enumerate(cols)],
        )
        schema.tables[tname] = info
        store = storage.register_table(info)
        store.on_epoch = None  # derived data: never persist


def _store_rows(storage, table_id: int) -> int:
    """LIVE row count: a delete/update delta must not count as a row
    (epoch.num_rows + len(deltas) would inflate until compaction)."""
    store = storage.tables.get(table_id)
    if store is None:
        return 0
    if not store.deltas:
        return store.epoch.num_rows
    # current() is read-only: all committed deltas are <= the last
    # issued ts, so no TSO allocation on this read path
    return store.snapshot(storage.tso.current()).num_visible_rows


def _rows_for(storage, catalog: Catalog, tname: str,
              viewer=None) -> list[list]:
    user_schemas = [s for k, s in sorted(catalog.schemas.items())
                    if k != DB_NAME]
    rows: list[list] = []
    if tname == "schemata":
        for s in user_schemas:
            rows.append(["def", s.name, "utf8mb4", "utf8mb4_bin", None])
    elif tname == "tables":
        for s in user_schemas:
            for t in sorted(s.tables.values(), key=lambda t: t.name):
                part = getattr(t, "partition", None)
                if part is not None:
                    nrows = sum(_store_rows(storage, d.id)
                                for d in part.defs)
                else:
                    nrows = _store_rows(storage, t.id)
                rows.append(["def", s.name, t.name, "BASE TABLE", "TiTPU",
                             10, "Fixed", nrows, 0, 0, 0, None,
                             "utf8mb4_bin", "", ""])
            for v in sorted(getattr(s, "views", {}).values(),
                            key=lambda v: v.name):
                # views list here too (MySQL: table_type='VIEW')
                rows.append(["def", s.name, v.name, "VIEW", None, 10,
                             None, None, None, None, None, None, None,
                             "", "VIEW"])
    elif tname == "columns":
        for s in user_schemas:
            for t in sorted(s.tables.values(), key=lambda t: t.name):
                for c in t.columns:
                    ft = c.ftype
                    key = "PRI" if c.is_primary else (
                        "UNI" if any(ix.unique and ix.col_offsets ==
                                     [c.offset] for ix in t.indices) else "")
                    rows.append([
                        "def", s.name, t.name, c.name, c.offset + 1,
                        None if c.default is None else str(c.default),
                        "YES" if c.nullable else "NO",
                        ft.kind.name.lower(),
                        ft.flen if ft.is_string else None,
                        ft.flen if ft.is_decimal else None,
                        ft.scale if ft.is_decimal else None,
                        "utf8mb4" if ft.is_string else None,
                        "utf8mb4_bin" if ft.is_string else None,
                        repr(ft), key,
                        "auto_increment" if c.auto_increment else "", ""])
    elif tname == "statistics":
        for s in user_schemas:
            for t in sorted(s.tables.values(), key=lambda t: t.name):
                for ix in t.indices:
                    if not ix.visible:
                        continue
                    for seq, off in enumerate(ix.col_offsets):
                        rows.append([
                            "def", s.name, t.name,
                            0 if ix.unique or ix.primary else 1,
                            s.name, ix.name, seq + 1,
                            t.columns[off].name, 0, "BTREE"])
    elif tname == "engines":
        rows.append(["InnoDB", "DEFAULT",
                     "TiTPU columnar engine (InnoDB-compatible surface)",
                     "YES", "NO", "NO"])
    elif tname == "collations":
        rows.append(["utf8mb4_bin", "utf8mb4", 46, "Yes", "Yes", 1])
        rows.append(["utf8mb4_general_ci", "utf8mb4", 45, "", "Yes", 1])
    elif tname == "character_sets":
        rows.append(["utf8mb4", "utf8mb4_bin", "UTF-8 Unicode", 4])
    elif tname == "key_column_usage":
        for s in user_schemas:
            for t in sorted(s.tables.values(), key=lambda t: t.name):
                for ix in t.indices:
                    if not (ix.unique or ix.primary):
                        continue
                    cname = "PRIMARY" if ix.primary else ix.name
                    for seq, off in enumerate(ix.col_offsets):
                        rows.append(["def", s.name, cname, "def", s.name,
                                     t.name, t.columns[off].name, seq + 1,
                                     None, None, None, None])
                for fk in getattr(t, "foreign_keys", []) or []:
                    for seq, off in enumerate(fk.col_offsets):
                        ref_col = fk.ref_cols[seq] \
                            if seq < len(fk.ref_cols) else None
                        rows.append(["def", s.name, fk.name, "def",
                                     s.name, t.name, t.columns[off].name,
                                     seq + 1, seq + 1, fk.ref_db,
                                     fk.ref_table, ref_col])
    elif tname == "referential_constraints":
        for s in user_schemas:
            for t in sorted(s.tables.values(), key=lambda t: t.name):
                for fk in getattr(t, "foreign_keys", []) or []:
                    rows.append(["def", s.name, fk.name, fk.ref_db,
                                 fk.on_update, fk.on_delete, t.name,
                                 fk.ref_table])
    elif tname == "sequences":
        for s in user_schemas:
            for seq in sorted((getattr(s, "sequences", {}) or {})
                              .values(), key=lambda x: x.name):
                rows.append([s.name, seq.name, seq.start, seq.increment,
                             seq.min_value, seq.max_value,
                             1 if seq.cycle else 0])
    elif tname == "partitions":
        for s in user_schemas:
            for t in sorted(s.tables.values(), key=lambda t: t.name):
                part = getattr(t, "partition", None)
                if part is None:
                    rows.append(["def", s.name, t.name, None, None,
                                 None, None, None, _store_rows(storage,
                                                               t.id)])
                    continue
                for i, d in enumerate(part.defs):
                    desc = "MAXVALUE" if part.kind == "range" and \
                        d.less_than is None else (
                        str(d.less_than) if part.kind == "range" else "")
                    rows.append([
                        "def", s.name, t.name, d.name, i + 1,
                        part.kind.upper(),
                        t.columns[part.col_offset].name, desc,
                        _store_rows(storage, d.id)])
    elif tname == "statements_summary":
        for e in sorted(storage.obs.statements.snapshot(),
                        key=lambda e: -e["sum_latency_ms"]):
            rows.append([
                e["digest"], e["schema_name"], e["digest_text"],
                e["sample_text"], e["exec_count"], e["errors"],
                round(e["sum_latency_ms"], 3),
                round(e["sum_latency_ms"] / max(e["exec_count"], 1), 3),
                round(e["max_latency_ms"], 3), e["sum_rows"],
                e.get("max_mem_bytes", 0), e.get("sum_spill_count", 0),
                e["first_seen"], e["last_seen"]])
    elif tname == "slow_query":
        # same row shape as cluster_slow_query minus (instance, error):
        # the diag service is the one producer of it
        rows = storage.diag.diag_slow_query()["rows"]
    elif tname == "tidb_top_sql":
        # same producer as the cluster fan-out (minus instance/error)
        rows = storage.diag.diag_top_sql()["rows"]
    elif tname == "tidb_wait_profile":
        rows = storage.diag.diag_wait_profile()["rows"]
    elif tname == "tidb_mesh_shards":
        rows = storage.diag.diag_mesh_shards()["rows"]
    elif tname == "tidb_mesh_storage":
        rows = storage.diag.diag_mesh_storage()["rows"]
    elif tname == "tidb_events":
        rows = storage.diag.diag_events()["rows"]
    elif tname == "tidb_hot_ranges":
        # same producer as the cluster fan-out (minus instance/error)
        rows = storage.diag.diag_hot_ranges()["rows"]
    elif tname == "statements_summary_history":
        # same producer as the cluster fan-out (minus instance/error)
        rows = storage.diag.diag_history()["rows"]
    elif tname == "tidb_plan_history":
        rows = storage.diag.diag_plan_history()["rows"]
    elif tname == "inspection_result":
        # same producer as the cluster fan-out (minus instance/error)
        rows = storage.diag.diag_inspection()["rows"]
        _warn_critical_inspections(rows, viewer)
    elif tname == "inspection_summary":
        from .. import obs_inspect
        rows = obs_inspect.summary_rows(storage)
    elif tname == "metrics_summary":
        hist = getattr(storage, "metrics_history", None)
        if hist is not None:
            # the ring plus a transient point for "now" — a read must
            # not append to (and eventually flush) the time-series
            now = hist.sample_now(record=False)
            for name, st in sorted(hist.summary(extra=now).items()):
                rows.append([name, st["samples"], st["min"], st["avg"],
                             st["max"], st["last"]])
    elif tname in ("cluster_info", "cluster_processlist",
                   "cluster_slow_query", "cluster_statements_summary",
                   "cluster_load", "cluster_top_sql",
                   "cluster_mesh_shards", "cluster_mesh_storage",
                   "cluster_inspection_result",
                   "cluster_statements_summary_history",
                   "cluster_plan_history", "cluster_tidb_wait_profile",
                   "cluster_hot_ranges"):
        from ..rpc import diag as _diag
        rows = _diag.cluster_rows(storage, tname,
                                  len(_DEFS[tname]), viewer)
    elif tname == "profiling":
        for p in (getattr(viewer, "_profiles", None) or []):
            prof = p["profile"]
            for seq, (frame, secs, samples) in enumerate(
                    prof.tree_rows(), 1):
                rows.append([p["query_id"], seq, frame, secs, samples])
    elif tname == "processlist":
        provider = getattr(storage, "processlist", None)
        plist = list(provider()) if provider is not None else []
        if not plist and viewer is not None:
            # embedded session (no wire server): own row, matching the
            # SHOW PROCESSLIST fallback
            import time as _t
            info = viewer.in_flight_sql
            t = int(_t.time() - viewer.in_flight_since)                 if info and viewer.in_flight_since else 0
            live = getattr(viewer, "_live_mem", None)
            plist = [(getattr(viewer, "conn_id", 0) or 0,
                      viewer.user or "root", "localhost",
                      viewer.current_db, "Query", t, "executing", info,
                      int(live.peak_footprint()) if live is not None
                      else int(getattr(viewer, "last_mem_peak", 0)),
                      int(live.spill_count) if live is not None
                      else int(getattr(viewer, "last_spill_count", 0)))]
        if viewer is not None and viewer.user is not None and not                 storage.privileges.check(viewer.user, "PROCESS", "*",
                                         "*", roles=viewer.active_roles):
            # without PROCESS only your own connections are visible
            # (same rule SHOW PROCESSLIST applies)
            plist = [r for r in plist if r[1] == viewer.user]
        for r in plist:
            rows.append([int(r[0]), r[1], r[2], r[3], r[4], int(r[5]),
                         r[6], r[7],
                         int(r[8]) if len(r) > 8 else 0,
                         int(r[9]) if len(r) > 9 else 0])
    elif tname == "views":
        for s in user_schemas:
            for v in sorted(getattr(s, "views", {}).values(),
                            key=lambda v: v.name):
                rows.append(["def", s.name, v.name, v.sql, "NONE", "NO",
                             getattr(v, "definer", "root@%"), "DEFINER"])
    elif tname == "user_privileges":
        pm = storage.privileges
        names = pm.account_names()
        if viewer is not None and viewer.user is not None and not                 pm.check(viewer.user, "ALL", "*", "*",
                         roles=viewer.active_roles):
            # non-admins see their own grants only (MySQL scopes this
            # to accounts the caller can administer)
            names = [n for n in names if n == viewer.user]
        for name in names:
            globals_ = [p for p, db, tbl in pm.grants_for(name)
                        if db == "*" and tbl == "*"]
            if "ALL" in globals_:
                # MySQL expands ALL into one row per privilege
                from ..session.privileges import PRIVS
                globals_ = sorted(PRIVS - {"ALL", "USAGE"})
            for p in (globals_ or ["USAGE"]):
                rows.append([f"'{name}'@'%'", "def", p, "NO"])
    return rows


def publish_store(storage, info: TableInfo, rows: list[list]) -> None:
    """Build a fresh memtable store COMPLETELY from `rows`, then publish
    in one assignment — concurrent readers either see the old rows or
    the new ones, never an empty/missing table mid-refresh. Shared by
    the information_schema and metrics_schema refresh paths."""
    from ..store.table_store import TableStore

    store = TableStore(info)
    store.on_epoch = None
    n = len(rows)
    columns: list[np.ndarray] = []
    valids: list = []
    for ci, c in enumerate(info.columns):
        ft = c.ftype
        data = np.zeros(n, dtype=ft.np_dtype)
        valid = np.ones(n, dtype=bool)
        d = store.dictionaries[ci]
        for ri, row in enumerate(rows):
            v = row[ci]
            if v is None:
                valid[ri] = False
            elif d is not None:
                data[ri] = d.encode(str(v))
            else:
                data[ri] = v
        columns.append(data)
        valids.append(None if valid.all() else valid)
    store.bulk_load(columns, valids)
    storage.tables[info.id] = store  # atomic publish


def _warn_critical_inspections(rows: list[list], viewer) -> None:
    """Critical inspection findings ALSO land in SHOW WARNINGS so the
    operator who just SELECTed sees the red ones without re-filtering."""
    if viewer is None or not hasattr(viewer, "add_warning"):
        return
    for r in rows:
        if r[2] == "critical":
            viewer.add_warning(
                f"inspection: {r[0]} critical on {r[1]} "
                f"({r[5][:160]})")


def refresh(storage, names: set[str], viewer=None) -> None:
    """Rebuild the named information_schema stores from the live catalog.
    `viewer` is the reading Session for the tables whose contents are
    per-viewer (PROCESSLIST visibility, USER_PRIVILEGES scope)."""
    ensure_schema(storage)
    cat: Catalog = storage.catalog
    schema = cat.schemas[DB_NAME]

    # a statement touching BOTH inspection tables gets one rule run
    # (and one edge-trigger update) shared by the pair — the tables it
    # reads must agree, and the snapshot build is not free
    precomputed: dict[str, list[list]] = {}
    if {"inspection_result", "inspection_summary"} <= names:
        from .. import obs_inspect
        res_rows, sum_rows = obs_inspect.result_and_summary_rows(storage)
        precomputed["inspection_result"] = res_rows
        precomputed["inspection_summary"] = sum_rows
        _warn_critical_inspections(res_rows, viewer)

    for tname in names:
        if tname not in _DEFS:
            continue
        info = schema.tables[tname]
        rows = precomputed.get(tname)
        if rows is None:
            rows = _rows_for(storage, cat, tname, viewer)
        publish_store(storage, info, rows)
