"""metrics_schema: every registered metric family as a SQL memtable.

Counterpart of the reference's metrics schema (reference: TiDB 4.0's
infoschema/metrics_schema.go — a `metrics_schema` database with one
virtual table per metric, each reading the Prometheus time series so
operators and the inspection rules share ONE query surface). The
embedded analog reads its own registries: every counter/gauge family
registered in the server registry or the process-wide registry becomes
a table named after the family, whose rows are the bounded
MetricsHistory ring (time-range) plus one live sample taken at read
time (point-in-time).

    SELECT time, labels, value FROM metrics_schema.tidb_queries_total;
    SELECT max(value) FROM metrics_schema.tidb_process_rss_bytes;

Row shape per table: (time, ts, labels, value) — `labels` is the
flattened label part of the sample ('stage="kernel"', '' when
unlabeled), so one table serves every series of its family.
Histograms stay on /metrics only, exactly like MetricsHistory's
flat_samples. Tables never persist (derived data) and rebuild on
demand like the information_schema memtables.
"""

from __future__ import annotations

import time

from .. import obs
from ..types.field_type import FieldType, TypeKind
from .schema import Catalog, ColumnInfo, SchemaInfo, TableInfo

DB_NAME = "metrics_schema"

_COLS = [
    ("time", FieldType(TypeKind.VARCHAR, flen=20)),
    ("ts", FieldType(TypeKind.DOUBLE)),
    ("labels", FieldType(TypeKind.VARCHAR, flen=160)),
    ("value", FieldType(TypeKind.DOUBLE)),
]


def _registries(storage) -> list:
    return [storage.obs.metrics, obs.PROCESS_METRICS]


def families(storage) -> dict[str, str]:
    """Live counter/gauge families -> help text (the table universe).
    Registration order is preserved; cross-registry duplicates are a
    lint error upstream (obs.lint_metrics), first one wins here."""
    fams: dict[str, str] = {}
    for reg in _registries(storage):
        with reg._lock:
            metrics = list(reg._metrics.values())
        for m in metrics:
            if isinstance(m, (obs.Counter, obs.Gauge)) \
                    and m.name not in fams:
                fams[m.name] = m.help
    return fams


def ensure_schema(storage) -> None:
    """Create the metrics_schema database and one table per live
    metric family. Idempotent and incremental: families registered
    after the first call get their tables on the next one. Catalog
    mutation runs under storage.infoschema_lock — unlike the
    information_schema's one-shot ensure, this check-then-insert
    re-opens every time a family registers, and two first-touch
    sessions racing alloc_id would alias two families onto one table
    id."""
    cat: Catalog = storage.catalog
    with storage.infoschema_lock:
        if DB_NAME not in cat.schemas:
            cat.schemas[DB_NAME] = SchemaInfo(DB_NAME)
        schema = cat.schemas[DB_NAME]
        for fam in families(storage):
            if fam in schema.tables:
                continue
            info = TableInfo(
                id=cat.alloc_id(),
                name=fam,
                columns=[ColumnInfo(cat.alloc_id(), cn, ft, offset=i)
                         for i, (cn, ft) in enumerate(_COLS)],
            )
            schema.tables[fam] = info
            store = storage.register_table(info)
            store.on_epoch = None  # derived data: never persist


def _rows_for(storage, family: str) -> list[list]:
    """The family's time-range rows (every MetricsHistory ring point)
    plus one live point-in-time sample — oldest first, the live point
    last. The read never mutates the ring (sample_now(record=False))."""
    hist = storage.metrics_history
    points = hist.snapshot()
    points.append(hist.sample_now(record=False))
    rows: list[list] = []
    for ent in points:
        ts = float(ent["ts"])
        when = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))
        for name, v in ent["values"].items():
            labels = obs.split_sample_name(name, family)
            if labels is None:
                continue
            rows.append([when, round(ts, 3), labels, float(v)])
    return rows


def refresh(storage, names: set[str]) -> None:
    """Rebuild the named metrics_schema stores (the per-statement hook
    session._refresh_infoschema drives, exactly like information_schema;
    unknown names fall through to the planner's normal 'table doesn't
    exist')."""
    ensure_schema(storage)
    from .infoschema import publish_store

    schema = storage.catalog.schemas[DB_NAME]
    for tname in names:
        info = schema.tables.get(tname)
        if info is None:
            continue
        publish_store(storage, info, _rows_for(storage, tname))


def lint(storage) -> list[str]:
    """Hygiene for the metrics_schema tier (tier-1 via
    tests/test_metric_lint.py): every table maps to a live registered
    counter/gauge family — a dangling table would serve empty rows
    forever and read as 'metric gone' instead of 'table stale'."""
    findings: list[str] = []
    schema = storage.catalog.schemas.get(DB_NAME)
    if schema is None:
        return findings
    fams = families(storage)
    for tname in schema.tables:
        if tname not in fams:
            findings.append(
                f"metrics_schema.{tname}: no live registered metric "
                "family backs this table (dangling)")
    return findings


__all__ = ["DB_NAME", "families", "ensure_schema", "refresh", "lint"]
