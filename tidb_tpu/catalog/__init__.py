from .schema import ColumnInfo, TableInfo, SchemaInfo, Catalog

__all__ = ["ColumnInfo", "TableInfo", "SchemaInfo", "Catalog"]
