"""SQL lexer for the MySQL-compatible subset.

Counterpart of the reference's goyacc-generated lexer in the external parser
module (reference: github.com/pingcap/parser, entry session/session.go:1190).
Hand-written: the grammar subset doesn't warrant a generator, and error
messages stay precise.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterator, Optional


class LexError(Exception):
    errno = 1064  # ER_PARSE_ERROR
    sqlstate = "42000"

    def __init__(self, msg: str, pos: int) -> None:
        super().__init__(f"{msg} at position {pos}")
        self.pos = pos


class TokenKind(enum.Enum):
    IDENT = "ident"
    KEYWORD = "keyword"
    INT = "int"
    DECIMAL = "decimal"  # numeric literal with a fractional part
    FLOAT = "float"  # scientific notation -> double
    STRING = "string"
    OP = "op"
    HINT = "hint"  # /*+ ... */ optimizer hint; text = inner content
    EOF = "eof"


@dataclass(frozen=True)
class Token:
    kind: TokenKind
    text: str  # keywords normalized to upper, idents as written
    pos: int

    def is_kw(self, *names: str) -> bool:
        return self.kind == TokenKind.KEYWORD and self.text in names

    def is_op(self, *ops: str) -> bool:
        return self.kind == TokenKind.OP and self.text in ops


# Reserved + non-reserved words the parser dispatches on. Anything else is an
# identifier. (MySQL has non-reserved keywords usable as idents; the parser
# handles the few cases that matter via expect_ident_or_kw.)
KEYWORDS = frozenset(
    """
    SELECT FROM WHERE GROUP BY HAVING ORDER LIMIT OFFSET AS DISTINCT ALL
    AND OR NOT XOR IS NULL TRUE FALSE IN BETWEEN LIKE EXISTS
    JOIN INNER LEFT RIGHT FULL OUTER CROSS ON USING
    INSERT INTO VALUES UPDATE SET DELETE REPLACE
    CREATE TABLE DATABASE SCHEMA DROP ALTER ADD COLUMN INDEX KEY PRIMARY
    UNIQUE DEFAULT AUTO_INCREMENT IF EXISTS USE
    BEGIN START TRANSACTION COMMIT ROLLBACK PESSIMISTIC OPTIMISTIC
    EXPLAIN ANALYZE SHOW TABLES DATABASES DESC DESCRIBE TRACE
    ASC CASE WHEN THEN ELSE END CAST AS CONVERT
    INTERVAL DATE TIME TIMESTAMP DATETIME YEAR
    UNION EXCEPT INTERSECT
    COUNT SUM AVG MIN MAX
    TINYINT SMALLINT INT INTEGER BIGINT FLOAT DOUBLE REAL DECIMAL NUMERIC
    CHAR VARCHAR TEXT BOOLEAN BOOL
    DIV MOD
    FIRST AFTER MODIFY CHANGE RENAME TO TRUNCATE
    GLOBAL SESSION VARIABLES STATUS SCHEMAS WARNINGS ERRORS ENGINES
    COLLATION COLUMNS FIELDS INDEXES KEYS NAMES
    GRANT REVOKE USER IDENTIFIED PRIVILEGES GRANTS
    CONSTRAINT FOREIGN REFERENCES
    FOR
    ADMIN DDL JOBS KILL QUERY CONNECTION
    OVER PARTITION ROWS RANGE UNBOUNDED PRECEDING FOLLOWING CURRENT ROW
    LOAD DATA LOCAL INFILE OUTFILE TERMINATED ENCLOSED ESCAPED LINES IGNORE
    OPTIONALLY CHECK
    """.split()
)

_MULTI_OPS = ("<=>", "->>", "->", "<<", ">>", "<>", "!=", "<=", ">=",
              ":=", "||", "&&")
_SINGLE_OPS = "+-*/%(),.;=<>!&|^~@?"


class Lexer:
    def __init__(self, text: str) -> None:
        self.text = text
        self.pos = 0

    def tokens(self) -> Iterator[Token]:
        while True:
            tok = self.next_token()
            yield tok
            if tok.kind == TokenKind.EOF:
                return

    # ------------------------------------------------------------------
    def next_token(self) -> Token:
        self._skip_ws_and_comments()
        text, pos = self.text, self.pos
        if pos >= len(text):
            return Token(TokenKind.EOF, "", pos)
        c = text[pos]

        if text.startswith("/*+", pos):
            # optimizer hint comment survives as a token (reference: the
            # parser yields hints to planner/core/hints.go)
            end = text.find("*/", pos + 3)
            if end < 0:
                raise LexError("unterminated hint comment", pos)
            self.pos = end + 2
            return Token(TokenKind.HINT, text[pos + 3:end].strip(), pos)
        if c.isdigit() or (c == "." and pos + 1 < len(text) and text[pos + 1].isdigit()):
            return self._number()
        if c in "bB" and pos + 1 < len(text) and text[pos + 1] == "'":
            # bit literal b'0101' -> integer token (reference: parser
            # BitValueLit)
            end = text.find("'", pos + 2)
            if end < 0:
                raise LexError("unterminated bit literal", pos)
            bits = text[pos + 2:end]
            if bits and not set(bits) <= {"0", "1"}:
                raise LexError(f"invalid bit literal b'{bits}'", pos)
            self.pos = end + 1
            return Token(TokenKind.INT, str(int(bits or "0", 2)), pos)
        if c.isalpha() or c == "_":
            return self._word()
        if c == "`":
            return self._quoted_ident()
        if c in "'\"":
            return self._string(c)
        for op in _MULTI_OPS:
            if text.startswith(op, pos):
                self.pos += len(op)
                return Token(TokenKind.OP, op, pos)
        if c in _SINGLE_OPS:
            self.pos += 1
            return Token(TokenKind.OP, c, pos)
        raise LexError(f"unexpected character {c!r}", pos)

    # ------------------------------------------------------------------
    def _skip_ws_and_comments(self) -> None:
        text = self.text
        while self.pos < len(text):
            c = text[self.pos]
            if c.isspace():
                self.pos += 1
            elif text.startswith("--", self.pos) and (
                self.pos + 2 >= len(text) or text[self.pos + 2] in " \t\n"
            ):
                nl = text.find("\n", self.pos)
                self.pos = len(text) if nl < 0 else nl + 1
            elif c == "#":
                nl = text.find("\n", self.pos)
                self.pos = len(text) if nl < 0 else nl + 1
            elif text.startswith("/*", self.pos) and not text.startswith(
                    "/*+", self.pos):
                end = text.find("*/", self.pos + 2)
                if end < 0:
                    raise LexError("unterminated comment", self.pos)
                self.pos = end + 2
            else:
                return

    def _number(self) -> Token:
        text, start = self.text, self.pos
        i = start
        while i < len(text) and text[i].isdigit():
            i += 1
        is_decimal = False
        if i < len(text) and text[i] == ".":
            is_decimal = True
            i += 1
            while i < len(text) and text[i].isdigit():
                i += 1
        is_float = False
        if i < len(text) and text[i] in "eE":
            j = i + 1
            if j < len(text) and text[j] in "+-":
                j += 1
            if j < len(text) and text[j].isdigit():
                is_float = True
                i = j
                while i < len(text) and text[i].isdigit():
                    i += 1
        self.pos = i
        lit = text[start:i]
        if is_float:
            return Token(TokenKind.FLOAT, lit, start)
        if is_decimal:
            return Token(TokenKind.DECIMAL, lit, start)
        return Token(TokenKind.INT, lit, start)

    def _word(self) -> Token:
        text, start = self.text, self.pos
        i = start
        while i < len(text) and (text[i].isalnum() or text[i] == "_"):
            i += 1
        self.pos = i
        word = text[start:i]
        upper = word.upper()
        if upper in KEYWORDS:
            return Token(TokenKind.KEYWORD, upper, start)
        return Token(TokenKind.IDENT, word, start)

    def _quoted_ident(self) -> Token:
        text, start = self.text, self.pos
        i = start + 1
        out = []
        while i < len(text):
            if text[i] == "`":
                if i + 1 < len(text) and text[i + 1] == "`":
                    out.append("`")
                    i += 2
                    continue
                self.pos = i + 1
                return Token(TokenKind.IDENT, "".join(out), start)
            out.append(text[i])
            i += 1
        raise LexError("unterminated quoted identifier", start)

    def _string(self, quote: str) -> Token:
        text, start = self.text, self.pos
        i = start + 1
        out = []
        while i < len(text):
            c = text[i]
            if c == quote:
                if i + 1 < len(text) and text[i + 1] == quote:
                    out.append(quote)
                    i += 2
                    continue
                self.pos = i + 1
                return Token(TokenKind.STRING, "".join(out), start)
            if c == "\\" and i + 1 < len(text):
                nxt = text[i + 1]
                mapped = {"n": "\n", "t": "\t", "r": "\r", "0": "\0",
                          "\\": "\\", "'": "'", '"': '"', "%": "\\%", "_": "\\_"}
                out.append(mapped.get(nxt, nxt))
                i += 2
                continue
            out.append(c)
            i += 1
        raise LexError("unterminated string literal", start)
