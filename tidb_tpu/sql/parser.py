"""Recursive-descent / Pratt parser for the MySQL-compatible subset.

Counterpart of the reference's external goyacc parser (reference:
github.com/pingcap/parser; used via session.ParseSQL, session/session.go:1190).
Covers the surface needed by TPC-H/SSB/ClickBench-style analytics plus DML,
DDL, txn control, EXPLAIN/SHOW — widened as the framework grows.
"""

from __future__ import annotations

from typing import Optional

from ..types.field_type import FieldType, TypeKind
from ..types.value import Decimal
from . import ast
from .lexer import Lexer, Token, TokenKind

# Binary operator precedence (higher binds tighter), MySQL order.
_PRECEDENCE = {
    "OR": 1, "||": 1,
    "XOR": 2,
    "AND": 3, "&&": 3,
    # 4 reserved for NOT (prefix, handled separately)
    "=": 5, "<=>": 5, "<>": 5, "!=": 5, "<": 5, "<=": 5, ">": 5, ">=": 5,
    "|": 6,
    "&": 7,
    "<<": 8, ">>": 8,
    "+": 9, "-": 9,
    "*": 10, "/": 10, "DIV": 10, "%": 10, "MOD": 10,
    "^": 11,
}

_COMPARISON_LEVEL = 5

_AGG_FUNCS = frozenset({"COUNT", "SUM", "AVG", "MIN", "MAX"})

_TYPE_KEYWORDS = {
    "TINYINT": TypeKind.TINYINT,
    "SMALLINT": TypeKind.SMALLINT,
    "INT": TypeKind.INT,
    "INTEGER": TypeKind.INT,
    "BIGINT": TypeKind.BIGINT,
    "FLOAT": TypeKind.FLOAT,
    "DOUBLE": TypeKind.DOUBLE,
    "REAL": TypeKind.DOUBLE,
    "DECIMAL": TypeKind.DECIMAL,
    "NUMERIC": TypeKind.DECIMAL,
    "DATE": TypeKind.DATE,
    "DATETIME": TypeKind.DATETIME,
    "TIMESTAMP": TypeKind.TIMESTAMP,
    "CHAR": TypeKind.CHAR,
    "VARCHAR": TypeKind.VARCHAR,
    "TEXT": TypeKind.TEXT,
    "BOOLEAN": TypeKind.BOOLEAN,
    "BOOL": TypeKind.BOOLEAN,
    "YEAR": TypeKind.YEAR,
}


class ParseError(Exception):
    errno = 1064  # ER_PARSE_ERROR (tidb_tpu/errno.py; avoids the import)
    sqlstate = "42000"

    def __init__(self, msg: str, token: Token) -> None:
        where = f"near {token.text!r}" if token.text else "at end of input"
        super().__init__(f"{msg} {where} (pos {token.pos})")
        self.token = token


class Parser:
    def __init__(self, text: str) -> None:
        self.text = text
        toks = list(Lexer(text).tokens())
        # optimizer hints are meaningful only right after SELECT; stray
        # hint comments elsewhere degrade to plain comments (MySQL does
        # the same — hints in unsupported positions are ignored)
        self.toks = [
            t for i, t in enumerate(toks)
            if t.kind != TokenKind.HINT
            or (i > 0 and toks[i - 1].is_kw("SELECT"))
        ]
        self.i = 0

    # ---- token helpers -----------------------------------------------------
    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def peek(self, n: int = 1) -> Token:
        j = min(self.i + n, len(self.toks) - 1)
        return self.toks[j]

    def advance(self) -> Token:
        t = self.toks[self.i]
        if t.kind != TokenKind.EOF:
            self.i += 1
        return t

    def accept_kw(self, *names: str) -> Optional[Token]:
        if self.cur.is_kw(*names):
            return self.advance()
        return None

    def accept_op(self, *ops: str) -> Optional[Token]:
        if self.cur.is_op(*ops):
            return self.advance()
        return None

    def expect_kw(self, *names: str) -> Token:
        t = self.accept_kw(*names)
        if t is None:
            raise ParseError(f"expected {'/'.join(names)}", self.cur)
        return t

    def expect_op(self, op: str) -> Token:
        t = self.accept_op(op)
        if t is None:
            raise ParseError(f"expected {op!r}", self.cur)
        return t

    def expect_ident(self) -> str:
        """Identifier; unreserved-ish keywords double as identifiers."""
        t = self.cur
        if t.kind == TokenKind.IDENT:
            self.advance()
            return t.text
        if t.kind == TokenKind.KEYWORD and t.text in _IDENT_KEYWORDS:
            self.advance()
            return t.text.lower()
        raise ParseError("expected identifier", t)

    # ---- entry -------------------------------------------------------------
    def parse(self) -> list[ast.Stmt]:
        stmts: list[ast.Stmt] = []
        while True:
            while self.accept_op(";"):
                pass
            if self.cur.kind == TokenKind.EOF:
                return stmts
            stmts.append(self.parse_statement())
            if self.cur.kind != TokenKind.EOF:
                self.expect_op(";")

    param_count: int = 0

    def parse_statement(self) -> ast.Stmt:
        t = self.cur
        if t.is_kw("SELECT"):
            return self.parse_select_statement()
        if t.is_kw("INSERT", "REPLACE"):
            return self.parse_insert()
        if t.is_kw("UPDATE"):
            return self.parse_update()
        if t.is_kw("DELETE"):
            return self.parse_delete()
        if t.is_kw("CREATE"):
            return self.parse_create()
        if t.is_kw("DROP"):
            return self.parse_drop()
        if t.is_kw("TRUNCATE"):
            self.advance()
            self.accept_kw("TABLE")
            return ast.TruncateTableStmt(self.parse_table_name())
        if t.is_kw("USE"):
            self.advance()
            return ast.UseStmt(self.expect_ident())
        if t.is_kw("BEGIN"):
            self.advance()
            mode = ""
            m = self.accept_kw("PESSIMISTIC", "OPTIMISTIC")
            if m is not None:
                mode = m.text
            return ast.BeginStmt(mode)
        if t.is_kw("START"):
            self.advance()
            self.expect_kw("TRANSACTION")
            return ast.BeginStmt()
        if t.is_kw("COMMIT"):
            self.advance()
            return ast.CommitStmt()
        if t.is_kw("ROLLBACK"):
            self.advance()
            return ast.RollbackStmt()
        if t.is_kw("EXPLAIN", "DESC", "DESCRIBE"):
            return self.parse_explain()
        if t.is_kw("TRACE"):
            self.advance()
            return ast.TraceStmt(self.parse_statement())
        if t.is_kw("KILL"):
            self.advance()
            query_only = self.accept_kw("QUERY") is not None
            if not query_only:
                self.accept_kw("CONNECTION")
            tok = self.cur
            self.advance()
            try:
                cid = int(tok.text)
            except ValueError:
                raise ParseError("expected connection id after KILL", tok)
            return ast.KillStmt(cid, query_only)
        if t.is_kw("SHOW"):
            return self.parse_show()
        if t.is_kw("SET"):
            return self.parse_set()
        if t.is_kw("ANALYZE"):
            self.advance()
            self.expect_kw("TABLE")
            tables = [self.parse_table_name()]
            while self.accept_op(","):
                tables.append(self.parse_table_name())
            return ast.AnalyzeTableStmt(tables)
        if t.is_kw("ALTER"):
            return self.parse_alter()
        if t.is_kw("RENAME"):
            self.advance()
            if self.accept_kw("USER"):
                pairs = []
                while True:
                    old = self._parse_account_name()
                    self.expect_kw("TO")
                    pairs.append((old, self._parse_account_name()))
                    if not self.accept_op(","):
                        break
                return ast.RenameUserStmt(pairs)
            self.expect_kw("TABLE")
            renames = []
            while True:
                old = self.parse_table_name()
                self.expect_kw("TO")
                renames.append((old, self.parse_table_name()))
                if not self.accept_op(","):
                    break
            return ast.RenameTableStmt(renames)
        if t.is_kw("ADMIN"):
            self.advance()
            if self.accept_kw("CHECK"):
                self.expect_kw("TABLE")
                tables = [self.parse_table_name()]
                while self.accept_op(","):
                    tables.append(self.parse_table_name())
                return ast.AdminStmt("CHECK_TABLE", tables)
            self.expect_kw("SHOW")
            self.expect_kw("DDL")
            self.expect_kw("JOBS")
            return ast.AdminStmt("SHOW_DDL_JOBS")
        if t.is_kw("LOAD"):
            return self.parse_load_data()
        if t.kind == TokenKind.IDENT and t.text.upper() == "CHECKSUM":
            self.advance()
            self.expect_kw("TABLE")
            tables = [self.parse_table_name()]
            while self.accept_op(","):
                tables.append(self.parse_table_name())
            return ast.ChecksumTableStmt(tables)
        if t.is_kw("GRANT", "REVOKE"):
            return self.parse_grant(revoke=t.is_kw("REVOKE"))
        raise ParseError("unsupported statement", t)

    def _string_lit(self, what: str) -> str:
        t = self.cur
        if t.kind != TokenKind.STRING:
            raise ParseError(f"expected string literal for {what}", t)
        self.advance()
        return t.text

    def _parse_file_format(self, path: str) -> "ast.FileFormat":
        """[FIELDS|COLUMNS TERMINATED BY s [OPTIONALLY] ENCLOSED BY s
        ESCAPED BY s] [LINES TERMINATED BY s] — shared by LOAD DATA and
        SELECT INTO OUTFILE (MySQL defaults: tab fields, newline lines)."""
        fmt = ast.FileFormat(path)
        if self.accept_kw("FIELDS", "COLUMNS"):
            seen = False
            while True:
                if self.accept_kw("TERMINATED"):
                    self.expect_kw("BY")
                    fmt.field_term = self._string_lit("TERMINATED BY")
                    if not fmt.field_term:
                        raise ParseError(
                            "FIELDS TERMINATED BY must not be empty",
                            self.cur)
                elif self.cur.is_kw("OPTIONALLY") or \
                        self.cur.is_kw("ENCLOSED"):
                    self.accept_kw("OPTIONALLY")
                    self.expect_kw("ENCLOSED")
                    self.expect_kw("BY")
                    fmt.enclosed = self._string_lit("ENCLOSED BY")
                elif self.accept_kw("ESCAPED"):
                    self.expect_kw("BY")
                    fmt.escaped = self._string_lit("ESCAPED BY")
                else:
                    if not seen:
                        raise ParseError("expected TERMINATED/ENCLOSED/"
                                         "ESCAPED BY", self.cur)
                    break
                seen = True
        if self.accept_kw("LINES"):
            self.expect_kw("TERMINATED")
            self.expect_kw("BY")
            fmt.line_term = self._string_lit("LINES TERMINATED BY")
            if not fmt.line_term:
                raise ParseError(
                    "LINES TERMINATED BY must not be empty", self.cur)
        return fmt

    def parse_load_data(self) -> ast.LoadDataStmt:
        """LOAD DATA [LOCAL] INFILE 'path' [REPLACE|IGNORE] INTO TABLE t
        [format] [IGNORE n LINES] [(col, ...)]
        (reference: executor/load_data.go)."""
        self.expect_kw("LOAD")
        self.expect_kw("DATA")
        local = bool(self.accept_kw("LOCAL"))
        self.expect_kw("INFILE")
        path = self._string_lit("INFILE")
        dup = "error"
        if self.accept_kw("REPLACE"):
            dup = "replace"
        elif self.accept_kw("IGNORE"):
            dup = "ignore"
        self.expect_kw("INTO")
        self.expect_kw("TABLE")
        table = self.parse_table_name()
        fmt = self._parse_file_format(path)
        ignore_lines = 0
        if self.accept_kw("IGNORE"):
            ignore_lines = self.parse_uint("IGNORE")
            self.expect_kw("LINES")
        columns = None
        if self.accept_op("("):
            columns = [self.expect_ident()]
            while self.accept_op(","):
                columns.append(self.expect_ident())
            self.expect_op(")")
        return ast.LoadDataStmt(table, fmt, columns, local, dup,
                                ignore_lines)

    def parse_grant(self, revoke: bool) -> ast.Stmt:
        """GRANT/REVOKE priv[, priv] ON [db.]tbl TO/FROM user
        (reference: privilege checks fed by mysql.user/db/tables_priv)."""
        self.advance()  # GRANT / REVOKE
        privs: list[str] = []
        priv_cols: list = []
        role_names: list[str] = []
        while True:
            if self.accept_kw("ALL"):
                self.accept_kw("PRIVILEGES")
                privs.append("ALL")
                priv_cols.append(None)
                role_names = []  # ALL can't be a role name
            else:
                if self.cur.kind in (TokenKind.STRING, TokenKind.IDENT):
                    role_names.append(self.cur.text)
                else:
                    role_names = []
                t = self.advance()
                privs.append(t.text.upper())
                if self.cur.is_op("("):
                    # column-scoped privilege: GRANT SELECT (a, b) ON t
                    priv_cols.append(self._paren_ident_list())
                    role_names = []
                else:
                    priv_cols.append(None)
                if self.cur.is_op("@"):
                    # 'role'@'host' account form (what SHOW GRANTS
                    # emits); host accepted and discarded (single-host)
                    self.advance()
                    self.advance()
            if not self.accept_op(","):
                break
        # GRANT role[, ...] TO user / REVOKE role FROM user: no ON clause
        if len(role_names) == len(privs) and (
                self.cur.is_kw("FROM") if revoke else self.cur.is_kw("TO")):
            self.advance()
            users = [self._parse_account_name()]
            while self.accept_op(","):
                users.append(self._parse_account_name())
            return ast.GrantRoleStmt(role_names, users, revoke)
        self.expect_kw("ON")
        db = tbl = "*"
        if self.accept_op("*"):
            if self.accept_op("."):
                self.expect_op("*")
        else:
            first = self.expect_ident()
            if self.accept_op("."):
                db = first
                tbl = "*" if self.accept_op("*") else self.expect_ident()
            else:
                # unqualified table scopes to the CURRENT database (MySQL
                # semantics) — resolved at execution, marked "" here
                db = ""
                tbl = first
        self.expect_kw("FROM" if revoke else "TO")
        user = self._parse_account_name()
        return ast.GrantStmt(privs, db, tbl, user, revoke, priv_cols)

    def parse_alter(self) -> ast.Stmt:
        self.expect_kw("ALTER")
        if self.accept_kw("USER"):
            if_exists = self._if_exists()
            name = self._parse_account_name()
            self.expect_kw("IDENTIFIED")
            self.expect_kw("BY")
            pwd = self._string_lit("IDENTIFIED BY")
            return ast.AlterUserStmt(name, pwd, if_exists)
        self.expect_kw("TABLE")
        table = self.parse_table_name()
        specs: list[ast.AlterSpec] = []
        while True:
            if self.accept_kw("ADD"):
                if self.cur.is_kw("PRIMARY"):
                    self.advance()
                    self.expect_kw("KEY")
                    specs.append(ast.AlterSpec(
                        "add_index",
                        index=ast.IndexDef("PRIMARY", self._paren_ident_list(),
                                           unique=True, primary=True)))
                elif self.cur.is_kw("UNIQUE"):
                    self.advance()
                    self.accept_kw("KEY", "INDEX")
                    name = self._opt_index_name()
                    specs.append(ast.AlterSpec(
                        "add_index",
                        index=ast.IndexDef(name, self._paren_ident_list(),
                                           unique=True)))
                elif self.cur.is_kw("KEY", "INDEX"):
                    self.advance()
                    name = self._opt_index_name()
                    specs.append(ast.AlterSpec(
                        "add_index",
                        index=ast.IndexDef(name, self._paren_ident_list())))
                else:
                    self.accept_kw("COLUMN")
                    specs.append(ast.AlterSpec(
                        "add_column", column=self.parse_column_def()))
            elif self.accept_kw("DROP"):
                if self.cur.is_kw("KEY", "INDEX"):
                    self.advance()
                    specs.append(ast.AlterSpec("drop_index",
                                               name=self.expect_ident()))
                elif self.cur.is_kw("PARTITION"):
                    self.advance()
                    specs.append(ast.AlterSpec("drop_partition",
                                               name=self.expect_ident()))
                else:
                    self.accept_kw("COLUMN")
                    specs.append(ast.AlterSpec("drop_column",
                                               name=self.expect_ident()))
            elif self.accept_kw("TRUNCATE"):
                self.expect_kw("PARTITION")
                specs.append(ast.AlterSpec("truncate_partition",
                                           name=self.expect_ident()))
            elif self.accept_kw("MODIFY"):
                self.accept_kw("COLUMN")
                specs.append(ast.AlterSpec(
                    "modify_column", column=self.parse_column_def()))
            elif self.accept_kw("RENAME"):
                self.accept_kw("TO", "AS")
                specs.append(ast.AlterSpec("rename",
                                           name=self.expect_ident()))
            else:
                raise ParseError("unsupported ALTER action", self.cur)
            if not self.accept_op(","):
                break
        return ast.AlterTableStmt(table, specs)

    # ---- SELECT ------------------------------------------------------------
    def parse_select_statement(self) -> ast.Stmt:
        """SELECT ... [UNION [ALL] SELECT ...]*; a trailing ORDER BY/LIMIT
        binds to the union (reference: parser union list grammar)."""
        first = self.parse_select()
        if not self.cur.is_kw("UNION"):
            return first
        selects = [first]
        alls: list[bool] = []
        while self.accept_kw("UNION"):
            if selects[-1].order_by or selects[-1].limit is not None:
                raise ParseError(
                    "incorrect usage of UNION and ORDER BY/LIMIT "
                    "(parenthesize the SELECT)", self.cur)
            is_all = bool(self.accept_kw("ALL"))
            if not is_all:
                self.accept_kw("DISTINCT")
            selects.append(self.parse_select())
            alls.append(is_all)
        # the trailing ORDER BY/LIMIT/INTO OUTFILE was consumed by the
        # last SELECT; it belongs to the union
        last = selects[-1]
        stmt = ast.SetOpStmt(selects, alls, last.order_by, last.limit,
                             last.offset)
        stmt.into_outfile = last.into_outfile
        last.order_by, last.limit, last.offset = [], None, 0
        last.into_outfile = None
        return stmt

    def parse_select(self) -> ast.SelectStmt:
        self.expect_kw("SELECT")
        hints: list[tuple[str, list[str]]] = []
        if self.cur.kind == TokenKind.HINT:
            hints = _parse_hints(self.advance().text)
        distinct = bool(self.accept_kw("DISTINCT"))
        self.accept_kw("ALL")

        fields = [self.parse_select_field()]
        while self.accept_op(","):
            fields.append(self.parse_select_field())

        stmt = ast.SelectStmt(fields=fields, distinct=distinct,
                              hints=hints)
        if self.accept_kw("FROM"):
            stmt.from_ = self.parse_table_refs()
        if self.accept_kw("WHERE"):
            stmt.where = self.parse_expr()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            stmt.group_by.append(self.parse_expr())
            while self.accept_op(","):
                stmt.group_by.append(self.parse_expr())
        if self.accept_kw("HAVING"):
            stmt.having = self.parse_expr()
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            stmt.order_by.append(self.parse_order_item())
            while self.accept_op(","):
                stmt.order_by.append(self.parse_order_item())
        if self.accept_kw("LIMIT"):
            first = self.parse_uint("LIMIT")
            if self.accept_op(","):  # LIMIT offset, count
                stmt.offset = first
                stmt.limit = self.parse_uint("LIMIT")
            else:
                stmt.limit = first
                if self.accept_kw("OFFSET"):
                    stmt.offset = self.parse_uint("OFFSET")
        if self.accept_kw("FOR"):
            self.expect_kw("UPDATE")
            stmt.for_update = True
        if self.cur.is_kw("INTO") and self.peek().is_kw("OUTFILE"):
            self.advance()
            self.advance()
            path = self._string_lit("OUTFILE")
            stmt.into_outfile = self._parse_file_format(path)
        return stmt

    def parse_uint(self, what: str) -> int:
        t = self.cur
        if t.kind != TokenKind.INT:
            raise ParseError(f"expected integer after {what}", t)
        self.advance()
        return int(t.text)

    def parse_select_field(self) -> ast.SelectField:
        if self.accept_op("*"):
            return ast.SelectField(expr=None)
        # t.* wildcard
        if (
            self.cur.kind == TokenKind.IDENT
            and self.peek().is_op(".")
            and self.peek(2).is_op("*")
        ):
            tbl = self.advance().text
            self.advance()
            self.advance()
            return ast.SelectField(expr=None, wildcard_table=tbl)
        expr = self.parse_expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self.expect_ident()
        elif self.cur.kind == TokenKind.IDENT:
            alias = self.advance().text
        elif self.cur.kind == TokenKind.STRING:
            alias = self.advance().text
        return ast.SelectField(expr=expr, alias=alias)

    def parse_order_item(self) -> ast.OrderItem:
        e = self.parse_expr()
        desc = False
        if self.accept_kw("DESC"):
            desc = True
        else:
            self.accept_kw("ASC")
        return ast.OrderItem(e, desc)

    # ---- FROM / joins ------------------------------------------------------
    def parse_table_refs(self) -> ast.TableRef:
        left = self.parse_join_chain()
        while self.accept_op(","):  # comma join = cross join
            right = self.parse_join_chain()
            left = ast.Join("CROSS", left, right)
        return left

    def parse_join_chain(self) -> ast.TableRef:
        left = self.parse_table_factor()
        while True:
            kind = None
            if self.accept_kw("INNER"):
                self.expect_kw("JOIN")
                kind = "INNER"
            elif self.accept_kw("CROSS"):
                self.expect_kw("JOIN")
                kind = "CROSS"
            elif self.accept_kw("LEFT"):
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "LEFT"
            elif self.accept_kw("RIGHT"):
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "RIGHT"
            elif self.accept_kw("JOIN"):
                kind = "INNER"
            else:
                return left
            right = self.parse_table_factor()
            on = None
            using = None
            if self.accept_kw("ON"):
                on = self.parse_expr()
            elif self.accept_kw("USING"):
                self.expect_op("(")
                using = [self.expect_ident()]
                while self.accept_op(","):
                    using.append(self.expect_ident())
                self.expect_op(")")
            left = ast.Join(kind, left, right, on=on, using=using)

    def parse_table_factor(self) -> ast.TableRef:
        if self.accept_op("("):
            if self.cur.is_kw("SELECT"):
                sub = self.parse_select_statement()
                self.expect_op(")")
                alias = ""
                self.accept_kw("AS")
                if self.cur.kind == TokenKind.IDENT:
                    alias = self.advance().text
                return ast.SubqueryTable(sub, alias)
            refs = self.parse_table_refs()
            self.expect_op(")")
            return refs
        return self.parse_table_name(allow_alias=True)

    def parse_table_name(self, allow_alias: bool = False) -> ast.TableName:
        name = self.expect_ident()
        db = None
        if self.accept_op("."):
            db, name = name, self.expect_ident()
        alias = None
        if allow_alias:
            if self.accept_kw("AS"):
                alias = self.expect_ident()
            elif self.cur.kind == TokenKind.IDENT:
                alias = self.advance().text
        return ast.TableName(name=name, db=db, alias=alias)

    # ---- DML ---------------------------------------------------------------
    def parse_insert(self) -> ast.InsertStmt:
        is_replace = bool(self.accept_kw("REPLACE"))
        if not is_replace:
            self.expect_kw("INSERT")
        self.accept_kw("INTO")
        table = self.parse_table_name()
        columns = None
        if self.accept_op("("):
            columns = [self.expect_ident()]
            while self.accept_op(","):
                columns.append(self.expect_ident())
            self.expect_op(")")
        if self.cur.is_kw("SELECT"):
            sel = self.parse_select_statement()
            return ast.InsertStmt(table, columns, select=sel,
                                  is_replace=is_replace,
                                  on_dup=self._parse_on_dup())
        self.expect_kw("VALUES")
        rows = [self.parse_value_row()]
        while self.accept_op(","):
            rows.append(self.parse_value_row())
        return ast.InsertStmt(table, columns, rows=rows,
                              is_replace=is_replace,
                              on_dup=self._parse_on_dup())

    def _parse_on_dup(self) -> list[ast.Assignment]:
        """ON DUPLICATE KEY UPDATE col = expr, ... (reference: ast
        OnDuplicateAssignment; VALUES(col) refers to the would-be
        inserted value)."""
        if not self.accept_kw("ON"):
            return []
        for kw in ("DUPLICATE", "KEY", "UPDATE"):
            t = self.cur
            if not (t.is_kw(kw) or (t.kind == TokenKind.IDENT
                                    and t.text.upper() == kw)):
                raise ParseError(f"expected {kw}", t)
            self.advance()
        out = [self.parse_assignment()]
        while self.accept_op(","):
            out.append(self.parse_assignment())
        return out

    def parse_value_row(self) -> list[ast.Expr]:
        self.expect_op("(")
        if self.accept_op(")"):
            return []
        row = [self.parse_expr()]
        while self.accept_op(","):
            row.append(self.parse_expr())
        self.expect_op(")")
        return row

    def parse_update(self) -> ast.UpdateStmt:
        self.expect_kw("UPDATE")
        table = self.parse_table_name(allow_alias=True)
        self.expect_kw("SET")
        assigns = [self.parse_assignment()]
        while self.accept_op(","):
            assigns.append(self.parse_assignment())
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        return ast.UpdateStmt(table, assigns, where)

    def parse_assignment(self) -> ast.Assignment:
        col = self.parse_column_ref()
        self.expect_op("=")
        return ast.Assignment(col, self.parse_expr())

    def parse_delete(self) -> ast.DeleteStmt:
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        table = self.parse_table_name(allow_alias=True)
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        return ast.DeleteStmt(table, where)

    # ---- DDL ---------------------------------------------------------------
    def _parse_account_name(self) -> str:
        """'user'[@'host'] — host accepted and discarded (single-host)."""
        t = self.cur
        if t.kind in (TokenKind.STRING, TokenKind.IDENT):
            self.advance()
            name = t.text
        else:
            name = self.expect_ident()
        if self.accept_op("@"):
            self.advance()  # host (ident or string)
        return name

    def _parse_binding_tail(self) -> tuple[str, str, "ast.Stmt"]:
        """FOR <stmt> USING <stmt> -> (orig raw text, bind raw text,
        parsed bind stmt). The raw texts are what bindinfo stores
        (reference: bindinfo/handle.go normalizes and persists both)."""
        self.expect_kw("FOR")
        start = self.cur.pos
        self.parse_select_statement()
        if not self.cur.is_kw("USING"):
            raise ParseError("expected USING in BINDING", self.cur)
        orig = self.text[start:self.cur.pos].strip()
        self.advance()
        bstart = self.cur.pos
        bind_stmt = self.parse_select_statement()
        bend = self.cur.pos if self.cur.kind != TokenKind.EOF \
            else len(self.text)
        bind = self.text[bstart:bend].strip().rstrip(";").strip()
        return orig, bind, bind_stmt

    def parse_create(self) -> ast.Stmt:
        self.expect_kw("CREATE")
        scope_t = None
        if self.cur.is_kw("GLOBAL", "SESSION") and \
                self.peek().kind == TokenKind.IDENT and \
                self.peek().text.upper() == "BINDING":
            scope_t = self.advance().text
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "BINDING":
            self.advance()
            orig, bind, bind_stmt = self._parse_binding_tail()
            return ast.CreateBindingStmt(scope_t or "SESSION", orig,
                                         bind, bind_stmt)
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "ROLE":
            self.advance()
            ine = self._if_not_exists()
            names = [self._parse_account_name()]
            while self.accept_op(","):
                names.append(self._parse_account_name())
            return ast.CreateRoleStmt(names, ine)
        or_replace = False
        if self.cur.is_kw("OR"):
            self.advance()
            if not (self.cur.kind == TokenKind.IDENT
                    and self.cur.text.upper() == "REPLACE") and \
                    not self.cur.is_kw("REPLACE"):
                raise ParseError("expected REPLACE after OR", self.cur)
            self.advance()
            or_replace = True
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "VIEW":
            self.advance()
            tn = self.parse_table_name()
            cols: tuple = ()
            if self.cur.is_op("("):
                cols = tuple(self._paren_ident_list())
            self.expect_kw("AS")
            start = self.cur.pos
            self.parse_select()  # validate; the TEXT is what's stored
            sql = self.text[start:
                            self.cur.pos if self.cur.kind
                            != TokenKind.EOF else len(self.text)].strip()
            if sql.endswith(";"):
                sql = sql[:-1]
            return ast.CreateViewStmt(tn.name, sql, cols, or_replace,
                                      tn.db)
        if or_replace:
            raise ParseError("OR REPLACE supports only VIEW", self.cur)
        if self.accept_kw("DATABASE", "SCHEMA"):
            ine = self._if_not_exists()
            return ast.CreateDatabaseStmt(self.expect_ident(), ine)
        if self.accept_kw("USER"):
            ine = self._if_not_exists()
            name = self._parse_account_name()
            password = ""
            if self.accept_kw("IDENTIFIED"):
                self.expect_kw("BY")
                password = self.advance().text
            return ast.CreateUserStmt(name, password, ine)
        unique = bool(self.accept_kw("UNIQUE"))
        if self.accept_kw("INDEX", "KEY"):
            name = self.expect_ident()
            self.expect_kw("ON")
            table = self.parse_table_name()
            return ast.CreateIndexStmt(name, table,
                                       self._paren_ident_list(), unique)
        if unique:
            raise ParseError("expected INDEX after CREATE UNIQUE", self.cur)
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "SEQUENCE":
            self.advance()
            return self._parse_create_sequence()
        self.expect_kw("TABLE")
        ine = self._if_not_exists()
        table = self.parse_table_name()
        self.expect_op("(")
        columns: list[ast.ColumnDef] = []
        indices: list[ast.IndexDef] = []
        fks: list[ast.FKDef] = []
        while True:
            if self.cur.is_kw("CONSTRAINT", "FOREIGN"):
                fks.append(self._parse_fk_clause())
            elif self.cur.is_kw("PRIMARY"):
                self.advance()
                self.expect_kw("KEY")
                cols = self._paren_ident_list()
                indices.append(ast.IndexDef("PRIMARY", cols, unique=True, primary=True))
            elif self.cur.is_kw("UNIQUE"):
                self.advance()
                self.accept_kw("KEY", "INDEX")
                name = self._opt_index_name()
                indices.append(ast.IndexDef(name, self._paren_ident_list(), unique=True))
            elif self.cur.is_kw("KEY", "INDEX"):
                self.advance()
                name = self._opt_index_name()
                indices.append(ast.IndexDef(name, self._paren_ident_list()))
            else:
                columns.append(self.parse_column_def())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        # table options (ENGINE=..., CHARSET=...) are swallowed up to the
        # PARTITION BY clause (which we parse) or end of statement
        partition_by = None
        while self.cur.kind != TokenKind.EOF and not self.cur.is_op(";"):
            if self.cur.is_kw("PARTITION"):
                partition_by = self._parse_partition_by()
                break
            self.advance()
        # column-level REFERENCES lift into table-level FK metadata
        for cd in columns:
            ref = getattr(cd, "references", None)
            if ref is not None:
                fks.append(ast.FKDef(None, [cd.name], ref[0], ref[1]))
        return ast.CreateTableStmt(table, columns, indices, ine,
                                   partition_by, fks)

    def _parse_fk_clause(self) -> ast.FKDef:
        """[CONSTRAINT [name]] FOREIGN KEY (cols) REFERENCES tbl (cols)
        [ON DELETE action] [ON UPDATE action]."""
        name = None
        if self.accept_kw("CONSTRAINT"):
            if self.cur.kind == TokenKind.IDENT:
                name = self.advance().text
        self.expect_kw("FOREIGN")
        self.expect_kw("KEY")
        if self.cur.kind == TokenKind.IDENT:  # optional index name
            name = name or self.advance().text
        cols = self._paren_ident_list()
        self.expect_kw("REFERENCES")
        ref_table = self.parse_table_name()
        ref_cols = self._paren_ident_list()
        on_delete = on_update = "RESTRICT"
        while self.accept_kw("ON"):
            which = self.expect_kw("DELETE", "UPDATE").text
            action = self._parse_fk_action()
            if which == "DELETE":
                on_delete = action
            else:
                on_update = action
        return ast.FKDef(name, cols, ref_table, ref_cols,
                         on_delete, on_update)

    def _parse_fk_action(self) -> str:
        if self.accept_kw("SET"):
            self.expect_kw("NULL")
            return "SET NULL"
        t = self.cur
        word = t.text.upper()
        if word in ("RESTRICT", "CASCADE"):
            self.advance()
            return word
        if word == "NO":
            self.advance()
            nxt = self.advance()
            if nxt.text.upper() != "ACTION":
                raise ParseError("expected NO ACTION", nxt)
            return "NO ACTION"
        raise ParseError("expected referential action", t)

    def _parse_create_sequence(self) -> ast.CreateSequenceStmt:
        """CREATE SEQUENCE (reference: TiDB's MariaDB-style sequences,
        ddl/sequence.go; CACHE is accepted and ignored — caching is the
        allocator's concern)."""
        ine = self._if_not_exists()
        stmt = ast.CreateSequenceStmt(self.parse_table_name(),
                                      if_not_exists=ine)
        while self.cur.kind in (TokenKind.IDENT, TokenKind.KEYWORD) and \
                not self.cur.is_op(";"):
            word = self.cur.text.upper()
            if word == "START":
                self.advance()
                if self.cur.kind == TokenKind.IDENT and \
                        self.cur.text.upper() == "WITH":
                    self.advance()
                stmt.start = self._parse_signed_int("START")
            elif word == "INCREMENT":
                self.advance()
                if self.cur.is_kw("BY"):
                    self.advance()
                stmt.increment = self._parse_signed_int("INCREMENT")
                if stmt.increment == 0:
                    raise ParseError("INCREMENT must not be 0", self.cur)
            elif word == "MINVALUE":
                self.advance()
                stmt.min_value = self._parse_signed_int("MINVALUE")
            elif word == "MAXVALUE":
                self.advance()
                stmt.max_value = self._parse_signed_int("MAXVALUE")
            elif word == "CACHE":
                self.advance()
                self.parse_uint("CACHE")  # accepted, allocator decides
            elif word in ("CYCLE", "NOCYCLE"):
                self.advance()
                stmt.cycle = word == "CYCLE"
            elif word in ("NOCACHE", "NOMINVALUE", "NOMAXVALUE"):
                self.advance()
            else:
                break
        if stmt.start < stmt.min_value or stmt.start > stmt.max_value:
            raise ParseError("START out of MINVALUE..MAXVALUE", self.cur)
        return stmt

    def _parse_signed_int(self, what: str) -> int:
        neg = bool(self.accept_op("-"))
        v = self.parse_uint(what)
        return -v if neg else v

    def _parse_partition_by(self) -> ast.PartitionByDef:
        """PARTITION BY HASH(col) PARTITIONS n |
        PARTITION BY RANGE (col) (PARTITION p VALUES LESS THAN (v|
        MAXVALUE), ...) (reference: parser partition options ->
        model.PartitionInfo, ddl/partition.go)."""
        self.expect_kw("PARTITION")
        self.expect_kw("BY")
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "HASH":
            self.advance()
            self.expect_op("(")
            col = self.expect_ident()
            self.expect_op(")")
            count = 1
            if self.cur.kind == TokenKind.IDENT and \
                    self.cur.text.upper() == "PARTITIONS":
                self.advance()
                count = self.parse_uint("PARTITIONS")
            if count < 1:
                raise ParseError("PARTITIONS must be >= 1", self.cur)
            return ast.PartitionByDef("hash", col, count=count)
        if self.cur.is_kw("RANGE"):
            self.advance()
            self.expect_op("(")
            col = self.expect_ident()
            self.expect_op(")")
            self.expect_op("(")
            ranges: list[tuple[str, Optional[int]]] = []
            while True:
                self.expect_kw("PARTITION")
                name = self.expect_ident()
                self.expect_kw("VALUES")
                kw = self.cur
                if not (kw.kind == TokenKind.IDENT
                        and kw.text.upper() == "LESS"):
                    raise ParseError("expected LESS THAN", kw)
                self.advance()
                if not (self.cur.kind == TokenKind.IDENT
                        and self.cur.text.upper() == "THAN"):
                    raise ParseError("expected THAN", self.cur)
                self.advance()
                if self.cur.kind == TokenKind.IDENT and \
                        self.cur.text.upper() == "MAXVALUE":
                    self.advance()
                    ranges.append((name, None))
                else:
                    self.expect_op("(")
                    neg = bool(self.accept_op("-"))
                    t = self.cur
                    if t.kind != TokenKind.INT:
                        raise ParseError(
                            "expected integer partition bound", t)
                    self.advance()
                    v = -int(t.text) if neg else int(t.text)
                    self.expect_op(")")
                    ranges.append((name, v))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return ast.PartitionByDef("range", col, ranges=ranges)
        raise ParseError("expected HASH or RANGE after PARTITION BY",
                         self.cur)

    def _if_not_exists(self) -> bool:
        if self.accept_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def _opt_index_name(self) -> Optional[str]:
        if self.cur.kind == TokenKind.IDENT and not self.peek().is_op("("):
            pass
        if self.cur.kind == TokenKind.IDENT:
            return self.advance().text
        return None

    def _paren_ident_list(self) -> list[str]:
        self.expect_op("(")
        out = [self.expect_ident()]
        while self.accept_op(","):
            out.append(self.expect_ident())
        self.expect_op(")")
        return out

    def parse_column_def(self) -> ast.ColumnDef:
        name = self.expect_ident()
        ftype = self.parse_field_type()
        d = ast.ColumnDef(name, ftype)
        while True:
            if self.accept_kw("NOT"):
                self.expect_kw("NULL")
                d.not_null = True
            elif self.accept_kw("NULL"):
                pass
            elif self.accept_kw("PRIMARY"):
                self.expect_kw("KEY")
                d.primary_key = True
                d.not_null = True
            elif self.accept_kw("UNIQUE"):
                self.accept_kw("KEY")
                d.unique = True
            elif self.accept_kw("AUTO_INCREMENT"):
                d.auto_increment = True
            elif self.accept_kw("DEFAULT"):
                d.default = self.parse_primary()
            elif self.accept_kw("REFERENCES"):
                # column-level FK shorthand: REFERENCES tbl (col)
                rt = self.parse_table_name()
                rc = self._paren_ident_list()
                d.references = (rt, rc)  # type: ignore[attr-defined]
            elif self.cur.is_kw("COLLATE") or (
                    self.cur.kind == TokenKind.IDENT
                    and self.cur.text.upper() == "COLLATE"):
                self.advance()
                name = self.advance().text.lower()
                if d.ftype.is_string:
                    d.ftype = FieldType(
                        d.ftype.kind, flen=d.ftype.flen,
                        scale=d.ftype.scale, nullable=d.ftype.nullable,
                        elems=d.ftype.elems, collate=name)
            elif self.cur.kind == TokenKind.IDENT and \
                    self.cur.text.upper() == "CHARACTER":
                self.advance()  # CHARACTER SET <name> — swallowed
                self.accept_kw("SET")
                if self.cur.kind in (TokenKind.IDENT, TokenKind.STRING):
                    self.advance()
            elif self.cur.kind == TokenKind.IDENT and \
                    self.cur.text.upper() == "COMMENT":
                self.advance()
                if self.cur.kind in (TokenKind.IDENT, TokenKind.STRING,
                                     TokenKind.KEYWORD):
                    self.advance()
            else:
                return d

    def parse_field_type(self) -> FieldType:
        t = self.cur
        kind = None
        upper = t.text.upper() if t.kind == TokenKind.IDENT else ""
        if t.kind == TokenKind.KEYWORD and t.text in _TYPE_KEYWORDS:
            kind = _TYPE_KEYWORDS[t.text]
            self.advance()
        elif t.is_kw("SET"):  # SET('a','b',...) in type position
            kind = TypeKind.SET
            self.advance()
        elif upper in ("ENUM", "BIT", "JSON"):
            kind = {"ENUM": TypeKind.ENUM, "BIT": TypeKind.BIT,
                    "JSON": TypeKind.JSON}[upper]
            self.advance()
        elif upper in ("SIGNED", "UNSIGNED"):
            self.advance()
            self.accept_kw("INT", "INTEGER")
            kind = TypeKind.BIGINT
        else:
            raise ParseError("expected type name", t)
        flen, scale = -1, 0
        elems: tuple = ()
        if kind in (TypeKind.ENUM, TypeKind.SET):
            self.expect_op("(")
            vals = []
            while True:
                s = self.cur
                if s.kind != TokenKind.STRING:
                    raise ParseError("expected string element", s)
                self.advance()
                vals.append(s.text)
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            if kind == TypeKind.SET and len(vals) > 64:
                raise ParseError("SET supports at most 64 elements", t)
            if len(set(v.lower() for v in vals)) != len(vals):
                raise ParseError("duplicate element in ENUM/SET", t)
            elems = tuple(vals)
        elif self.accept_op("("):
            flen = self.parse_uint("type length")
            if self.accept_op(","):
                scale = self.parse_uint("type scale")
            self.expect_op(")")
        if kind == TypeKind.DECIMAL:
            if flen < 0:
                flen = 10  # MySQL default DECIMAL(10,0)
            if flen > 18:
                raise ParseError(f"DECIMAL({flen}) exceeds supported precision 18",
                                 t)
        if kind == TypeKind.BIT:
            if flen < 0:
                flen = 1
            if flen > 63:
                # the int64 physical buffer holds 63 value bits; MySQL's
                # BIT(64) tail is rejected loudly (same policy as the
                # DECIMAL>18 gate)
                raise ParseError("BIT width exceeds supported 63", t)
        if self.cur.kind == TokenKind.IDENT and self.cur.text.upper() == "UNSIGNED":
            self.advance()  # accepted but not tracked yet
        return FieldType(kind, flen=flen, scale=scale, elems=elems)

    def parse_drop(self) -> ast.Stmt:
        self.expect_kw("DROP")
        scope_t = None
        if self.cur.is_kw("GLOBAL", "SESSION") and \
                self.peek().kind == TokenKind.IDENT and \
                self.peek().text.upper() == "BINDING":
            scope_t = self.advance().text
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "BINDING":
            self.advance()
            self.expect_kw("FOR")
            start = self.cur.pos
            self.parse_select_statement()
            end = self.cur.pos if self.cur.kind != TokenKind.EOF \
                else len(self.text)
            orig = self.text[start:end].strip().rstrip(";").strip()
            return ast.DropBindingStmt(scope_t or "SESSION", orig)
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "ROLE":
            self.advance()
            if_exists = self._if_exists()
            names = [self._parse_account_name()]
            while self.accept_op(","):
                names.append(self._parse_account_name())
            return ast.DropRoleStmt(names, if_exists)
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "VIEW":
            self.advance()
            if_exists = self._if_exists()
            tn = self.parse_table_name()
            return ast.DropViewStmt(tn.name, if_exists, tn.db)
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "SEQUENCE":
            self.advance()
            if_exists = self._if_exists()
            names = [self.parse_table_name()]
            while self.accept_op(","):
                names.append(self.parse_table_name())
            return ast.DropSequenceStmt(names, if_exists)
        if self.accept_kw("DATABASE", "SCHEMA"):
            if_exists = self._if_exists()
            return ast.DropDatabaseStmt(self.expect_ident(), if_exists)
        if self.accept_kw("USER"):
            if_exists = self._if_exists()
            return ast.DropUserStmt(self._parse_account_name(), if_exists)
        if self.accept_kw("INDEX", "KEY"):
            name = self.expect_ident()
            self.expect_kw("ON")
            return ast.DropIndexStmt(name, self.parse_table_name())
        self.expect_kw("TABLE")
        if_exists = self._if_exists()
        tables = [self.parse_table_name()]
        while self.accept_op(","):
            tables.append(self.parse_table_name())
        return ast.DropTableStmt(tables, if_exists)

    def _if_exists(self) -> bool:
        if self.accept_kw("IF"):
            self.expect_kw("EXISTS")
            return True
        return False

    # ---- misc statements ---------------------------------------------------
    def parse_explain(self) -> ast.Stmt:
        self.advance()  # EXPLAIN/DESC/DESCRIBE
        analyze = bool(self.accept_kw("ANALYZE"))
        return ast.ExplainStmt(self.parse_statement(), analyze)

    def _show_like(self, stmt: ast.ShowStmt) -> ast.ShowStmt:
        if self.cur.is_kw("LIKE"):
            self.advance()
            stmt.pattern = self.advance().text
        elif self.cur.is_kw("WHERE"):
            self.advance()
            self.parse_expr()  # accepted, unfiltered (compat tolerance)
        return stmt

    def parse_show(self) -> ast.ShowStmt:
        self.expect_kw("SHOW")
        scope = "SESSION"
        if self.accept_kw("GLOBAL"):
            scope = "GLOBAL"
        elif self.accept_kw("SESSION"):
            scope = "SESSION"
        self.accept_kw("FULL")
        if self.cur.is_kw("TABLE") and \
                self.peek().is_kw("STATUS"):
            self.advance()
            self.advance()
            return self._show_like(ast.ShowStmt("TABLE_STATUS"))
        if self.accept_kw("TABLES"):
            return self._show_like(ast.ShowStmt("TABLES"))
        if self.accept_kw("DATABASES", "SCHEMAS"):
            return self._show_like(ast.ShowStmt("DATABASES"))
        if self.accept_kw("STATUS"):
            return self._show_like(ast.ShowStmt("STATUS", scope=scope))
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "BINDINGS":
            self.advance()
            return ast.ShowStmt("BINDINGS", scope=scope)
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "PROCESSLIST":
            self.advance()
            return ast.ShowStmt("PROCESSLIST")
        if self.accept_kw("WARNINGS", "ERRORS"):
            return ast.ShowStmt("WARNINGS")
        if self.accept_kw("ENGINES"):
            return ast.ShowStmt("ENGINES")
        if self.accept_kw("COLLATION"):
            return self._show_like(ast.ShowStmt("COLLATION"))
        if self.cur.kind in (TokenKind.IDENT, TokenKind.KEYWORD) and \
                self.cur.text.upper() in ("CHARACTER", "CHARSET"):
            if self.cur.text.upper() == "CHARACTER":
                self.advance()
                self.expect_kw("SET")
            else:
                self.advance()
            return self._show_like(ast.ShowStmt("CHARSET"))
        if self.cur.kind == TokenKind.KEYWORD and \
                self.cur.text == "PRIVILEGES":
            self.advance()
            return ast.ShowStmt("PRIVILEGES")
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "PROFILES":
            self.advance()
            return ast.ShowStmt("PROFILES")
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "PROFILE":
            # SHOW PROFILE [type[, type]...] [FOR QUERY n]: type
            # clauses (CPU, BLOCK IO, ...) are accepted and ignored —
            # the sampler has one view, wall-clock stacks
            self.advance()
            stmt = ast.ShowStmt("PROFILE")
            types = {"ALL", "BLOCK", "IO", "CONTEXT", "SWITCHES", "CPU",
                     "IPC", "MEMORY", "PAGE", "FAULTS", "SOURCE",
                     "SWAPS"}
            while self.cur.kind in (TokenKind.IDENT, TokenKind.KEYWORD) \
                    and self.cur.text.upper() in types:
                self.advance()
                self.accept_op(",")
            if self.accept_kw("FOR"):
                t = self.cur
                if not (t.kind in (TokenKind.IDENT, TokenKind.KEYWORD)
                        and t.text.upper() == "QUERY"):
                    raise ParseError("expected QUERY", t)
                self.advance()
                t = self.cur
                if t.kind != TokenKind.INT:
                    raise ParseError(
                        "expected integer after FOR QUERY", t)
                self.advance()
                stmt.pattern = t.text
            return stmt
        if self.accept_kw("COLUMNS", "FIELDS"):
            self.expect_kw("FROM")
            return self._show_like(
                ast.ShowStmt("COLUMNS", self.parse_table_name()))
        if self.accept_kw("INDEX", "INDEXES", "KEYS"):
            self.expect_kw("FROM")
            return ast.ShowStmt("INDEX", self.parse_table_name())
        if self.accept_kw("GRANTS"):
            stmt = ast.ShowStmt("GRANTS")
            if self.accept_kw("FOR"):
                stmt.pattern = self._parse_account_name()
            return stmt
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "SLOW":
            self.advance()
            if self.cur.kind == TokenKind.IDENT and \
                    self.cur.text.upper() == "QUERIES":
                self.advance()
            return ast.ShowStmt("SLOW")
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "METRICS":
            self.advance()
            return ast.ShowStmt("METRICS")
        if self.accept_kw("CREATE"):
            if self.accept_kw("DATABASE", "SCHEMA"):
                return ast.ShowStmt("CREATE_DATABASE",
                                    pattern=self.expect_ident())
            if self.cur.kind == TokenKind.IDENT and \
                    self.cur.text.upper() == "VIEW":
                self.advance()
                return ast.ShowStmt("CREATE_VIEW", self.parse_table_name())
            self.expect_kw("TABLE")
            return ast.ShowStmt("CREATE_TABLE", self.parse_table_name())
        if self.accept_kw("VARIABLES"):
            return self._show_like(ast.ShowStmt("VARIABLES", scope=scope))
        raise ParseError("unsupported SHOW", self.cur)

    def parse_set(self) -> ast.SetStmt:
        """SET assignments + the special client forms: SET NAMES cs,
        SET CHARACTER SET cs, SET [scope] TRANSACTION ISOLATION LEVEL x
        (reference: executor/set.go + ast SetStmt variants)."""
        self.expect_kw("SET")
        # SET PASSWORD [FOR 'u'] = 'pwd' (maps to ALTER USER; reference:
        # executor/simple.go executeSetPwd)
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "PASSWORD" and \
                (self.peek().is_kw("FOR") or self.peek().is_op("=")):
            self.advance()
            name = ""
            if self.accept_kw("FOR"):
                name = self._parse_account_name()
            self.expect_op("=")
            pwd = self._string_lit("SET PASSWORD")
            return ast.AlterUserStmt(name, pwd)
        # SET [DEFAULT] ROLE (reference: executor/set_role; roles in
        # privilege/privileges) — statement forms, not var assignments
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "ROLE":
            self.advance()
            return self._parse_set_role_tail()
        if self.cur.is_kw("DEFAULT") and \
                self.peek().kind == TokenKind.IDENT and \
                self.peek().text.upper() == "ROLE":
            self.advance()
            self.advance()
            if self.accept_kw("ALL"):
                mode, roles = "ALL", []
            elif self.cur.kind == TokenKind.IDENT and \
                    self.cur.text.upper() == "NONE":
                self.advance()
                mode, roles = "NONE", []
            else:
                mode = "LIST"
                roles = [self._parse_account_name()]
                while self.accept_op(","):
                    roles.append(self._parse_account_name())
            self.expect_kw("TO")
            users = [self._parse_account_name()]
            while self.accept_op(","):
                users.append(self._parse_account_name())
            return ast.SetDefaultRoleStmt(mode, roles, users)
        items = []
        while True:
            scope = "SESSION"
            if self.cur.is_kw("NAMES") or (
                    self.cur.kind == TokenKind.IDENT
                    and self.cur.text.upper() == "NAMES"):
                self.advance()
                cs = self.advance().text  # ident or string literal
                if self.cur.kind in (TokenKind.IDENT, TokenKind.KEYWORD) \
                        and self.cur.text.upper() == "COLLATE":
                    self.advance()
                    self.advance()  # collation name (accepted, ignored)
                items.append(("NAMES", "names", ast.Literal(cs, "string")))
            elif self.cur.kind in (TokenKind.IDENT, TokenKind.KEYWORD) and \
                    self.cur.text.upper() == "CHARACTER" and \
                    self.peek().is_kw("SET"):
                self.advance()
                self.advance()
                cs = self.advance().text
                items.append(("NAMES", "names", ast.Literal(cs, "string")))
            else:
                if self.accept_kw("GLOBAL"):
                    scope = "GLOBAL"
                elif self.accept_kw("SESSION"):
                    scope = "SESSION"
                if self.cur.is_kw("TRANSACTION"):
                    self.advance()
                    if not (self.cur.kind == TokenKind.IDENT
                            and self.cur.text.upper() == "ISOLATION"):
                        raise ParseError("expected ISOLATION LEVEL",
                                         self.cur)
                    self.advance()
                    if not (self.cur.kind == TokenKind.IDENT
                            and self.cur.text.upper() == "LEVEL"):
                        raise ParseError("expected LEVEL", self.cur)
                    self.advance()
                    words = [self.advance().text.upper()]
                    while self.cur.kind in (TokenKind.IDENT,
                                            TokenKind.KEYWORD) and \
                            self.cur.text.upper() in ("READ", "COMMITTED",
                                                      "UNCOMMITTED",
                                                      "REPEATABLE",
                                                      "SERIALIZABLE"):
                        words.append(self.advance().text.upper())
                    level = "-".join(words)
                    items.append((scope, "tx_isolation",
                                  ast.Literal(level, "string")))
                    if not self.accept_op(","):
                        return ast.SetStmt(items)
                    continue
                if self.accept_op("@"):
                    if self.accept_op("@"):  # @@[scope.]var
                        if self.cur.kind in (TokenKind.IDENT,
                                             TokenKind.KEYWORD) and \
                                self.cur.text.upper() in ("GLOBAL",
                                                          "SESSION") and \
                                self.peek().is_op("."):
                            scope = self.advance().text.upper()
                            self.advance()
                    else:
                        scope = "USERVAR"
                name = self.expect_ident()
                if not self.accept_op("=") and not self.accept_op(":="):
                    raise ParseError("expected = in SET", self.cur)
                items.append((scope, name.lower(), self.parse_set_value()))
            if not self.accept_op(","):
                return ast.SetStmt(items)

    def _parse_set_role_tail(self) -> "ast.SetRoleStmt":
        if self.accept_kw("ALL"):
            return ast.SetRoleStmt("ALL")
        if self.cur.is_kw("DEFAULT"):
            self.advance()
            return ast.SetRoleStmt("DEFAULT")
        if self.cur.kind == TokenKind.IDENT and \
                self.cur.text.upper() == "NONE":
            self.advance()
            return ast.SetRoleStmt("NONE")
        roles = [self._parse_account_name()]
        while self.accept_op(","):
            roles.append(self._parse_account_name())
        return ast.SetRoleStmt("LIST", roles)

    def parse_set_value(self) -> ast.Expr:
        """SET values admit bare idents/keywords (utf8mb4, ON, DEFAULT) as
        string-ish tokens in addition to ordinary expressions."""
        t = self.cur
        if t.is_kw("DEFAULT"):
            self.advance()
            return ast.Literal(None, "default")
        if t.kind == TokenKind.IDENT and not self.peek().is_op("(", "."):
            self.advance()
            return ast.Literal(t.text, "string")
        if t.kind == TokenKind.KEYWORD and t.text in ("ON", "OFF") :
            self.advance()
            return ast.Literal(t.text, "string")
        return self.parse_expr()

    # ---- expressions (Pratt) -----------------------------------------------
    def parse_expr(self) -> ast.Expr:
        return self.parse_binary(0)

    def parse_binary(self, min_prec: int) -> ast.Expr:
        left = self.parse_unary()
        while True:
            t = self.cur
            op = None
            if t.kind == TokenKind.OP and t.text in _PRECEDENCE:
                op = t.text
            elif t.kind == TokenKind.KEYWORD and t.text in _PRECEDENCE:
                op = t.text
            # NOT IN / NOT LIKE / NOT BETWEEN / IS / IN / BETWEEN / LIKE
            if t.is_kw("IS", "IN", "BETWEEN", "LIKE", "NOT") and (
                _COMPARISON_LEVEL > min_prec
            ):
                handled, left = self._parse_predicate_suffix(left)
                if handled:
                    continue
            if op is None:
                return left
            prec = _PRECEDENCE[op]
            if prec <= min_prec:
                return left
            self.advance()
            if op in ("||",):
                op = "OR"
            if op in ("&&",):
                op = "AND"
            if op == "!=":
                op = "<>"
            if op == "MOD":
                op = "%"
            right = self.parse_binary(prec)
            left = ast.BinaryOp(op, left, right)

    def _parse_predicate_suffix(self, left: ast.Expr) -> tuple[bool, ast.Expr]:
        """IS [NOT] NULL, [NOT] IN, [NOT] BETWEEN, [NOT] LIKE."""
        if self.cur.is_kw("IS"):
            self.advance()
            negated = bool(self.accept_kw("NOT"))
            if self.accept_kw("NULL"):
                return True, ast.IsNull(left, negated)
            if self.accept_kw("TRUE"):
                e: ast.Expr = ast.BinaryOp("=", left, ast.Literal(True, "bool"))
            elif self.accept_kw("FALSE"):
                e = ast.BinaryOp("=", left, ast.Literal(False, "bool"))
            else:
                raise ParseError("expected NULL/TRUE/FALSE after IS", self.cur)
            if negated:
                e = ast.UnaryOp("NOT", e)
            return True, e
        negated = False
        if self.cur.is_kw("NOT") and self.peek().is_kw("IN", "BETWEEN", "LIKE"):
            self.advance()
            negated = True
        if self.accept_kw("IN"):
            self.expect_op("(")
            if self.cur.is_kw("SELECT"):
                sub = self.parse_select()
                self.expect_op(")")
                return True, ast.InSubquery(left, sub, negated)
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return True, ast.InList(left, items, negated)
        if self.accept_kw("BETWEEN"):
            low = self.parse_binary(_COMPARISON_LEVEL)
            self.expect_kw("AND")
            high = self.parse_binary(_COMPARISON_LEVEL)
            return True, ast.Between(left, low, high, negated)
        if self.accept_kw("LIKE"):
            pattern = self.parse_binary(_COMPARISON_LEVEL)
            return True, ast.Like(left, pattern, negated)
        return False, left

    def parse_unary(self) -> ast.Expr:
        if self.accept_kw("NOT") or self.accept_op("!"):
            return ast.UnaryOp("NOT", self.parse_binary(4))
        if self.accept_op("-"):
            operand = self.parse_unary()
            if isinstance(operand, ast.Literal) and operand.tag in (
                "int", "decimal", "float"
            ):
                if operand.tag == "decimal":
                    return ast.Literal(-operand.value, "decimal")
                return ast.Literal(-operand.value, operand.tag)
            return ast.UnaryOp("-", operand)
        if self.accept_op("+"):
            return self.parse_unary()
        if self.accept_kw("INTERVAL"):
            value = self.parse_primary()
            unit = self._interval_unit()
            return ast.IntervalExpr(value, unit)
        if self.cur.is_kw("VALUES") and self.peek().is_op("("):
            # VALUES(col) inside ON DUPLICATE KEY UPDATE
            self.advance()
            self.expect_op("(")
            ref = self.parse_column_ref()
            self.expect_op(")")
            return ast.FuncCall("VALUES", [ref])
        e = self.parse_primary()
        # JSON path extraction operators: col->'$.k' / col->>'$.k'
        # (reference: parser maps -> to JSON_EXTRACT and ->> to
        # JSON_UNQUOTE(JSON_EXTRACT))
        while self.cur.is_op("->", "->>"):
            op = self.advance().text
            p = self.cur
            if p.kind != TokenKind.STRING:
                raise ParseError("expected JSON path string", p)
            self.advance()
            e = ast.FuncCall("JSON_EXTRACT",
                             [e, ast.Literal(p.text, "string")])
            if op == "->>":
                e = ast.FuncCall("JSON_UNQUOTE", [e])
        return e

    def _interval_unit(self) -> str:
        t = self.cur
        units = {"DAY", "WEEK", "MONTH", "QUARTER", "YEAR", "HOUR", "MINUTE",
                 "SECOND", "MICROSECOND"}
        if t.kind == TokenKind.IDENT and t.text.upper() in units:
            self.advance()
            return t.text.upper()
        if t.kind == TokenKind.KEYWORD and t.text in units:
            self.advance()
            return t.text
        raise ParseError("expected interval unit", t)

    def parse_primary(self) -> ast.Expr:
        t = self.cur
        if t.is_op("@"):
            self.advance()
            if self.accept_op("@"):
                scope = "SESSION"
                if self.cur.kind in (TokenKind.IDENT, TokenKind.KEYWORD) \
                        and self.cur.text.upper() in ("GLOBAL", "SESSION") \
                        and self.peek().is_op("."):
                    scope = self.advance().text.upper()
                    self.advance()
                return ast.SysVarExpr(self.expect_ident().lower(), scope)
            return ast.UserVarExpr(self.expect_ident().lower())
        if t.is_op("?"):
            self.advance()
            self.param_count += 1
            return ast.ParamMarker(self.param_count - 1)
        if t.kind == TokenKind.INT:
            self.advance()
            return ast.Literal(int(t.text), "int")
        if t.kind == TokenKind.DECIMAL:
            self.advance()
            return ast.Literal(Decimal.parse(t.text), "decimal")
        if t.kind == TokenKind.FLOAT:
            self.advance()
            return ast.Literal(float(t.text), "float")
        if t.kind == TokenKind.STRING:
            self.advance()
            return ast.Literal(t.text, "string")
        if t.is_kw("NULL"):
            self.advance()
            return ast.Literal(None, "null")
        if t.is_kw("TRUE"):
            self.advance()
            return ast.Literal(True, "bool")
        if t.is_kw("FALSE"):
            self.advance()
            return ast.Literal(False, "bool")
        # DATE 'lit' / TIMESTAMP 'lit' typed literals
        if t.is_kw("DATE", "TIMESTAMP", "DATETIME") and \
                self.peek().kind == TokenKind.STRING:
            self.advance()
            lit = self.advance()
            return ast.Literal(lit.text, {"DATE": "date"}.get(t.text, "datetime"))
        if t.is_kw("CASE"):
            return self.parse_case()
        if t.is_kw("CAST", "CONVERT"):
            return self.parse_cast()
        if t.is_kw("EXISTS"):
            self.advance()
            self.expect_op("(")
            sub = self.parse_select()
            self.expect_op(")")
            return ast.SubqueryExpr(sub, exists=True)
        if t.is_op("("):
            self.advance()
            if self.cur.is_kw("SELECT"):
                sub = self.parse_select()
                self.expect_op(")")
                return ast.SubqueryExpr(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        # aggregate keywords used as functions
        if t.kind == TokenKind.KEYWORD and t.text in _AGG_FUNCS:
            self.advance()
            return self.parse_func_call(t.text)
        # reserved words that double as function names when followed by (
        if t.kind == TokenKind.KEYWORD and \
                (t.text in _FUNC_KEYWORDS or t.text in ("INSERT",
                                                        "REPLACE")) and \
                self.peek().is_op("("):
            self.advance()
            return self.parse_func_call(t.text)
        if t.kind == TokenKind.IDENT or (
            t.kind == TokenKind.KEYWORD and t.text in _IDENT_KEYWORDS
        ):
            name = self.advance().text
            if self.cur.is_op("("):
                return self.parse_func_call(name.upper())
            return self._finish_column_ref(name)
        raise ParseError("expected expression", t)

    def parse_func_call(self, name: str) -> ast.Expr:
        self.expect_op("(")
        if name == "EXTRACT":
            # EXTRACT(unit FROM expr) -> YEAR/MONTH/DAY(expr)
            unit = self._interval_unit()
            if unit not in ("YEAR", "MONTH", "DAY"):
                raise ParseError(f"EXTRACT unit {unit} unsupported", self.cur)
            self.expect_kw("FROM")
            arg = self.parse_expr()
            self.expect_op(")")
            return ast.FuncCall(unit, [arg])
        if name in ("SUBSTRING", "SUBSTR"):
            # SUBSTRING(s FROM a [FOR b]) | SUBSTRING(s, a [, b])
            args = [self.parse_expr()]
            if self.accept_kw("FROM"):
                args.append(self.parse_expr())
                if self.accept_kw("FOR"):
                    args.append(self.parse_expr())
            else:
                while self.accept_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            return ast.FuncCall("SUBSTRING", args)
        distinct = bool(self.accept_kw("DISTINCT"))
        if self.accept_op("*"):
            self.expect_op(")")
            return self._maybe_over(ast.FuncCall(name, [], is_star=True))
        if self.accept_op(")"):
            return self._maybe_over(ast.FuncCall(name, []))
        args = [self.parse_expr()]
        while self.accept_op(","):
            args.append(self.parse_expr())
        self.expect_op(")")
        return self._maybe_over(ast.FuncCall(name, args, distinct=distinct))

    def _maybe_over(self, fc: ast.FuncCall) -> ast.FuncCall:
        """fn(...) OVER ([PARTITION BY ...] [ORDER BY ...]) — default
        frames only (RANGE UNBOUNDED PRECEDING .. CURRENT ROW)."""
        if not self.cur.is_kw("OVER"):
            return fc
        self.advance()
        self.expect_op("(")
        spec = ast.WindowSpec()
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            spec.partition_by.append(self.parse_expr())
            while self.accept_op(","):
                spec.partition_by.append(self.parse_expr())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            spec.order_by.append(self.parse_order_item())
            while self.accept_op(","):
                spec.order_by.append(self.parse_order_item())
        if self.cur.is_kw("ROWS", "RANGE"):
            spec.frame = self._parse_frame()
        self.expect_op(")")
        fc.window = spec
        return fc

    def _parse_frame(self) -> ast.WindowFrame:
        """ROWS|RANGE BETWEEN <bound> AND <bound>, or the single-bound
        form (bound .. CURRENT ROW)."""
        unit = self.advance().text  # ROWS | RANGE

        def bound() -> tuple[str, Optional[int]]:
            if self.accept_kw("UNBOUNDED"):
                kw = self.expect_kw("PRECEDING", "FOLLOWING")
                return ("unbounded" if kw.text == "PRECEDING"
                        else "unbounded_following"), None
            if self.accept_kw("CURRENT"):
                self.expect_kw("ROW")
                return "current", None
            t = self.cur
            if t.kind != TokenKind.INT:
                raise ParseError("expected frame bound", t)
            self.advance()
            kw = self.expect_kw("PRECEDING", "FOLLOWING")
            return kw.text.lower(), int(t.text)

        if self.accept_kw("BETWEEN"):
            s_type, s_val = bound()
            self.expect_kw("AND")
            e_type, e_val = bound()
        else:
            s_type, s_val = bound()
            e_type, e_val = "current", None
        if s_type == "unbounded_following" or e_type == "unbounded":
            raise ParseError("invalid window frame bounds", self.cur)
        return ast.WindowFrame(unit, s_type, s_val, e_type, e_val)

    def _finish_column_ref(self, first: str) -> ast.ColumnRef:
        if self.accept_op("."):
            second = self.expect_ident()
            if self.accept_op("."):
                return ast.ColumnRef(self.expect_ident(), table=second, db=first)
            return ast.ColumnRef(second, table=first)
        return ast.ColumnRef(first)

    def parse_column_ref(self) -> ast.ColumnRef:
        return self._finish_column_ref(self.expect_ident())

    def parse_case(self) -> ast.Case:
        self.expect_kw("CASE")
        operand = None
        if not self.cur.is_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.accept_kw("WHEN"):
            when = self.parse_expr()
            self.expect_kw("THEN")
            branches.append((when, self.parse_expr()))
        else_expr = self.parse_expr() if self.accept_kw("ELSE") else None
        self.expect_kw("END")
        return ast.Case(operand, branches, else_expr)

    def parse_cast(self) -> ast.Cast:
        kw = self.advance()  # CAST or CONVERT
        self.expect_op("(")
        operand = self.parse_expr()
        if kw.text == "CAST":
            self.expect_kw("AS")
        else:
            self.expect_op(",")
        target = self.parse_field_type()
        self.expect_op(")")
        return ast.Cast(operand, target)


# Keywords that may double as identifiers (table/column names) when not in
# keyword position — mirrors MySQL's non-reserved keyword list for the subset
# we actually reserve.
def _parse_hints(text: str) -> list[tuple[str, list[str]]]:
    """'LEADING(a, b) USE_INDEX(t, i)' -> [('LEADING', ['a','b']), ...]
    (reference: planner/core/hints.go hint table). Unknown hints are
    carried through; the planner ignores what it doesn't implement."""
    import re as _re

    out: list[tuple[str, list[str]]] = []
    for m in _re.finditer(r"([A-Za-z_][A-Za-z0-9_]*)\s*(\(([^)]*)\))?",
                          text):
        name = m.group(1).upper()
        args = [a.strip().strip("`").lower()
                for a in (m.group(3) or "").split(",") if a.strip()]
        out.append((name, args))
    return out


_IDENT_KEYWORDS = frozenset(
    """
    DATE TIME TIMESTAMP DATETIME YEAR STATUS VARIABLES TABLES DATABASES
    COUNT SUM AVG MIN MAX COLUMN FIRST AFTER BEGIN COMMIT IF
    ADMIN DDL JOBS OVER PARTITION ROWS RANGE
    SCHEMAS WARNINGS ERRORS ENGINES COLLATION COLUMNS FIELDS INDEXES KEYS
    NAMES USER IDENTIFIED PRIVILEGES GRANTS PESSIMISTIC OPTIMISTIC
    UNBOUNDED PRECEDING FOLLOWING CURRENT ROW TRACE
    KILL QUERY CONNECTION
    DATA LOCAL TERMINATED ENCLOSED ESCAPED LINES
    """.split()
)

# Reserved words that double as function names when followed immediately by
# '(' — mirrors MySQL's treatment of LEFT(), RIGHT(), REPLACE(), etc.
# Keywords already in _IDENT_KEYWORDS (IF, DATE, YEAR, ...) are handled by
# the identifier branch and are deliberately not repeated here.
_FUNC_KEYWORDS = frozenset(
    """
    LEFT RIGHT REPLACE MOD TRUNCATE DATABASE SCHEMA CHAR
    """.split()
)


def parse_sql(text: str) -> list[ast.Stmt]:
    return Parser(text).parse()


def parse_one(text: str) -> ast.Stmt:
    stmts = parse_sql(text)
    if len(stmts) != 1:
        raise ParseError("expected exactly one statement",
                         Token(TokenKind.EOF, "", 0))
    return stmts[0]
