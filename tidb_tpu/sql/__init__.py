from .lexer import Lexer, Token, TokenKind, LexError
from .parser import Parser, ParseError, parse_sql

__all__ = [
    "Lexer",
    "Token",
    "TokenKind",
    "LexError",
    "Parser",
    "ParseError",
    "parse_sql",
]
