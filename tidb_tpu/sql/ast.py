"""AST node definitions for the SQL subset.

Counterpart of the reference's `ast.StmtNode`/`ast.ExprNode` hierarchy in
the external parser module. Plain dataclasses; the planner walks these.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..types.field_type import FieldType


# ---- generic traversal ------------------------------------------------------

def walk(node, visit) -> None:
    """Depth-first visit of every dataclass node (lists and tuples of
    nodes included). visit(node) returning False prunes that subtree."""
    import dataclasses as _dc

    if _dc.is_dataclass(node) and not isinstance(node, type):
        if visit(node) is False:
            return
        for f in _dc.fields(node):
            walk_value(getattr(node, f.name), visit)


def walk_value(v, visit) -> None:
    import dataclasses as _dc

    if _dc.is_dataclass(v) and not isinstance(v, type):
        walk(v, visit)
    elif isinstance(v, (list, tuple)):
        for x in v:
            walk_value(x, visit)


def transform(node, fn):
    """Bottom-up rewrite: fn(node) -> replacement (or the node itself).
    Mutates dataclass fields in place; lists/tuples are rebuilt."""
    import dataclasses as _dc

    def rec(v):
        if _dc.is_dataclass(v) and not isinstance(v, type):
            for f in _dc.fields(v):
                setattr(v, f.name, rec(getattr(v, f.name)))
            return fn(v)
        if isinstance(v, list):
            return [rec(x) for x in v]
        if isinstance(v, tuple):
            return tuple(rec(x) for x in v)
        return v

    return rec(node)


# ---- expressions ------------------------------------------------------------

class Expr:
    pass


@dataclass
class Literal(Expr):
    value: Any  # int | float | Decimal | str | bool | None
    # literal type tag: 'int' | 'float' | 'decimal' | 'string' | 'null' | 'bool'
    tag: str = "int"


@dataclass
class ColumnRef(Expr):
    name: str
    table: Optional[str] = None  # qualifier as written
    db: Optional[str] = None

    def __str__(self) -> str:
        parts = [p for p in (self.db, self.table, self.name) if p]
        return ".".join(parts)


@dataclass
class BinaryOp(Expr):
    op: str  # '+', '-', '*', '/', 'DIV', '%', '=', '<', 'AND', 'OR', ...
    left: Expr
    right: Expr


@dataclass
class UnaryOp(Expr):
    op: str  # '-', 'NOT'
    operand: Expr


@dataclass
class IsNull(Expr):
    operand: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    operand: Expr
    items: list[Expr]
    negated: bool = False


@dataclass
class Between(Expr):
    operand: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class Like(Expr):
    operand: Expr
    pattern: Expr
    negated: bool = False


@dataclass
class ParamMarker(Expr):
    """A '?' placeholder in a prepared statement (binds at EXECUTE)."""

    idx: int


@dataclass
class SysVarExpr(Expr):
    """@@name / @@global.name / @@session.name — substituted with the
    variable's current value before planning."""

    name: str
    scope: str = "SESSION"


@dataclass
class UserVarExpr(Expr):
    """@name user variable read (session-scoped, SET @name = ...)."""

    name: str


@dataclass
class WindowFrame:
    """ROWS|RANGE BETWEEN <start> AND <end>. Bound types: 'unbounded',
    'current', 'preceding', 'following'; value set for the offset kinds."""

    unit: str  # 'ROWS' | 'RANGE'
    start_type: str
    start_value: Optional[int] = None
    end_type: str = "current"
    end_value: Optional[int] = None


@dataclass
class WindowSpec:
    partition_by: list["Expr"] = field(default_factory=list)
    order_by: list["OrderItem"] = field(default_factory=list)
    frame: Optional[WindowFrame] = None


@dataclass
class FuncCall(Expr):
    name: str  # upper-cased
    args: list[Expr]
    distinct: bool = False  # COUNT(DISTINCT x)
    is_star: bool = False  # COUNT(*)
    window: Optional[WindowSpec] = None  # fn(...) OVER (...)


@dataclass
class Case(Expr):
    operand: Optional[Expr]  # CASE x WHEN ... vs CASE WHEN cond ...
    branches: list[tuple[Expr, Expr]]  # (when, then)
    else_expr: Optional[Expr] = None


@dataclass
class Cast(Expr):
    operand: Expr
    target: FieldType


@dataclass
class IntervalExpr(Expr):
    value: Expr
    unit: str  # 'DAY', 'MONTH', 'YEAR', ...


@dataclass
class SubqueryExpr(Expr):
    query: "SelectStmt"
    # modifier: None (scalar), 'EXISTS', 'IN' handled via InSubquery
    exists: bool = False
    negated: bool = False


@dataclass
class InSubquery(Expr):
    operand: Expr
    query: "SelectStmt"
    negated: bool = False


# ---- statements -------------------------------------------------------------

class Stmt:
    pass


@dataclass
class SelectField:
    expr: Optional[Expr]  # None => wildcard
    alias: Optional[str] = None
    wildcard_table: Optional[str] = None  # t.* qualifier


@dataclass
class TableRef:
    pass


@dataclass
class TableName(TableRef):
    name: str
    db: Optional[str] = None
    alias: Optional[str] = None


@dataclass
class Join(TableRef):
    kind: str  # 'INNER' | 'LEFT' | 'RIGHT' | 'CROSS'
    left: TableRef
    right: TableRef
    on: Optional[Expr] = None
    using: Optional[list[str]] = None


@dataclass
class SubqueryTable(TableRef):
    query: "SelectStmt"
    alias: str = ""


@dataclass
class OrderItem:
    expr: Expr
    desc: bool = False


@dataclass
class SelectStmt(Stmt):
    fields: list[SelectField]
    from_: Optional[TableRef] = None
    where: Optional[Expr] = None
    group_by: list[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    distinct: bool = False
    for_update: bool = False  # SELECT ... FOR UPDATE row locks
    # optimizer hints from /*+ ... */: (NAME, [args]) in source order
    hints: list[tuple[str, list[str]]] = field(default_factory=list)
    # SELECT ... INTO OUTFILE 'path' (reference: executor/select_into.go)
    into_outfile: Optional["FileFormat"] = None


@dataclass
class FileFormat:
    """FIELDS/LINES clauses shared by LOAD DATA and INTO OUTFILE
    (reference: ast.FieldsClause/LinesClause; defaults per MySQL docs)."""

    path: str
    field_term: str = "\t"
    enclosed: Optional[str] = None
    escaped: str = "\\"
    line_term: str = "\n"


@dataclass
class SetOpStmt(Stmt):
    """Chain of UNION [ALL] selects; trailing ORDER BY/LIMIT bind to the
    whole union (MySQL semantics for unparenthesized selects)."""

    selects: list[SelectStmt]
    alls: list[bool]  # alls[i]: is selects[i+1] joined with UNION ALL
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    into_outfile: Optional["FileFormat"] = None


@dataclass
class InsertStmt(Stmt):
    table: TableName
    columns: Optional[list[str]]  # None => all, in order
    rows: list[list[Expr]] = field(default_factory=list)
    select: Optional[SelectStmt] = None  # INSERT ... SELECT
    is_replace: bool = False
    # ON DUPLICATE KEY UPDATE assignments; VALUES(col) refs allowed
    on_dup: list = field(default_factory=list)


@dataclass
class LoadDataStmt(Stmt):
    """LOAD DATA [LOCAL] INFILE (reference: executor/load_data.go)."""

    table: TableName
    fmt: FileFormat
    columns: Optional[list[str]] = None  # None => all, in order
    local: bool = False
    dup_mode: str = "error"  # error | ignore | replace
    ignore_lines: int = 0


@dataclass
class Assignment:
    column: ColumnRef
    value: Expr


@dataclass
class UpdateStmt(Stmt):
    table: TableName
    assignments: list[Assignment]
    where: Optional[Expr] = None


@dataclass
class DeleteStmt(Stmt):
    table: TableName
    where: Optional[Expr] = None


@dataclass
class ColumnDef:
    name: str
    ftype: FieldType
    not_null: bool = False
    primary_key: bool = False
    unique: bool = False
    auto_increment: bool = False
    default: Optional[Expr] = None


@dataclass
class IndexDef:
    name: Optional[str]
    columns: list[str]
    unique: bool = False
    primary: bool = False


@dataclass
class FKDef:
    """FOREIGN KEY clause (reference: ast.Constraint with
    ConstraintForeignKey refs)."""

    name: Optional[str]
    columns: list[str]
    ref_table: "TableName"
    ref_columns: list[str]
    on_delete: str = "RESTRICT"
    on_update: str = "RESTRICT"


@dataclass
class CreateSequenceStmt(Stmt):
    name: "TableName"
    start: int = 1
    increment: int = 1
    min_value: int = 1
    max_value: int = (1 << 63) - 1
    cycle: bool = False
    if_not_exists: bool = False


@dataclass
class DropSequenceStmt(Stmt):
    names: list["TableName"]
    if_exists: bool = False


@dataclass
class PartitionByDef:
    """PARTITION BY clause (reference: ast.PartitionOptions)."""

    kind: str  # 'hash' | 'range'
    column: str
    # hash: partition count; range: [(name, less_than|None=MAXVALUE)]
    count: int = 0
    ranges: list[tuple[str, Optional[int]]] = field(default_factory=list)


@dataclass
class CreateTableStmt(Stmt):
    table: TableName
    columns: list[ColumnDef]
    indices: list[IndexDef] = field(default_factory=list)
    if_not_exists: bool = False
    partition_by: Optional[PartitionByDef] = None
    foreign_keys: list = field(default_factory=list)  # [FKDef]


@dataclass
class DropTableStmt(Stmt):
    tables: list[TableName]
    if_exists: bool = False


@dataclass
class AlterSpec:
    """One ALTER TABLE action (reference: ast.AlterTableSpec)."""

    op: str  # add_column | drop_column | add_index | drop_index |
    #          modify_column | rename | drop_partition | truncate_partition
    column: Optional[ColumnDef] = None
    index: Optional[IndexDef] = None
    name: str = ""  # drop target / rename-to / partition name


@dataclass
class AlterTableStmt(Stmt):
    table: TableName
    specs: list[AlterSpec] = field(default_factory=list)


@dataclass
class CreateIndexStmt(Stmt):
    name: str
    table: TableName
    columns: list[str]
    unique: bool = False


@dataclass
class DropIndexStmt(Stmt):
    name: str
    table: TableName


@dataclass
class RenameTableStmt(Stmt):
    renames: list[tuple[TableName, TableName]] = field(default_factory=list)


@dataclass
class AdminStmt(Stmt):
    kind: str  # 'SHOW_DDL_JOBS' | 'CHECK_TABLE'
    tables: list[TableName] = field(default_factory=list)


@dataclass
class AlterUserStmt(Stmt):
    """ALTER USER 'u' IDENTIFIED BY 'pwd' (reference: executor/simple.go
    executeAlterUser; SET PASSWORD maps here too)."""

    name: str
    password: str
    if_exists: bool = False


@dataclass
class RenameUserStmt(Stmt):
    pairs: list  # [(old, new)]


@dataclass
class ChecksumTableStmt(Stmt):
    """CHECKSUM TABLE t[, ...] (reference: executor/checksum.go)."""

    tables: list[TableName]


@dataclass
class CreateBindingStmt(Stmt):
    """CREATE [GLOBAL|SESSION] BINDING FOR <stmt> USING <hinted stmt>
    (reference: bindinfo/handle.go; ast CreateBindingStmt)."""

    scope: str  # 'GLOBAL' | 'SESSION'
    orig_sql: str  # raw text of the FOR statement
    bind_sql: str  # raw text of the USING statement
    bind_stmt: SelectStmt = None  # parsed USING stmt (hints source)


@dataclass
class DropBindingStmt(Stmt):
    scope: str
    orig_sql: str


@dataclass
class CreateDatabaseStmt(Stmt):
    name: str
    if_not_exists: bool = False


@dataclass
class DropDatabaseStmt(Stmt):
    name: str
    if_exists: bool = False


@dataclass
class TruncateTableStmt(Stmt):
    table: TableName


@dataclass
class UseStmt(Stmt):
    db: str


@dataclass
class BeginStmt(Stmt):
    mode: str = ""  # '' (tidb_txn_mode default) | PESSIMISTIC | OPTIMISTIC


@dataclass
class CommitStmt(Stmt):
    pass


@dataclass
class RollbackStmt(Stmt):
    pass


@dataclass
class ExplainStmt(Stmt):
    target: Stmt
    analyze: bool = False


@dataclass
class TraceStmt(Stmt):
    """TRACE <stmt>: runs the statement, returns the span tree
    (reference: executor/trace.go)."""

    target: Stmt


@dataclass
class ShowStmt(Stmt):
    kind: str  # 'TABLES' | 'DATABASES' | 'CREATE_TABLE' | 'VARIABLES' | ...
    target: Optional[TableName] = None
    pattern: Optional[str] = None  # LIKE pattern (VARIABLES/STATUS/COLUMNS)
    scope: str = "SESSION"  # SHOW GLOBAL|SESSION VARIABLES


@dataclass
class SetStmt(Stmt):
    # assignments of session/global variables: list of (scope, name, expr)
    items: list[tuple[str, str, Expr]] = field(default_factory=list)


@dataclass
class AnalyzeTableStmt(Stmt):
    tables: list[TableName] = field(default_factory=list)


@dataclass
class CreateUserStmt(Stmt):
    name: str
    password: str = ""
    if_not_exists: bool = False


@dataclass
class DropUserStmt(Stmt):
    name: str
    if_exists: bool = False


@dataclass
class GrantStmt(Stmt):
    privs: list[str] = field(default_factory=list)  # upper-case names
    db: str = "*"
    table: str = "*"
    user: str = ""
    revoke: bool = False
    # per-priv optional column list: GRANT SELECT (a, b) ON t
    priv_cols: list = field(default_factory=list)


@dataclass
class CreateRoleStmt(Stmt):
    names: list[str]
    if_not_exists: bool = False


@dataclass
class DropRoleStmt(Stmt):
    names: list[str]
    if_exists: bool = False


@dataclass
class GrantRoleStmt(Stmt):
    """GRANT role[, ...] TO user[, ...] / REVOKE ... FROM ...
    (reference: privilege/privileges roles; executor/grant.go)."""

    roles: list[str]
    users: list[str]
    revoke: bool = False


@dataclass
class SetRoleStmt(Stmt):
    mode: str  # 'ALL' | 'NONE' | 'DEFAULT' | 'LIST'
    roles: list[str] = field(default_factory=list)


@dataclass
class SetDefaultRoleStmt(Stmt):
    mode: str  # 'ALL' | 'NONE' | 'LIST'
    roles: list[str]
    users: list[str]


@dataclass
class KillStmt(Stmt):
    """KILL [QUERY | CONNECTION] <id> (reference: server/server.go:548
    Kill; QUERY interrupts the running statement, CONNECTION also drops
    the session)."""

    conn_id: int
    query_only: bool = False


@dataclass
class CreateViewStmt(Stmt):
    name: str
    select_sql: str
    columns: tuple = ()
    or_replace: bool = False
    db: Optional[str] = None


@dataclass
class DropViewStmt(Stmt):
    name: str
    if_exists: bool = False
    db: Optional[str] = None
