"""Rules-driven cluster inspection engine: the system diagnoses itself.

Counterpart of the reference's SQL-queryable diagnostics tier
(reference: TiDB 4.0's executor/inspection_result.go — a registry of
named inspection rules evaluated over the metrics schema and cluster
state, surfaced as INFORMATION_SCHEMA.INSPECTION_RESULT /
INSPECTION_SUMMARY so operators debug a production cluster with SELECTs
instead of log archaeology). Four PRs of passive telemetry feed it:

  * MetricsHistory rings + live gauge/counter samples (PR 3)
  * the structured EventLog (PR 6: governor kills, admission sheds,
    breaker trips, fsync/checkpoint stalls, mesh skew/storm/watermark)
  * Top SQL attribution windows (PR 6)
  * the mesh flight recorder (PR 8: per-shard skew, compile storms,
    HBM provenance)
  * governor/admission/breaker/transport/membership state (PR 4/5)

Every rule is registered with a name, a default severity and reference
text (what knob/surface explains the finding) and is a PURE FUNCTION
over one bounded InspectionContext snapshot — no thread, no lock held
across rules, no RPC beyond the snapshot build. `diagnostics.enabled =
false` short-circuits before the snapshot is built, so the statement
path does zero inspection work (the contract tests/test_inspection.py
pins).

Surfaces: information_schema.inspection_result / inspection_summary,
cluster_inspection_result (per-member fan-out over the PR 3 diag RPC,
degrading per peer), /debug/inspection + the /status `inspection`
section, and an edge-triggered `inspection_finding` event the first
time a rule crosses severity=critical for an item.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Optional

from . import obs

SEVERITIES = ("info", "warning", "critical")
_SEV_ORDER = {s: i for i, s in enumerate(SEVERITIES)}


@dataclass
class DiagnosticsState:
    """Per-storage diagnostics settings + the edge-trigger memory.
    Field names/defaults mirror config.DiagnosticsConfig (the TOML
    owner); Config.seed_diagnostics copies the knobs in. Mirrored
    rather than imported so an embedded Storage never parses config."""

    enabled: bool = True
    # how many MetricsHistory samples a windowed rule considers (the
    # window in SECONDS is this times metrics-history-interval)
    history_windows: int = 8
    # mesh skew must persist this many dispatches before it is a
    # finding — one skewed dispatch is noise, a sustained one is a
    # placement problem
    skew_min_dispatches: int = 2
    fsync_stall_threshold: int = 3       # stalls in the window
    heartbeat_stale_ms: int = 10000      # follower hb age past this
    host_fallback_fraction: float = 0.5  # of a digest's stage split
    governor_kill_threshold: int = 1     # kills in the window
    admission_shed_threshold: int = 1    # sheds in the window
    # one range changing write leadership this many times in the
    # window is flapping (a clean failover is ONE transfer)
    range_flap_threshold: int = 3
    # one range SPLITTING this many times inside split-flap-window-s
    # is flapping: the advisory keeps firing without draining the
    # heat — the salted/monotonic hot-key symptom splits cannot fix
    split_flap_threshold: int = 3
    # seconds of range_split history the split-flap rule considers
    # (its own window, not history-windows: splits are rare and
    # cooldown-paced, so the shared window is usually too short)
    split_flap_window_s: int = 300
    row_eval_threshold: int = 1          # per-row registry rows/window
    # a serving replica's apply lag past this is a follower-apply-lag
    # warning; critical at 3x (the replica stopped advancing); 0 off
    apply_lag_warn_ms: int = 2000
    # dominant-wait: a digest spending at least this fraction of its
    # wall blocked in backoff.* or lease_wait is a finding (needs
    # performance.wait-profile-enabled for the data to exist)
    dominant_wait_threshold: float = 0.5
    # a range whose published closed_ts has not advanced for this long
    # WHILE its write counters moved is range-closed-ts-stall
    # (warning; critical at 3x — every range-aware replica read over
    # it is falling back to the leader); 0 disables the rule
    closed_ts_stall_ms: int = 10000
    # range-closed-ts-stall memory: range_id -> (closed_ts, wall_ms
    # first seen at that value, write mark) — the rule needs history
    # to tell "static" from "just observed" (edge memory like
    # seen_critical, surviving reseeds)
    closed_progress: dict = field(default_factory=dict)
    # (rule, item) pairs already reported critical: inspection_finding
    # events fire on NEW members only (edge-triggered, not level)
    seen_critical: set = field(default_factory=set)
    # serializes the edge-trigger update between concurrent inspections
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)
    # /status scrape cache: (monotonic ts, severity counts) — a
    # monitoring poller hitting /status every few seconds must not run
    # the full rule engine (and its transport/membership snapshot) per
    # scrape
    _status_cache: Optional[tuple] = field(default=None, repr=False)


STATUS_CACHE_TTL_S = 5.0


@dataclass(frozen=True)
class Finding:
    rule: str
    item: str        # what the finding is about (digest, device, peer)
    severity: str    # info | warning | critical
    value: str       # the observed value that crossed the threshold
    details: str     # human-readable diagnosis


class Rule:
    """One named diagnosis: metadata + the pure evaluation function."""

    __slots__ = ("name", "severity", "reference", "fn")

    def __init__(self, name: str, severity: str, reference: str,
                 fn: Callable) -> None:
        self.name = name
        self.severity = severity
        self.reference = reference
        self.fn = fn


RULES: dict[str, Rule] = {}


def rule(name: str, severity: str, reference: str):
    """Register one inspection rule. The metadata is mandatory and
    validated at import (lint_rules re-checks it in tier-1): a rule
    without a reference is a finding an operator cannot act on."""
    def deco(fn: Callable) -> Callable:
        if not name or not reference:
            raise ValueError(
                f"inspection rule needs name+reference, got {name!r}")
        if severity not in SEVERITIES:
            raise ValueError(
                f"inspection rule {name}: severity {severity!r} not in "
                f"{SEVERITIES}")
        if name in RULES:
            raise ValueError(f"inspection rule {name} already registered")
        RULES[name] = Rule(name, severity, reference, fn)
        return fn
    return deco


def lint_rules(rules: Optional[dict] = None) -> list[str]:
    """Registry hygiene (run by tests/test_metric_lint.py): every rule
    declares a kebab-case name, a valid severity and reference text."""
    findings: list[str] = []
    for name, r in (RULES if rules is None else rules).items():
        if not name or name != name.lower() or " " in name \
                or "_" in name:
            findings.append(f"rule {name!r}: name must be kebab-case")
        if getattr(r, "severity", None) not in SEVERITIES:
            findings.append(
                f"rule {name}: severity {getattr(r, 'severity', None)!r} "
                f"not in {SEVERITIES}")
        if not getattr(r, "reference", ""):
            findings.append(f"rule {name}: missing reference text")
        if not callable(getattr(r, "fn", None)):
            findings.append(f"rule {name}: fn is not callable")
    return findings


# ---- the snapshot rules evaluate over --------------------------------------

class InspectionContext:
    """One bounded point-in-time snapshot of every telemetry plane a
    rule may read. Built once per inspection run; rules never touch
    live state directly, so they stay pure and cheaply testable."""

    def __init__(self, storage) -> None:
        self.storage = storage
        self.cfg: DiagnosticsState = storage.diagnostics
        self.now = time.time()
        hist = storage.metrics_history
        ring = hist.snapshot()
        if self.cfg.history_windows > 0:
            ring = ring[-self.cfg.history_windows:]
        # the "now" point: live counters/gauges after a probe pass,
        # computed WITHOUT touching the ring (reads never mutate it)
        self.now_point = hist.sample_now(record=False)
        self.points = ring + [self.now_point]
        # exactly what the knobs document: window seconds =
        # history-windows x metrics-history-interval (no hidden floor)
        self.window_s = \
            float(self.cfg.history_windows) * float(hist.interval_s)
        self.events = storage.obs.events.snapshot()
        self.topsql = storage.obs.topsql
        self.waitprofile = storage.obs.waitprofile
        gov = getattr(storage, "governor", None)
        self.governor = gov.stats() if gov is not None else {}
        gate = getattr(storage, "admission", None)
        self.admission = gate.stats() if gate is not None else {}
        try:
            self.transport = storage.transport_health()
        except Exception:  # noqa: BLE001 — a dead leader mid-snapshot
            self.transport = {"mode": "unknown"}
        from .copr import mesh as _mesh
        client = _mesh.client_of(storage)
        self.mesh_client = client
        self.mesh = client.recorder.snapshot() if client is not None \
            else {"dispatches": [], "compiles": []}
        # workload-history regression findings, computed ONCE per
        # snapshot (both history rules read this list; an absent or
        # disabled history plane contributes nothing)
        hist = getattr(storage, "history", None)
        self.history_findings = hist.regression_findings() \
            if hist is not None and hist.enabled else []
        # keyspace heat findings, computed ONCE per snapshot (both heat
        # rules read this list; a disabled heat plane contributes
        # nothing — the [heatmap] zero-work contract)
        heat = getattr(storage, "heat", None)
        self.heat_findings = heat.findings() \
            if heat is not None and heat.enabled else []
        # hosted range rows (closed_ts / max_commit_ts / traffic), one
        # snapshot per inspection; no range plane armed = no rows = the
        # range rules stay silent on a healthy single-range server
        plane = getattr(storage, "ranges", None)
        try:
            self.ranges = plane.server.describe() \
                if plane is not None else []
        except Exception:  # noqa: BLE001 — plane closing mid-snapshot
            self.ranges = []

    # ---- helpers rules share -------------------------------------------
    def metric(self, labeled_name: str) -> float:
        """Current value of one flattened sample ('name{k="v"}')."""
        return float(self.now_point["values"].get(labeled_name, 0.0))

    def metric_family(self, family: str) -> dict[str, float]:
        """Current samples of one family: labeled name -> value."""
        out = {}
        for name, v in self.now_point["values"].items():
            if obs.split_sample_name(name, family) is not None:
                out[name] = float(v)
        return out

    def metric_delta(self, family: str) -> dict[str, float]:
        """Per-sample growth of a (cumulative) family across the
        considered history window. Needs at least one RING point as the
        baseline — with no history the delta is unknowable (process-
        global counters carry other servers' past), so it reports
        nothing rather than guessing."""
        if len(self.points) < 2:
            return {}
        base = self.points[0]["values"]
        out: dict[str, float] = {}
        for name, v in self.metric_family(family).items():
            d = float(v) - float(base.get(name, 0.0))
            if d > 0:
                out[name] = d
        return out

    def window_events(self, kind: str) -> list[dict]:
        """Ring events of one kind inside the rule window."""
        cutoff = self.now - self.window_s
        return [e for e in self.events
                if e["kind"] == kind and e.get("unix", 0.0) >= cutoff]

    def members(self) -> list[dict]:
        return [m for m in self.transport.get("members", [])
                if isinstance(m, dict)]


# ---- the shipped rules ------------------------------------------------------

def _labels_of(name: str) -> str:
    """'fam{k="v"}' -> 'k="v"' (the item text for labeled samples);
    family-agnostic cousin of obs.split_sample_name."""
    i = name.find("{")
    return name[i + 1:-1] if i >= 0 else ""


@rule("mesh-shard-skew", "warning",
      "mesh.skew-warn-ratio — sustained shard-row imbalance; rebalance "
      "the hot range or lower shard-threshold-rows "
      "(information_schema.tidb_mesh_shards)")
def _r_mesh_skew(ctx: InspectionContext) -> list[Finding]:
    client = ctx.mesh_client
    if client is None:
        return []
    thr = float(client.recorder.plane.cfg.skew_warn_ratio)
    if thr <= 0:
        return []
    cutoff = ctx.now - ctx.window_s
    out = []
    for e in ctx.mesh["dispatches"]:
        # sustained AND current: count/grade only the dispatches that
        # INDIVIDUALLY crossed the warn ratio INSIDE the rule window
        # (the recorder's (ts, skew) crossing ledger). The entry's
        # monotonic max_skew or a lifetime hit pile would let one old
        # spike escalate — or one fresh transient fire — forever.
        recent = [s for (t, s) in e.get("skew_hits", ())
                  if t >= cutoff]
        if len(recent) < ctx.cfg.skew_min_dispatches:
            continue
        worst = max(recent)
        sev = "critical" if worst >= 2 * thr else "warning"
        out.append(Finding(
            "mesh-shard-skew", e["digest"], sev, f"{worst:.2f}",
            f"{e['kind']} dispatch ({e['op'] or 'scan'}) max/mean "
            f"shard rows reached {worst:.2f} >= {thr:g} on "
            f"{len(recent)} of {e['dispatches']} dispatches in the "
            f"window; last rows={e['last_rows']}"))
    return out


@rule("mesh-recompile-storm", "warning",
      "kernel signature re-entering XLA compile (bucket/placement-mode "
      "churn); pin tile sizes or placement (/debug/mesh compile ring)")
def _r_recompile_storm(ctx: InspectionContext) -> list[Finding]:
    out = []
    for e in ctx.mesh["compiles"]:
        if not e.get("storm"):
            continue
        out.append(Finding(
            "mesh-recompile-storm", e["signature"], "warning",
            str(e["count"]),
            f"{e['kind']} kernel compiled {e['count']}x "
            f"({e['total_s']:.2f}s total); last key {e['last_key']}"))
    return out


@rule("mesh-hbm-watermark", "critical",
      "mesh.hbm-watermark-fraction — device HBM near capacity; shed "
      "resident epochs or raise mesh.hbm-bytes "
      "(information_schema.tidb_mesh_storage)")
def _r_hbm_watermark(ctx: InspectionContext) -> list[Finding]:
    out = []
    seen = set()
    # live level check first: a device that has sat above the
    # watermark since before the window emitted its (edge-triggered)
    # event long ago, but it is still the problem NOW
    client = ctx.mesh_client
    if client is not None:
        plane = client.recorder.plane
        if plane.mesh_built:
            cap = plane.device_capacity_bytes()
            if cap > 0:
                thr = cap * float(plane.cfg.hbm_watermark_fraction)
                for dev, b in sorted(plane.device_bytes().items()):
                    if b < thr:
                        continue
                    seen.add(f"device {dev}")
                    out.append(Finding(
                        "mesh-hbm-watermark", f"device {dev}",
                        "critical", str(int(b)),
                        f"{int(b)} live buffer bytes >= "
                        f"{plane.cfg.hbm_watermark_fraction:.0%} of "
                        f"{cap}-byte capacity"))
    # plus devices that crossed inside the window and have since
    # dropped (the recorder's edge-triggered event names them)
    for e in reversed(ctx.window_events("mesh_hbm_watermark")):
        item = e["detail"].split(":", 1)[0][:64] or "(device)"
        if item in seen:
            continue
        seen.add(item)
        out.append(Finding("mesh-hbm-watermark", item, "critical",
                           "", e["detail"]))
    return out


@rule("wal-fsync-stall", "warning",
      "storage.sync-log — WAL fsyncs stalling >=100ms; check disk "
      "contention or switch to sync-log=interval "
      "(tidb_events kind=fsync_stall)")
def _r_fsync_stall(ctx: InspectionContext) -> list[Finding]:
    stalls = ctx.window_events("fsync_stall")
    if len(stalls) < ctx.cfg.fsync_stall_threshold:
        return []
    return [Finding(
        "wal-fsync-stall", "wal", "warning", str(len(stalls)),
        f"{len(stalls)} fsync stalls inside {ctx.window_s:.0f}s "
        f"(threshold {ctx.cfg.fsync_stall_threshold}); last: "
        f"{stalls[-1]['detail']}")]


@rule("governor-kill", "warning",
      "performance.server-memory-limit — the memory governor killed "
      "statements; raise the limit or reduce concurrency "
      "(tidb_events kind=governor_kill)")
def _r_governor_kill(ctx: InspectionContext) -> list[Finding]:
    kills = ctx.window_events("governor_kill")
    if len(kills) < ctx.cfg.governor_kill_threshold:
        return []
    sev = "critical" if len(kills) >= 3 * ctx.cfg.governor_kill_threshold \
        else "warning"
    return [Finding(
        "governor-kill", "memory", sev, str(len(kills)),
        f"{len(kills)} governor kills inside {ctx.window_s:.0f}s "
        f"(limit {ctx.governor.get('limit_bytes', 0)} bytes, last "
        f"usage {ctx.governor.get('usage_bytes', 0)}); last victim: "
        f"{kills[-1]['detail'][:200]}")]


@rule("admission-shed", "warning",
      "performance.token-limit / admission-timeout-ms — waiters shed "
      "with errno 9003; raise token-limit or spread the workload "
      "(tidb_events kind=admission_shed)")
def _r_admission_shed(ctx: InspectionContext) -> list[Finding]:
    sheds = ctx.window_events("admission_shed")
    if len(sheds) < ctx.cfg.admission_shed_threshold:
        return []
    return [Finding(
        "admission-shed", "admission", "warning", str(len(sheds)),
        f"{len(sheds)} statements shed inside {ctx.window_s:.0f}s "
        f"(token limit {ctx.admission.get('token_limit', 0)}, queue "
        f"depth {ctx.admission.get('queue_depth', 0)}); last: "
        f"{sheds[-1]['detail'][:200]}")]


@rule("rpc-breaker-open", "critical",
      "transport.breaker-threshold — the RPC circuit breaker is open: "
      "the leader is unreachable and calls fail fast "
      "(/status transport breaker)")
def _r_breaker_open(ctx: InspectionContext) -> list[Finding]:
    state = str(ctx.transport.get("breaker", "closed"))
    if state == "closed":
        return []
    sev = "critical" if state == "open" else "warning"
    return [Finding(
        "rpc-breaker-open", str(ctx.transport.get("peer", "leader")),
        sev, state,
        f"circuit breaker {state} after "
        f"{ctx.transport.get('breaker_fail_streak', 0)} consecutive "
        f"budget-exhausted calls; last contact "
        f"{ctx.transport.get('last_contact_age_s')}s ago")]


@rule("follower-heartbeat-stale", "warning",
      "transport.lease-ms — a member's heartbeat is stale or down; "
      "check the peer process/network (/status transport members)")
def _r_heartbeat_stale(ctx: InspectionContext) -> list[Finding]:
    out = []
    thr_s = ctx.cfg.heartbeat_stale_ms / 1000.0
    for m in ctx.members():
        inst = str(m.get("addr") or m.get("role") or "?")
        down = m.get("down")
        if down:
            out.append(Finding(
                "follower-heartbeat-stale", inst, "critical",
                "down", f"member unreachable: {down}"))
            continue
        age = m.get("hb_age_s")
        if age is None or thr_s <= 0:
            continue
        if float(age) >= thr_s:
            sev = "critical" if float(age) >= 3 * thr_s else "warning"
            out.append(Finding(
                "follower-heartbeat-stale", inst, sev,
                f"{float(age):.1f}s",
                f"{m.get('role', 'member')} heartbeat age "
                f"{float(age):.1f}s >= "
                f"diagnostics.heartbeat-stale-ms {thr_s * 1000:.0f}ms"))
    return out


@rule("follower-apply-lag", "warning",
      "replica-read.apply-interval-ms — a serving replica's closed/"
      "applied timestamp is falling behind the leader; past 3x the "
      "warn threshold it has effectively stopped advancing and every "
      "routed read falls back to the leader (/debug/replicas, "
      "tidb_follower_apply_lag_seconds)")
def _r_follower_apply_lag(ctx: InspectionContext) -> list[Finding]:
    thr = float(ctx.cfg.apply_lag_warn_ms)
    if thr <= 0:
        return []
    out = []
    for m in ctx.members():
        if m.get("role") != "follower" or not m.get("serving"):
            continue
        lag = m.get("apply_lag_ms")
        if lag is None or float(lag) < thr:
            continue
        lag = float(lag)
        inst = str(m.get("addr") or "?")
        sev = "critical" if lag >= 3 * thr else "warning"
        out.append(Finding(
            "follower-apply-lag", inst, sev, f"{lag:.0f}ms",
            f"serving replica's applied ts is {lag:.0f}ms behind the "
            f"leader (warn threshold "
            f"{ctx.cfg.apply_lag_warn_ms}ms"
            + ("; the replica has stopped advancing — routed reads "
               "are falling back to the leader" if sev == "critical"
               else "") + ")"))
    return out


@rule("range-leader-flap", "warning",
      "ranges.lease-ms — one range's write leadership changed hands "
      "repeatedly inside the window (a clean failover is ONE "
      "transfer); leaders cannot hold their lease — check lease-ms "
      "against renewal latency and crash-looping hosts "
      "(tidb_events kind=range_transfer, tidb_range_transfers_total)")
def _r_range_leader_flap(ctx: InspectionContext) -> list[Finding]:
    moves = ctx.window_events("range_transfer")
    if len(moves) < ctx.cfg.range_flap_threshold:
        return []
    # every range_transfer detail leads with "r<id> " (rpc/ranged.py)
    per: dict = {}
    for e in moves:
        rid = str(e.get("detail", "")).split(" ", 1)[0] or "?"
        per.setdefault(rid, []).append(e)
    out = []
    for rid, evs in sorted(per.items()):
        if len(evs) < ctx.cfg.range_flap_threshold:
            continue
        out.append(Finding(
            "range-leader-flap", rid, "warning", str(len(evs)),
            f"range {rid} changed write leadership {len(evs)} times "
            f"inside {ctx.window_s:.0f}s (threshold "
            f"{ctx.cfg.range_flap_threshold}); last: "
            f"{evs[-1]['detail'][:200]}"))
    return out


@rule("range-split-flap", "warning",
      "diagnostics.split-flap-threshold / split-flap-window-s — one "
      "range kept splitting inside the window: the heat advisory "
      "keeps firing without the split draining the hotspot (the "
      "salted/monotonic hot-key symptom); splitting cannot help — "
      "fix the key design or raise ranges.split-cooldown-ms "
      "(tidb_events kind=range_split, tidb_range_splits_total)")
def _r_range_split_flap(ctx: InspectionContext) -> list[Finding]:
    thr = int(ctx.cfg.split_flap_threshold)
    if thr <= 0:
        return []
    # splits are cooldown-paced, so the rule carries its OWN window
    # (split-flap-window-s) instead of the shared history window
    win = float(ctx.cfg.split_flap_window_s) or ctx.window_s
    cutoff = ctx.now - win
    splits = [e for e in ctx.events
              if e["kind"] == "range_split"
              and e.get("unix", 0.0) >= cutoff]
    if len(splits) < thr:
        return []
    # every range_split detail leads with "r<parent> " (rpc/ranged.py)
    per: dict = {}
    for e in splits:
        rid = str(e.get("detail", "")).split(" ", 1)[0] or "?"
        per.setdefault(rid, []).append(e)
    out = []
    for rid, evs in sorted(per.items()):
        if len(evs) < thr:
            continue
        out.append(Finding(
            "range-split-flap", rid, "warning", str(len(evs)),
            f"range {rid} split {len(evs)} times inside {win:.0f}s "
            f"(threshold {thr}); last: {evs[-1]['detail'][:200]}"))
    return out


@rule("range-closed-ts-stall", "warning",
      "diagnostics.closed-ts-stall-ms — one range's published closed "
      "timestamp stopped advancing while its writes kept landing: a "
      "pending-commit ledger entry or an unresolved orphan lock is "
      "pinning it, and every range-aware replica read touching the "
      "range falls back to the leader (cluster_info range rows, "
      "/debug/ranges; tidb_events kind=orphan_resolved shows the "
      "resolver working the backlog)")
def _r_range_closed_ts_stall(ctx: InspectionContext) -> list[Finding]:
    thr = float(ctx.cfg.closed_ts_stall_ms)
    if thr <= 0 or not ctx.ranges:
        return []
    mem = ctx.cfg.closed_progress
    now_ms = ctx.now * 1000.0
    out = []
    live = set()
    for row in ctx.ranges:
        rid = str(row.get("range_id", "?"))
        live.add(rid)
        closed = int(row.get("closed_ts") or 0)
        # write progress independent of closed_ts: the commit floor
        # (always present) plus heat traffic (when armed). An IDLE
        # range with a static closed_ts is not a stall — there is
        # nothing to close past.
        mark = (int(row.get("max_commit_ts") or 0),
                int(row.get("write_rows") or 0))
        prev = mem.get(rid)
        if prev is None or closed != prev[0]:
            mem[rid] = (closed, now_ms, mark)
            continue
        stalled_ms = now_ms - float(prev[1])
        if mark == prev[2] or stalled_ms < thr:
            continue
        sev = "critical" if stalled_ms >= 3 * thr else "warning"
        out.append(Finding(
            "range-closed-ts-stall", rid, sev, f"{stalled_ms:.0f}ms",
            f"range {rid} closed_ts {closed} static for "
            f"{stalled_ms:.0f}ms while writes advanced "
            f"(commit floor {prev[2][0]} -> {mark[0]}, threshold "
            f"{ctx.cfg.closed_ts_stall_ms}ms"
            + ("; the range cannot close any newer timestamp — "
               "range-aware replica reads over it are all falling "
               "back to the leader" if sev == "critical" else "")
            + "); check for an orphaned lock or a lost txn_done "
            f"({row.get('pending', 0)} ledger entries pending)"))
    for rid in [r for r in mem if r not in live]:
        del mem[rid]
    return out


@rule("top-sql-host-fallback", "warning",
      "device-fragment gate — a digest's stage split is dominated by "
      "host_fallback (de-deviced query); see Session.last_engines / "
      "tests/test_device_path_lint.py for the gate reason")
def _r_host_fallback(ctx: InspectionContext) -> list[Finding]:
    if not ctx.topsql.enabled:
        return []
    frac = float(ctx.cfg.host_fallback_fraction)
    worst: dict[str, tuple] = {}
    for b in ctx.topsql.snapshot():
        # windowed like the event rules: Top SQL buckets only rotate
        # when statements arrive, so on an idle server an old bucket
        # (and its long-fixed de-deviced digest) survives indefinitely
        if b["start"] + ctx.topsql.window_s < ctx.now - ctx.window_s:
            continue
        ents = list(b["digests"].values())
        if b.get("other") is not None:
            ents.append(b["other"])
        for e in ents:
            host = float(e["stages"].get("host_fallback", 0.0))
            total = float(sum(e["stages"].values()))
            if host <= 0 or total <= 0 or host / total < frac:
                continue
            prev = worst.get(e["digest"])
            if prev is None or host / total > prev[0]:
                worst[e["digest"]] = (host / total, host,
                                      e["digest_text"])
    return [Finding(
        "top-sql-host-fallback", digest, "warning", f"{share:.0%}",
        f"host_fallback is {share:.0%} of the stage split "
        f"({host_s * 1e3:.1f}ms): {text[:200]}")
        for digest, (share, host_s, text) in sorted(worst.items())]


@rule("dominant-wait", "warning",
      "performance.wait-profile-enabled — a digest spends most of its "
      "wall time blocked in lock/lease contention (backoff.* or "
      "lease_wait), not executing; "
      "information_schema.tidb_wait_profile has the full typed split, "
      "diagnostics.dominant-wait-threshold tunes the cutoff")
def _r_dominant_wait(ctx: InspectionContext) -> list[Finding]:
    wp = ctx.waitprofile
    if not wp.enabled:
        return []
    thr = float(ctx.cfg.dominant_wait_threshold)
    worst: dict[str, tuple] = {}
    for b in wp.snapshot():
        # windowed like top-sql-host-fallback: wait buckets only
        # rotate when statements arrive, so an idle server would keep
        # reporting a long-fixed contention storm forever
        if b["start"] + wp.window_s < ctx.now - ctx.window_s:
            continue
        ents = list(b["digests"].values())
        if b.get("other") is not None:
            ents.append(b["other"])
        for e in ents:
            wall = float(e.get("sum_wall_s", 0.0))
            if wall <= 0:
                continue
            blocked = {k: v for k, v in e["waits"].items()
                       if k == "lease_wait" or k.startswith("backoff.")}
            share = min(sum(blocked.values()) / wall, 1.0)
            if not blocked or share < thr:
                continue
            top = max(blocked, key=lambda k: blocked[k])
            prev = worst.get(e["digest"])
            if prev is None or share > prev[0]:
                worst[e["digest"]] = (share, top,
                                      blocked[top], wall,
                                      e["digest_text"])
    return [Finding(
        "dominant-wait", digest, "warning", f"{share:.0%}",
        f"{share:.0%} of {wall * 1e3:.1f}ms wall spent blocked in "
        f"contention waits (heaviest: {top} {top_s * 1e3:.1f}ms): "
        f"{text[:200]}")
        for digest, (share, top, top_s, wall, text)
        in sorted(worst.items())]


@rule("registry-row-eval", "warning",
      "copr/funcs.py registry fallback — a scalar function "
      "de-vectorized onto the per-row path "
      "(tidb_registry_row_eval_total{func})")
def _r_registry_row_eval(ctx: InspectionContext) -> list[Finding]:
    out = []
    for name, d in sorted(ctx.metric_delta(
            "tidb_registry_row_eval_total").items()):
        if d < ctx.cfg.row_eval_threshold:
            continue
        item = _labels_of(name) or "(unlabeled)"
        out.append(Finding(
            "registry-row-eval", item, "warning", str(int(d)),
            f"{int(d)} rows evaluated per-row by the scalar-function "
            f"registry inside the window ({name}) — the expression "
            "left the vectorized path"))
    return out


@rule("metric-cardinality", "warning",
      "obs.lint_metrics — metric-hygiene finding at runtime (family "
      "wider than the mesh, malformed exposition, duplicate family)")
def _r_metric_lint(ctx: InspectionContext) -> list[Finding]:
    findings = obs.lint_metrics(
        [ctx.storage.obs.metrics, obs.PROCESS_METRICS])
    out = []
    for f in findings[:32]:  # bounded: a broken registry, not a flood
        item = f.split(":", 1)[0].removeprefix("metric ").strip()[:128]
        out.append(Finding("metric-cardinality", item or "(registry)",
                           "warning", "", f[:500]))
    return out


@rule("lock-order-inversion", "critical",
      "TIDB_TPU_LOCK_CHECK / [analysis] lock-check — the instrumented "
      "lock wrapper observed a lock-order cycle (potential deadlock) "
      "or a blocking syscall under a hot lock; /debug/lockgraph has "
      "the edges and sample stacks")
def _r_lock_order_inversion(ctx: InspectionContext) -> list[Finding]:
    # reads the PROCESS-wide lock graph, not the snapshot: the checker
    # is opt-in instrumentation (zero overhead when off), and its
    # findings are cumulative facts about this process's execution —
    # exactly what an inspection read should surface
    from .analysis import lockcheck
    if not lockcheck.enabled():
        return []
    out = []
    for f in lockcheck.findings():
        if f["kind"] == "lock-order-inversion":
            out.append(Finding(
                "lock-order-inversion", f["item"], "critical", "cycle",
                f"lock-order cycle observed at runtime: {f['item']} — "
                f"two threads acquiring these locks in opposite "
                f"orders can deadlock"))
        else:  # blocking-under-hot-lock
            out.append(Finding(
                "lock-order-inversion", f["item"], "warning",
                str(f.get("count", 1)),
                f"blocking syscall with a hot lock held "
                f"({f['item']}, x{f.get('count', 1)}): every peer of "
                f"that lock serializes behind the syscall"))
    return out


@rule("plan-regression", "warning",
      "history.regression-ratio — a digest executes a NEW plan at "
      "least this many times slower than the historical p50 of the "
      "plan it replaced (information_schema.tidb_plan_history names "
      "both plans; Session.last_engines / the plan_change event name "
      "the path that changed)")
def _r_plan_regression(ctx: InspectionContext) -> list[Finding]:
    out = []
    for f in ctx.history_findings:
        if f["rule"] == "plan-regression":
            out.append(Finding("plan-regression", f["item"],
                               f["severity"], f["value"], f["details"]))
    return out


@rule("stmt-perf-regression", "warning",
      "history.regression-ratio — a digest's latency drifted past the "
      "ratio against its own baseline windows ON THE SAME plan "
      "(information_schema.statements_summary_history has the "
      "window-by-window trajectory)")
def _r_stmt_perf_regression(ctx: InspectionContext) -> list[Finding]:
    out = []
    for f in ctx.history_findings:
        if f["rule"] == "stmt-perf-regression":
            out.append(Finding("stmt-perf-regression", f["item"],
                               f["severity"], f["value"], f["details"]))
    return out


@rule("config-sync-log", "warning",
      "storage.sync-log — off on a leader with live followers: acked "
      "commits can die with the machine while replicas follow them")
def _r_config_sync_log(ctx: InspectionContext) -> list[Finding]:
    if ctx.storage.sync_log != "off":
        return []
    if ctx.transport.get("mode") != "socket-leader":
        return []
    followers = [m for m in ctx.members()
                 if m.get("role") == "follower"]
    if not followers:
        return []
    return [Finding(
        "config-sync-log", "storage.sync-log", "warning", "off",
        f"leader runs sync-log=off with {len(followers)} live "
        "follower(s); a power loss can drop acked commits that "
        "followers already replicated")]


@rule("hot-range", "warning",
      "heatmap.hot-ratio / heatmap.sustained-buckets — one range "
      "serves at least hot-ratio x the fleet-median traffic for "
      "sustained-buckets consecutive heat buckets "
      "(information_schema.tidb_hot_ranges has the per-range matrix; "
      "/debug/keyviz renders it)")
def _r_hot_range(ctx: InspectionContext) -> list[Finding]:
    out = []
    for f in ctx.heat_findings:
        if f["rule"] == "hot-range":
            out.append(Finding("hot-range", f["item"],
                               f["severity"], f["value"], f["details"]))
    return out


@rule("range-split-advisory", "info",
      "heatmap.key-sample-cap — the within-range key that best halves "
      "a hot range's observed write traffic (its weighted-median "
      "sampled key); advisory only — add it to ranges.split-points "
      "to act on it")
def _r_range_split_advisory(ctx: InspectionContext) -> list[Finding]:
    out = []
    for f in ctx.heat_findings:
        if f["rule"] == "range-split-advisory":
            out.append(Finding("range-split-advisory", f["item"],
                               f["severity"], f["value"], f["details"]))
    return out


# ---- the engine -------------------------------------------------------------

def inspect(storage) -> list[Finding]:
    """Evaluate every registered rule over one snapshot of the given
    storage. Returns [] — WITHOUT building the snapshot or touching any
    rule — while diagnostics.enabled is false (the zero-work contract).
    A rule that raises degrades to an info finding naming itself; it
    never fails the query."""
    st: Optional[DiagnosticsState] = getattr(storage, "diagnostics",
                                             None)
    if st is None or not st.enabled:
        return []
    ctx = InspectionContext(storage)
    findings: list[Finding] = []
    for r in RULES.values():
        try:
            findings.extend(r.fn(ctx) or ())
        except Exception as e:  # noqa: BLE001 — diagnosis must not fail
            findings.append(Finding(
                r.name, "(rule)", "info", "error",
                f"rule raised {type(e).__name__}: {str(e)[:200]}"))
    _edge_trigger(storage, st, findings)
    return findings


def _edge_trigger(storage, st: DiagnosticsState,
                  findings: list[Finding]) -> None:
    """Record one inspection_finding event per (rule, item) the FIRST
    time it reports critical; a finding that clears and re-fires
    re-triggers. Level-triggered events would flood the ring on every
    inspection read."""
    crit = {(f.rule, f.item): f for f in findings
            if f.severity == "critical"}
    with st._lock:
        new = set(crit) - st.seen_critical
        st.seen_critical = set(crit)
    for key in sorted(new):
        f = crit[key]
        storage.obs.events.record(
            "inspection_finding", severity="critical",
            detail=f"{f.rule}: {f.item} {f.value} — "
                   f"{f.details}"[:500])


def _result_rows_of(findings: list[Finding]) -> list[list]:
    ordered = sorted(findings,
                     key=lambda f: (-_SEV_ORDER.get(f.severity, 0),
                                    f.rule, f.item))
    return [[f.rule, f.item, f.severity, f.value,
             RULES[f.rule].reference if f.rule in RULES else "",
             f.details] for f in ordered]


def _summary_rows_of(findings: list[Finding]) -> list[list]:
    by_rule: dict[str, list[Finding]] = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    rows = []
    for name, r in sorted(RULES.items()):
        fs = by_rule.get(name, [])
        worst = max((f.severity for f in fs),
                    key=lambda s: _SEV_ORDER.get(s, 0), default="")
        items = ",".join(sorted({f.item for f in fs}))[:256]
        rows.append([name, worst, len(fs), items, r.reference[:256]])
    return rows


def result_rows(storage) -> list[list]:
    """information_schema.inspection_result rows: (rule, item,
    severity, value, reference, details), worst severity first."""
    return _result_rows_of(inspect(storage))


def summary_rows(storage) -> list[list]:
    """information_schema.inspection_summary: one row per REGISTERED
    rule (finding count, worst observed severity, sample items) — the
    SQL-queryable view of the registry itself. Empty while disabled."""
    st = getattr(storage, "diagnostics", None)
    if st is None or not st.enabled:
        return []
    return _summary_rows_of(inspect(storage))


def result_and_summary_rows(storage) -> tuple[list[list], list[list]]:
    """Both inspection tables from ONE rule run — a statement that
    touches inspection_result AND inspection_summary must not pay two
    snapshot builds, and the two tables it reads must agree."""
    st = getattr(storage, "diagnostics", None)
    if st is None or not st.enabled:
        return [], []
    findings = inspect(storage)
    return _result_rows_of(findings), _summary_rows_of(findings)


def status_section(storage) -> dict:
    """The /status `inspection` section: enabled flag, rule count, and
    finding counts by severity. Zero rule work while disabled; counts
    are cached for STATUS_CACHE_TTL_S so a monitoring poller never
    turns the liveness endpoint into a per-scrape rule run."""
    st = getattr(storage, "diagnostics", None)
    enabled = bool(st is not None and st.enabled)
    out = {"enabled": enabled, "rules": len(RULES)}
    if not enabled:
        return out
    cached = st._status_cache
    now = time.monotonic()
    if cached is not None and now - cached[0] < STATUS_CACHE_TTL_S:
        out["findings"] = dict(cached[1])
        return out
    counts = {s: 0 for s in SEVERITIES}
    for f in inspect(storage):
        counts[f.severity] = counts.get(f.severity, 0) + 1
    st._status_cache = (now, dict(counts))
    out["findings"] = counts
    return out


def debug_payload(storage) -> dict:
    """The /debug/inspection JSON: settings + full findings + the
    per-rule summary — derived from ONE inspection run so the two
    sections of one payload can never disagree."""
    st = getattr(storage, "diagnostics", None)
    out: dict = {
        "enabled": bool(st is not None and st.enabled),
        "rules": sorted(RULES),
    }
    if not out["enabled"]:
        return out
    findings = inspect(storage)
    out["findings"] = [
        {"rule": r[0], "item": r[1], "severity": r[2], "value": r[3],
         "reference": r[4], "details": r[5]}
        for r in _result_rows_of(findings)]
    out["summary"] = [
        {"rule": r[0], "severity": r[1], "findings": r[2],
         "items": r[3], "reference": r[4]}
        for r in _summary_rows_of(findings)]
    return out


# ---- process-wide storage tracking (bench post-mortems) ---------------------

# every live Storage, weakly held: bench.py's flight child persists an
# inspection snapshot of whatever stores the flight built when it dies,
# so an rc=137/rc=124 leaves a diagnosis instead of just a tail
_STORAGES: "weakref.WeakSet" = weakref.WeakSet()


def track(storage) -> None:
    _STORAGES.add(storage)


def inspect_all() -> list[dict]:
    """One inspection snapshot per live tracked storage (best effort:
    a half-torn-down store contributes an error entry, never raises)."""
    out = []
    for st in list(_STORAGES):
        try:
            out.append({
                "path": st.path,
                "findings": [
                    {"rule": r[0], "item": r[1], "severity": r[2],
                     "value": r[3], "details": r[5]}
                    for r in result_rows(st)],
                "events": st.obs.events.snapshot()[-20:],
            })
        except Exception as e:  # noqa: BLE001 — post-mortem best effort
            out.append({"path": getattr(st, "path", None),
                        "error": f"{type(e).__name__}: {str(e)[:200]}"})
    return out


__all__ = ["DiagnosticsState", "Finding", "Rule", "RULES", "rule",
           "lint_rules", "InspectionContext", "inspect", "result_rows",
           "summary_rows", "status_section", "debug_payload", "track",
           "inspect_all"]
