"""A Chunk: an ordered batch of equal-length Columns.

Counterpart of reference util/chunk/chunk.go:32. Operators stream chunks of
bounded row count (reference uses 1024; we default to a TPU-tile-friendly
size at the coprocessor layer — see copr) and results are rendered back to
host scalars only at the edge.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, Sequence

import numpy as np

from .column import Column


@dataclass
class Chunk:
    columns: list[Column]

    def __post_init__(self) -> None:
        if self.columns:
            n = len(self.columns[0])
            if not all(len(c) == n for c in self.columns):
                raise ValueError("ragged chunk: column lengths differ")

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self.columns)

    def column(self, i: int) -> Column:
        return self.columns[i]

    def row(self, i: int) -> tuple[Any, ...]:
        return tuple(c.value_at(i) for c in self.columns)

    def iter_rows(self) -> Iterator[tuple[Any, ...]]:
        for i in range(self.num_rows):
            yield self.row(i)

    def to_pylist(self) -> list[tuple[Any, ...]]:
        return list(self.iter_rows())

    def take(self, indices: np.ndarray) -> "Chunk":
        return Chunk([c.take(indices) for c in self.columns])

    def slice(self, start: int, stop: int) -> "Chunk":
        idx = np.arange(start, stop)
        return self.take(idx)

    @staticmethod
    def concat(chunks: Sequence["Chunk"]) -> "Chunk":
        assert chunks
        if len(chunks) == 1:
            return chunks[0]
        ncols = chunks[0].num_cols
        if not all(ch.num_cols == ncols for ch in chunks):
            raise ValueError("Chunk.concat: column count mismatch")
        cols = []
        for ci in range(ncols):
            parts = [ch.columns[ci] for ch in chunks]
            first = parts[0]
            # single pass: remap foreign string dictionaries into the first
            # part's dictionary, then one concatenate over all parts
            datas = []
            for p in parts:
                if (
                    first.ftype.is_string
                    and first.dictionary is not None
                    and p.dictionary is not None
                    and p.dictionary is not first.dictionary
                ):
                    datas.append(first._remapped_data(p))
                else:
                    datas.append(p.data)
            data = np.concatenate(datas)
            if all(p.valid is None for p in parts):
                valid = None
            else:
                valid = np.concatenate([p.validity for p in parts])
            cols.append(Column(first.ftype, data, valid, first.dictionary))
        return Chunk(cols)
