"""Columnar storage: typed flat buffers + validity masks + string dictionaries.

Counterpart of the reference's Apache-Arrow-like chunk column (reference:
util/chunk/column.go:61 — null bitmap + offsets + flat data buffer), with two
TPU-first changes:

* Strings are dictionary-encoded as int32 codes against a shared, append-only
  per-table-column `Dictionary`. Any string predicate or collation-aware
  ordering is evaluated host-side ONCE over the (small) dictionary and then
  applied device-side as a gather over codes — the device never touches
  variable-length bytes.
* NULLs are a `bool` validity array (True = valid), not a packed bitmap:
  XLA fuses mask ops for free, and padding masks for static tiles reuse the
  same representation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Optional, Sequence

import numpy as np

from ..errno import ER_INVALID_JSON_TEXT, WARN_DATA_TRUNCATED, CodedError
from ..types.field_type import FieldType, TypeKind
from ..types.value import (
    Decimal,
    decode_date,
    decode_datetime,
    encode_date,
    encode_datetime,
    parse_date,
    parse_datetime,
)


class Dictionary:
    """Append-only string dictionary shared by all regions of a table column.

    Codes are NOT order-preserving (inserts append); ordering and range
    predicates are handled by computing per-code lookup tables host-side
    (see copr/kernels). Equality is exact on codes.
    """

    __slots__ = ("values", "_index", "_ci_cache", "_ci_len")

    def __init__(self, values: Optional[Iterable[str]] = None) -> None:
        self.values: list[str] = []
        self._index: dict[str, int] = {}
        self._ci_cache: Optional[dict[str, int]] = None
        self._ci_len = 0
        if values:
            for v in values:
                self.encode(v)

    def __len__(self) -> int:
        return len(self.values)

    def encode(self, s: str) -> int:
        code = self._index.get(s)
        if code is None:
            code = len(self.values)
            self.values.append(s)
            self._index[s] = code
        return code

    def lookup(self, s: str) -> int:
        """Code for s, or -1 if the string is absent (never matches equality)."""
        return self._index.get(s, -1)

    def decode(self, code: int) -> str:
        return self.values[code]

    def code_table(self, pred) -> np.ndarray:
        """bool[len(dict)] lookup table: pred evaluated over every dict value.

        This is how arbitrary string predicates (LIKE, >=, collation compares)
        become a single device-side gather.
        """
        return np.fromiter((pred(v) for v in self.values), dtype=bool,
                           count=len(self.values))

    def sort_ranks(self, ci: bool = False) -> np.ndarray:
        """int32[len(dict)] rank of each code in sorted order; device maps
        codes -> ranks to get order-correct comparisons. ci=True ranks by
        casefolded value (the *_ci collation family, reference:
        util/collate/collate.go:62)."""
        if ci:
            keyed = np.array([v.casefold() for v in self.values],
                             dtype=object)
        else:
            keyed = np.array(self.values, dtype=object)
        order = np.argsort(keyed, kind="stable")
        ranks = np.empty(len(self.values), dtype=np.int32)
        ranks[order] = np.arange(len(self.values), dtype=np.int32)
        return ranks

    def _ci_map(self) -> dict[str, int]:
        """casefolded value -> first (canonical) code; grown
        incrementally as the append-only dictionary grows, so repeated
        ci joins/IN-lists stay O(1) per probe."""
        m = self._ci_cache
        if m is None:
            m = {}
            self._ci_cache = m
            self._ci_len = 0
        for i in range(self._ci_len, len(self.values)):
            m.setdefault(self.values[i].casefold(), i)
        self._ci_len = len(self.values)
        return m

    def ci_canonical(self) -> np.ndarray:
        """int64[len(dict)] canonical code per code: the first code whose
        value casefolds equally. Grouping/joining ci-collated columns maps
        codes through this so 'A' and 'a' land together."""
        m = self._ci_map()
        return np.fromiter((m[v.casefold()] for v in self.values),
                           np.int64, count=len(self.values))

    def lookup_ci(self, s: str) -> int:
        """Canonical code of any value casefold-equal to s, or -1."""
        return self._ci_map().get(s.casefold(), -1)


class EnumDictionary(Dictionary):
    """Fixed, definition-ordered dictionary for ENUM columns: encode
    validates membership (case-insensitively, like MySQL) and sort order
    is definition order, not lexicographic (reference: ENUM compares by
    index, types/enum.go)."""

    __slots__ = ()

    def __init__(self, elems) -> None:
        super().__init__()
        for e in elems:
            Dictionary.encode(self, e)  # seed bypasses validation

    def encode(self, s: str) -> int:
        code = self._index.get(s)
        if code is not None:
            return code
        code = self.lookup_ci(s)
        if code < 0:
            raise TruncateError(
                f"Data truncated: invalid ENUM value {s!r}")
        return code

    def sort_ranks(self, ci: bool = False) -> np.ndarray:
        return np.arange(len(self.values), dtype=np.int32)



class TruncateError(CodedError, ValueError):
    """Value does not fit the column's domain (ENUM/SET membership)."""

    errno = WARN_DATA_TRUNCATED
    sqlstate = "01000"


class InvalidJSONError(CodedError, ValueError):
    errno = ER_INVALID_JSON_TEXT
    sqlstate = "22032"


@dataclass
class Column:
    """One typed column: flat numpy buffer + validity + optional dictionary."""

    ftype: FieldType
    data: np.ndarray
    valid: Optional[np.ndarray] = None  # None => all valid
    dictionary: Optional[Dictionary] = None

    def __len__(self) -> int:
        return len(self.data)

    @property
    def nbytes(self) -> int:
        """Buffer bytes held by this column (dictionary excluded: it is
        shared table state, not per-chunk working set)."""
        n = self.data.nbytes
        if self.valid is not None:
            n += self.valid.nbytes
        return n

    @property
    def validity(self) -> np.ndarray:
        if self.valid is None:
            return np.ones(len(self.data), dtype=bool)
        return self.valid

    def null_at(self, i: int) -> bool:
        return self.valid is not None and not self.valid[i]

    # ---- element access (render / host fallback path) ----------------------
    def value_at(self, i: int) -> Any:
        """Decode physical storage to a host scalar (None for NULL)."""
        if self.null_at(i):
            return None
        return decode_scalar(self.ftype, self.data[i], self.dictionary)

    def to_pylist(self) -> list[Any]:
        return [self.value_at(i) for i in range(len(self))]

    # ---- construction ------------------------------------------------------
    @staticmethod
    def empty(ftype: FieldType, dictionary: Optional[Dictionary] = None) -> "Column":
        return Column(ftype, np.empty(0, dtype=ftype.np_dtype), None, dictionary)

    @staticmethod
    def from_values(
        ftype: FieldType,
        values: Sequence[Any],
        dictionary: Optional[Dictionary] = None,
    ) -> "Column":
        """Encode host scalars into the physical layout.

        Accepts Python ints/floats/strs/Decimals/dates and string literals for
        temporal types. None encodes as NULL.
        """
        n = len(values)
        data = np.zeros(n, dtype=ftype.np_dtype)
        valid = np.ones(n, dtype=bool)
        if ftype.is_string and dictionary is None:
            dictionary = Dictionary()
        for i, v in enumerate(values):
            if v is None:
                valid[i] = False
                continue
            data[i] = _encode_scalar(ftype, v, dictionary)
        return Column(ftype, data, None if valid.all() else valid, dictionary)

    def take(self, indices: np.ndarray) -> "Column":
        return Column(
            self.ftype,
            self.data[indices],
            None if self.valid is None else self.valid[indices],
            self.dictionary,
        )

    def _remapped_data(self, other: "Column") -> np.ndarray:
        """other's codes re-encoded into self's dictionary (strings only)."""
        assert self.dictionary is not None and other.dictionary is not None
        if len(other.dictionary) == 0:
            # all-NULL column: placeholder codes, nothing to remap
            return other.data
        remap = np.fromiter(
            (self.dictionary.encode(v) for v in other.dictionary.values),
            dtype=np.int32,
            count=len(other.dictionary),
        )
        return remap[other.data]

    def append(self, other: "Column") -> "Column":
        if self.ftype.kind != other.ftype.kind or (
            self.ftype.is_decimal and self.ftype.scale != other.ftype.scale
        ):
            raise TypeError(f"append type mismatch: {self.ftype!r} vs {other.ftype!r}")
        other_data = other.data
        dictionary = self.dictionary or other.dictionary
        if (
            self.ftype.is_string
            and self.dictionary is not None
            and other.dictionary is not None
            and other.dictionary is not self.dictionary
        ):
            other_data = self._remapped_data(other)
            dictionary = self.dictionary
        data = np.concatenate([self.data, other_data])
        if self.valid is None and other.valid is None:
            valid = None
        else:
            valid = np.concatenate([self.validity, other.validity])
        return Column(self.ftype, data, valid, dictionary)


def decode_scalar(ftype: FieldType, raw: Any,
                  dictionary: Optional[Dictionary]) -> Any:
    """Physical cell value -> host scalar (the inverse of
    _encode_scalar; shared by Column.value_at and the point fast path's
    row decode, which reads physical tuples without ever building a
    Column)."""
    if raw is None:
        return None
    k = ftype.kind
    if k == TypeKind.SET:
        mask = int(raw)
        return ",".join(e for j, e in enumerate(ftype.elems)
                        if mask >> j & 1)
    if ftype.is_decimal:
        return Decimal(int(raw), ftype.scale)
    if k == TypeKind.DATE:
        return decode_date(int(raw))
    if k in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
        return decode_datetime(int(raw))
    if ftype.is_string:
        assert dictionary is not None
        return dictionary.decode(int(raw))
    if ftype.is_float:
        return float(raw)
    return int(raw)


def _encode_scalar(ftype: FieldType, v: Any, dictionary: Optional[Dictionary]) -> Any:
    """Host scalar -> physical representation for one cell."""
    k = ftype.kind
    if k == TypeKind.SET:
        if isinstance(v, (int, np.integer)):
            mask = int(v)
            if mask >> len(ftype.elems):
                raise ValueError(f"invalid SET bitmask {mask}")
            return mask
        lowered = {e.lower(): j for j, e in enumerate(ftype.elems)}
        mask = 0
        for part in str(v).split(","):
            part = part.strip()
            if not part:
                continue
            j = lowered.get(part.lower())
            if j is None:
                raise TruncateError(
                    f"Data truncated: invalid SET value {part!r}")
            mask |= 1 << j
        return mask
    if k == TypeKind.BIT:
        n = int(v)
        width = min(ftype.flen if ftype.flen > 0 else 1, 63)
        if n < 0 or n >> width:
            raise ValueError(f"BIT({width}) value {n} out of range")
        return n
    if k == TypeKind.JSON:
        import json as _json

        assert dictionary is not None
        s = v if isinstance(v, str) else _json.dumps(v)
        try:
            # normalize so equal documents encode to equal codes
            # (reference: types/json/binary.go canonical binary form)
            s = _json.dumps(_json.loads(s), sort_keys=True,
                            separators=(", ", ": "))
        except ValueError:
            raise InvalidJSONError(
                f"Invalid JSON text: {s[:40]!r}") from None
        return dictionary.encode(s)
    if ftype.is_decimal:
        if isinstance(v, Decimal):
            d = v.rescale(ftype.scale)
        elif isinstance(v, str):
            d = Decimal.parse(v).rescale(ftype.scale)
        elif isinstance(v, int):
            d = Decimal.from_int(v, ftype.scale)
        elif isinstance(v, float):
            # MySQL converts doubles via their decimal string form (shortest
            # repr), then rounds half away from zero
            d = Decimal.parse(repr(v)).rescale(ftype.scale)
        else:
            raise TypeError(f"cannot encode {type(v)} as {ftype!r}")
        if not (-(2**63) < d.unscaled < 2**63):
            raise OverflowError(f"decimal out of device range: {d}")
        return d.unscaled
    if k == TypeKind.DATE:
        if isinstance(v, str):
            return parse_date(v)
        if hasattr(v, "year") and not hasattr(v, "hour"):
            return encode_date(v)
        return int(v)
    if k in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
        if isinstance(v, str):
            return parse_datetime(v)
        if hasattr(v, "hour"):
            return encode_datetime(v)
        return int(v)
    if ftype.is_string:
        assert dictionary is not None
        return dictionary.encode(str(v))
    if ftype.is_float:
        if isinstance(v, Decimal):
            return v.to_float()
        return float(v)
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, float) and v.is_integer():
        return int(v)
    return int(v)
