from .column import Column, Dictionary
from .chunk import Chunk

__all__ = ["Column", "Dictionary", "Chunk"]
