from .engine import ExecContext, run_physical

__all__ = ["ExecContext", "run_physical"]
