"""Host execution engine: runs the root side of physical plans.

Counterpart of the reference's executor package (reference:
executor/executor.go Volcano Open/Next/Close; builder.go:99 dispatch) with a
TPU-first simplification: operators are chunk-at-a-time materialized rather
than pipelined iterators — the heavy lifting happened on the device; what
reaches the host is either partial-agg rows (small) or filtered row sets.
A streaming/spilling volcano loop comes with the memory-quota work.

Final aggregation merges device partials (reference P2: HashAggExec final
stage, executor/aggregate.go:146); joins/sorts are vectorized numpy
(reference: join.go/sort.go worker pools — replaced by array ops).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from .. import obs
from ..chunk.chunk import Chunk
from ..chunk.column import Column, Dictionary
from ..copr.client import CopClient
from ..copr.npeval import NumpyEval, _truthy
from ..plan.expr import AggDesc, Call, Col, Const, PlanExpr, ScalarSubq
from ..plan.physical import (
    PhysHashAgg,
    PhysHashJoin,
    PhysIndexJoin,
    PhysMergeJoin,
    PhysLimit,
    PhysIndexMerge,
    PhysPointGet,
    PhysProjection,
    PhysSelection,
    PhysSort,
    PhysUnion,
    PhysWindow,
    PhysTableRead,
    PhysicalPlan,
)
from ..store.storage import Transaction
from ..types.field_type import FieldType, TypeKind
from ..types.value import Decimal
from ..util import interrupt
from ..util.memory import MemTracker, QueryMemExceeded, SpillDir

_NULL_KEY = np.iinfo(np.int64).min


@dataclass
class ExecContext:
    txn: Transaction
    cop: CopClient
    stats: Optional[object] = None  # obs.RuntimeStatsColl for EXPLAIN ANALYZE
    mem: Optional[MemTracker] = None  # per-query quota tracker
    # statement-end hook (session uses it to unregister the tracker
    # root from the server-wide memory governor); runs exactly once
    on_close: Optional[object] = None

    def __post_init__(self) -> None:
        self._subq_cache: dict[int, Const] = {}
        if self.mem is None:
            self.mem = MemTracker()
        self._spill: Optional[SpillDir] = None

    @property
    def spill(self) -> SpillDir:
        if self._spill is None:
            self._spill = SpillDir()
        return self._spill

    def close(self) -> None:
        if self._spill is not None:
            self._spill.close()
            self._spill = None
        cb, self.on_close = self.on_close, None
        if cb is not None:
            cb()


def _overflow(ctx: ExecContext, est: int, label: str) -> bool:
    """True when `est` bytes don't fit the query quota and the operator
    should switch to its partitioned on-disk strategy; raises when the
    configured action is CANCEL (reference: util/memory/action.go:28 —
    spill actions vs PanicOnExceed)."""
    if not ctx.mem.over_budget(est):
        # admitted in memory: record the working set on the statement's
        # materialization ledger so the server-wide governor can rank
        # statements by weight (and MEM_MAX explains kills afterwards);
        # deliberately NOT consume() — quota/spill decisions unchanged
        ctx.mem.account(est)
        return False
    ctx.mem.check(est, label)  # raises under CANCEL
    ctx.mem.note_spill()
    if ctx.stats is not None and hasattr(ctx.stats, "note_spill"):
        ctx.stats.note_spill(label)
    return True


def _subst_subq(e: PlanExpr, ctx: ExecContext) -> PlanExpr:
    """Replace uncorrelated ScalarSubq nodes with materialized Consts.

    The subquery plan runs once per statement (reference evaluates
    uncorrelated scalar subqueries eagerly at rewrite time,
    planner/core/expression_rewriter.go)."""
    if isinstance(e, ScalarSubq):
        cached = ctx._subq_cache.get(id(e))
        if cached is not None:
            return cached
        chunk = run_physical(e.phys, ctx)
        if chunk.num_rows == 0 or not chunk.columns:
            const = Const(None, e.ftype)
        else:
            if chunk.num_rows > 1:
                raise ValueError("scalar subquery returned more than one row")
            col = chunk.columns[0]
            if not col.validity[0]:
                const = Const(None, e.ftype)
            elif col.dictionary is not None:
                const = Const(col.dictionary.decode(int(col.data[0])),
                              e.ftype)
            else:
                v = col.data[0]
                const = Const(float(v) if col.ftype.is_float else int(v),
                              e.ftype)
        ctx._subq_cache[id(e)] = const
        return const
    if isinstance(e, Call):
        new_args = [_subst_subq(a, ctx) for a in e.args]
        if all(n is o for n, o in zip(new_args, e.args)):
            return e
        return Call(e.op, new_args, e.ftype, e.extra)
    return e


# plan-node class -> the operator label the resource-attribution plane
# aggregates under (obs.StageRecorder op_wall / TopSQL / slow log);
# PhysTableRead refines by its pushed-down DAG tail, PhysFragmentRead's
# internals open their own finer-grained frames (copr/fragment.py)
_OP_LABELS = {
    "PhysFragmentRead": "fragment",
    "PhysPointGet": "point_get",
    "PhysIndexMerge": "index_merge",
    "PhysSelection": "filter",
    "PhysProjection": "project",
    "PhysHashAgg": "agg",
    "PhysSort": "sort",
    "PhysLimit": "limit",
    "PhysHashJoin": "join",
    "PhysMergeJoin": "join",
    "PhysIndexJoin": "join",
    "PhysUnion": "union",
    "PhysWindow": "window",
}


def _op_label(plan: PhysicalPlan) -> str:
    if isinstance(plan, PhysTableRead):
        dag = plan.dag
        if dag.agg is not None:
            return "scan+agg"
        if dag.topn is not None:
            return "scan+topn"
        return "scan"
    return _OP_LABELS.get(type(plan).__name__, "other")


def run_physical(plan: PhysicalPlan, ctx: ExecContext) -> Chunk:
    from .. import obs

    # always-on per-operator attribution: when a statement recorder is
    # installed (every session statement), each node runs under an
    # operator frame recording its EXCLUSIVE wall time + tagging the
    # dispatch stages/transfer bytes opened inside — the continuous
    # feed for Top SQL and the slow log's operator column. Cost is two
    # perf_counter reads and a dict update per plan node.
    rec = obs.active_stage_recorder()
    if ctx.stats is not None:
        import time as _time

        # attribute dispatch-stage time (staging/compile/transfer/
        # kernel/device_get/host_fallback) to this node, INCLUSIVE of
        # children — same convention as the node wall time
        before = rec.snapshot() if rec is not None else None
        t0 = _time.perf_counter()
        engine_tag = [None]
        with obs.operator(_op_label(plan)):
            chunk = _run_node(plan, ctx, engine_tag)
        stages = rec.delta_since(before) if rec is not None else None
        # mesh flight recorder: collect this node's per-shard dispatch
        # accounting (a no-op None on the single-device CopClient) —
        # feeds the EXPLAIN ANALYZE `mesh` column and the skew detector
        ctx.stats.record(plan, _time.perf_counter() - t0, chunk.num_rows,
                         engine_tag[0], stages=stages,
                         mesh=ctx.cop.take_mesh_note())
        return chunk
    if rec is not None:
        with obs.operator(_op_label(plan)):
            chunk = _run_node(plan, ctx, None)
        ctx.cop.take_mesh_note()
        return chunk
    chunk = _run_node(plan, ctx, None)
    ctx.cop.take_mesh_note()
    return chunk


def _run_node(plan: PhysicalPlan, ctx: ExecContext,
              engine_tag: Optional[list]) -> Chunk:
    interrupt.check()  # KILL QUERY checkpoint between plan nodes
    if isinstance(plan, PhysTableRead):
        if plan.dag.scan.table_id < 0:
            return Chunk([])  # dual pseudo-table: one conceptual row, no cols
        snap = ctx.txn.snapshot(plan.dag.scan.table_id)
        # placement-aware dispatch: the engine pins the mesh placement
        # (shard the epoch over the device mesh vs single-device) for
        # this node from the snapshot it just took, so every staging/
        # kernel decision below sees one consistent answer
        with ctx.cop.placement_scope(snap):
            result = ctx.cop.execute(plan.dag, snap)
        obs.note_engine(result.engine)
        if engine_tag is not None:
            engine_tag[0] = result.engine
        out = Chunk.concat(result.chunks) if result.chunks else \
            _empty_like(plan)
        if plan.dag.agg is None and plan.dag.topn is None and \
                plan.dag.limit is None and plan.dag.selection is not None:
            # scan-count feedback: the observed row count corrects the
            # histogram estimate for this exact conjunct set (reference:
            # statistics/feedback.go + handle/update.go:551)
            from ..plan.physical import conds_digest
            stats = ctx.txn.storage.stats
            stats.record_feedback(
                plan.dag.scan.table_id,
                conds_digest(plan.dag.selection.conditions), out.num_rows)
            # column-attributable predicates also correct the histogram
            # buckets / point estimates themselves
            stats.record_condition_feedback(
                plan.dag.scan.table_id, plan.dag.scan.col_offsets,
                plan.dag.selection.conditions, out.num_rows)
        return out
    from ..plan.fragment import PhysFragmentRead
    if isinstance(plan, PhysFragmentRead):
        from ..copr.fragment import execute_fragment
        snaps = {t.table.id: ctx.txn.snapshot(t.table.id)
                 for t in plan.frag.tables}
        for sm in plan.frag.semis:  # membership builds need snapshots too
            tid = sm.table.table.id
            if tid not in snaps:
                snaps[tid] = ctx.txn.snapshot(tid)
        result = execute_fragment(ctx.cop, plan.frag, snaps)
        obs.note_engine(result.engine)
        if engine_tag is not None:
            engine_tag[0] = result.engine
        if not result.chunks:
            return _empty_like(plan)
        return Chunk.concat(result.chunks)
    if isinstance(plan, PhysPointGet):
        return _run_point_get(plan, ctx)
    if isinstance(plan, PhysIndexMerge):
        return _run_index_merge(plan, ctx)
    if isinstance(plan, PhysUnion):
        return _run_union(plan, ctx)
    if isinstance(plan, PhysWindow):
        return _run_window(plan, ctx)
    if isinstance(plan, PhysSelection):
        child = run_physical(plan.children[0], ctx)
        ev = _evaluator(child)
        mask = np.ones(child.num_rows, dtype=bool)
        for c in plan.conditions:
            v, vl = ev.eval(_subst_subq(c, ctx))
            mask &= _truthy(np.asarray(v)) & vl
        return child.take(np.nonzero(mask)[0])
    if isinstance(plan, PhysProjection):
        child = run_physical(plan.children[0], ctx)
        ev = _evaluator(child)
        if not child.columns:
            ev.n = 1  # dual: constants evaluate to a single row
        cols = []
        for e, f in zip(plan.exprs, plan.schema.fields):
            e = _subst_subq(e, ctx)
            if f.ftype.is_string and not isinstance(e, Col):
                # computed strings cross dictionary domains: evaluate in the
                # string domain, re-encode into a fresh dictionary
                sv, svl = ev.eval_str(e)
                d = Dictionary()
                data = np.fromiter(
                    (d.encode(s) if ok else 0 for s, ok in zip(sv, svl)),
                    dtype=np.int32, count=ev.n)
                cols.append(Column(f.ftype, data,
                                   None if svl.all() else svl, d))
                continue
            v, vl = ev.eval(e)
            v = np.asarray(v)
            vl = np.asarray(vl)
            dictionary = None
            if f.ftype.is_string and isinstance(e, Col):
                dictionary = child.columns[e.idx].dictionary
            cols.append(Column(f.ftype, v.astype(f.ftype.np_dtype),
                               None if vl.all() else vl, dictionary))
        if not cols:
            # zero-column projection over pseudo table: one row
            return Chunk([])
        return Chunk(cols)
    if isinstance(plan, PhysHashAgg):
        return _run_agg(plan, ctx)
    if isinstance(plan, PhysSort):
        child = run_physical(plan.children[0], ctx)
        items = [(_subst_subq(e, ctx), d) for e, d in plan.items]
        est = child.nbytes + child.num_rows * 8 * max(1, len(items))
        if items and child.num_rows and _overflow(ctx, est, "Sort"):
            return _spill_sort(child, items, ctx)
        order = _sort_order(child, items)
        return child.take(order)
    if isinstance(plan, PhysLimit):
        child = run_physical(plan.children[0], ctx)
        start = min(plan.offset, child.num_rows)
        stop = min(plan.offset + plan.limit, child.num_rows)
        return child.slice(start, stop)
    if isinstance(plan, (PhysHashJoin, PhysMergeJoin)):
        # the merge join reuses the join driver: its single-key match is
        # the sort-free searchsorted alignment (_equi_match fast path)
        return _run_join(plan, ctx)
    if isinstance(plan, PhysIndexJoin):
        return _run_index_join(plan, ctx)
    raise TypeError(f"run_physical: unknown node {type(plan).__name__}")


def _gathered_chunk(snap, gathered, col_offsets, schema, conditions,
                    ctx: ExecContext) -> Chunk:
    """Shared fetch tail of the point-get and index-merge readers:
    assemble gathered columns into a chunk and apply the residual
    filter engine-side."""
    columns = []
    for (data, valid), off, f in zip(gathered, col_offsets,
                                     schema.fields):
        columns.append(Column(f.ftype, data,
                              None if valid.all() else valid,
                              snap.dictionaries[off]))
    chunk = Chunk(columns)
    if conditions and chunk.num_rows:
        ev = _evaluator(chunk)
        mask = np.ones(chunk.num_rows, dtype=bool)
        for c in conditions:
            v, vl = ev.eval(_subst_subq(c, ctx))
            mask &= _truthy(np.asarray(v)) & vl
        chunk = chunk.take(np.nonzero(mask)[0])
    return chunk


def _run_point_get(plan: PhysPointGet, ctx: ExecContext) -> Chunk:
    """Fetch rows by handle / unique key, then apply the residual filter
    (reference: executor/point_get.go Next; batch_point_get.go)."""
    from ..store.index import probe_and_gather

    snap = ctx.txn.snapshot(plan.table.id)
    if plan.handles is not None:
        handles = np.array(
            sorted({h for h in plan.handles if snap.has_handle(h)}),
            dtype=np.int64)
        gathered = snap.gather(handles, plan.col_offsets)
    else:
        handles, gathered = probe_and_gather(snap, plan.ranges,
                                             plan.col_offsets)
    return _gathered_chunk(snap, gathered, plan.col_offsets, plan.schema,
                           plan.conditions, ctx)


def _run_index_merge(plan: "PhysIndexMerge", ctx: ExecContext) -> Chunk:
    """Union every branch's handle set, gather once, re-check the full
    filter (reference: executor/index_merge_reader.go — the partial
    workers' union then table fetch, collapsed to vector ops). A branch
    with index=None carries literal pk-handle points."""
    from ..store.index import IndexSearcher

    snap = ctx.txn.snapshot(plan.table.id)
    found: list[np.ndarray] = []
    for r in plan.branches:
        if r.index is None:
            hs = np.array([h for (h,) in r.points if snap.has_handle(h)],
                          dtype=np.int64)
            found.append(hs)
            continue
        searcher = IndexSearcher(snap.store, snap, r.index)
        if r.interval is not None:
            lo, hi, li, hi_i = r.interval
            found.append(searcher.range(lo, hi, li, hi_i))
        else:
            found.extend(searcher.eq(p) for p in r.points)
    handles = (np.unique(np.concatenate(found)) if found
               else np.empty(0, dtype=np.int64))
    gathered = snap.gather(handles, plan.col_offsets)
    return _gathered_chunk(snap, gathered, plan.col_offsets, plan.schema,
                           plan.conditions, ctx)


def _empty_like(plan: PhysicalPlan) -> Chunk:
    return Chunk([
        Column(f.ftype, np.empty(0, f.ftype.np_dtype))
        for f in plan.schema.fields
    ])


def _evaluator(chunk: Chunk) -> NumpyEval:
    cols = [(c.data, c.validity) for c in chunk.columns]
    dicts = [c.dictionary for c in chunk.columns]
    return NumpyEval(cols, dicts, chunk.num_rows)


# ==================== union ====================

def _run_union(plan: "PhysUnion", ctx: ExecContext) -> Chunk:
    """UNION ALL: normalize each child chunk to the unified schema and
    concatenate (reference: executor union over children; DISTINCT is the
    aggregation the planner placed above)."""
    from ..chunk.column import Dictionary

    out_fields = plan.schema.fields
    shared_dicts = [Dictionary() if f.ftype.is_string else None
                    for f in out_fields]
    pieces: list[Chunk] = []
    for child in plan.children:
        chunk = run_physical(child, ctx)
        cols = []
        for i, f in enumerate(out_fields):
            src = chunk.columns[i] if i < len(chunk.columns) else None
            cols.append(_normalize_union_col(src, f.ftype, shared_dicts[i]))
        pieces.append(Chunk(cols))
    return Chunk.concat(pieces)


def _normalize_union_col(src, ft, shared_dict):
    """Convert a child column to the union's result type: decimal rescale,
    integer/float widening, dictionary re-encode into the shared dict."""
    if src is None:
        return Column(ft, np.empty(0, ft.np_dtype), None, shared_dict)
    data = src.data
    valid = src.validity
    if ft.is_string:
        # re-encode through the shared dictionary so codes unify
        if src.dictionary is not None:
            remap = np.fromiter(
                (shared_dict.encode(v) for v in src.dictionary.values),
                dtype=np.int32, count=len(src.dictionary))
            codes = remap[data] if len(remap) else np.zeros(len(data),
                                                           np.int32)
        else:
            codes = data.astype(np.int32)
        return Column(ft, codes, None if valid.all() else valid,
                      shared_dict)
    if ft.is_decimal:
        sscale = src.ftype.scale if src.ftype.is_decimal else 0
        d = data.astype(np.int64)
        if sscale < ft.scale:
            d = d * (10 ** (ft.scale - sscale))
        return Column(ft, d, None if valid.all() else valid)
    if ft.is_float:
        d = data.astype(np.float64)
        if src.ftype.is_decimal:
            d = d / (10 ** src.ftype.scale)
        return Column(ft, d, None if valid.all() else valid)
    return Column(ft, data.astype(ft.np_dtype),
                  None if valid.all() else valid)


# ==================== window functions ====================

def _run_window(plan: PhysWindow, ctx: ExecContext) -> Chunk:
    """Window computation over the child chunk (reference:
    executor/window.go): per item, sort by (partition, order keys),
    compute vectorized running/whole-partition values, scatter back to the
    original row order. Default frame semantics: with ORDER BY the value
    is cumulative with peers sharing results (RANGE UNBOUNDED
    PRECEDING..CURRENT ROW); without, the whole partition."""
    child = run_physical(plan.children[0], ctx)
    n = child.num_rows
    ev = _evaluator(child)
    out_cols = list(child.columns)
    for item, f in zip(plan.items,
                       plan.schema.fields[len(child.columns):]):
        data, valid = _window_values(item, f.ftype, child, ev, n, ctx)
        dictionary = None
        if f.ftype.is_string:
            # value-propagating funcs over a string column carry its
            # dictionary (builder gates out other string-typed windows)
            arg0 = item.args[0] if item.args else None
            if isinstance(arg0, Col):
                dictionary = child.columns[arg0.idx].dictionary
        out_cols.append(Column(f.ftype, data,
                               None if valid is None or valid.all()
                               else valid, dictionary))
    return Chunk(out_cols)


def _window_sort_keys(item, child, ev, n):
    """lexsort keys: order keys (last = primary is partition)."""
    keys = []
    for e, desc in reversed(item.order):
        v, vl = ev.eval(e)
        v = np.asarray(v)
        vl = np.asarray(vl)
        if e.ftype.is_string and isinstance(e, Col):
            d = child.columns[e.idx].dictionary
            if d is not None and len(d):
                ranks = d.sort_ranks(ci=e.ftype.is_ci)
                v = ranks[np.clip(v, 0, len(d) - 1)].astype(np.int64)
        if np.issubdtype(v.dtype, np.floating):
            key = np.where(vl, v.astype(np.float64), -np.inf)
        else:
            key = np.where(vl, v.astype(np.int64), _NULL_KEY + 1)
        keys.append(-key if desc else key)
    return keys


def _window_values(item, out_t, child, ev, n, ctx):
    # partition ids
    if item.partition:
        pcols = []
        for e in item.partition:
            v, vl = ev.eval(e)
            pcols.append((np.asarray(v), np.asarray(vl)))
        pid, _ = _group_ids(pcols, n)
    else:
        pid = np.zeros(n, np.int64)
    okeys = _window_sort_keys(item, child, ev, n)
    order = np.lexsort(tuple(okeys) + (pid,)) if (okeys or n) else         np.arange(n)
    pid_s = pid[order]
    iota = np.arange(n, dtype=np.int64)
    starts = np.r_[True, pid_s[1:] != pid_s[:-1]] if n else         np.zeros(0, bool)
    pstart = np.maximum.accumulate(np.where(starts, iota, 0)) if n else iota

    # peer groups: same partition AND same order-key values
    if item.order and n:
        peer_start = starts.copy()
        for k in okeys:
            ks = k[order]
            peer_start |= np.r_[True, ks[1:] != ks[:-1]]
    else:
        peer_start = starts.copy() if n else starts

    def last_of_peer():
        """index of the last row of each row's peer group (sorted order);
        without ORDER BY, the last row of the partition."""
        if n == 0:
            return iota
        boundary = peer_start if item.order else starts
        nxt = np.where(boundary, iota, n)
        nxt = np.r_[nxt[1:], n]
        nxt = np.minimum.accumulate(nxt[::-1])[::-1]
        return np.minimum(nxt - 1, n - 1)

    # per-row partition end + size (frame clipping, ntile, cume_dist)
    bnds = np.nonzero(starts)[0] if n else np.zeros(0, np.int64)
    pend = (np.r_[bnds[1:], n] - 1)[np.cumsum(starts) - 1] if n else iota
    psize = pend - pstart + 1 if n else iota

    name = item.func
    valid_out = None
    frame = getattr(item, "frame", None)
    if frame is not None and n and name in (
            "SUM", "COUNT", "AVG", "MIN", "MAX",
            "FIRST_VALUE", "LAST_VALUE", "NTH_VALUE"):
        fs, fe = _frame_bounds(frame, item, iota, pstart, pend,
                               peer_start, last_of_peer, okeys, order, n)
        vals, valid_out = _frame_agg(name, item, out_t, ev, order,
                                     fs, fe, n)
    elif name == "ROW_NUMBER":
        vals = (iota - pstart + 1).astype(np.int64)
    elif name == "RANK":
        first_peer = np.maximum.accumulate(
            np.where(peer_start, iota, 0)) if n else iota
        vals = (first_peer - pstart + 1).astype(np.int64)
    elif name == "DENSE_RANK":
        cp = np.cumsum(peer_start) if n else iota
        cp_at_start = cp[pstart] if n else cp
        vals = (cp - cp_at_start + 1).astype(np.int64)
    elif name in ("LEAD", "LAG"):
        av, avl = ev.eval(item.args[0])
        av = np.asarray(av)[order]
        avl = np.asarray(avl)[order]
        off = 1
        if len(item.args) > 1:
            off = int(_const_of(item.args[1]))
            if off < 0:
                raise ValueError(f"{name} offset must be non-negative")
        src = iota + (off if name == "LEAD" else -off)
        ok = (src >= 0) & (src < n)
        src_c = np.clip(src, 0, max(n - 1, 0))
        ok &= pid_s[src_c] == pid_s  # stay inside the partition
        vals = np.where(ok, av[src_c], 0)
        valid_s = np.where(ok, avl[src_c], False)
        if len(item.args) > 2:  # explicit default
            dv = _const_of(item.args[2])
            if dv is not None:
                if isinstance(dv, str):
                    arg0 = item.args[0]
                    d = child.columns[arg0.idx].dictionary \
                        if isinstance(arg0, Col) else None
                    if d is not None:
                        dv = d.encode(dv)
                    else:
                        # numeric column: coerce MySQL-style or reject
                        try:
                            dv = float(dv) if "." in dv else int(dv)
                        except ValueError:
                            raise ValueError(
                                f"{name} default {dv!r} does not coerce "
                                "to the column type") from None
                vals = np.where(ok, vals, dv)
                valid_s = valid_s | ~ok
        vals, valid_out = vals, valid_s
    elif name in ("FIRST_VALUE", "LAST_VALUE", "NTH_VALUE"):
        av, avl = ev.eval(item.args[0])
        av = np.asarray(av)[order]
        avl = np.asarray(avl)[order]
        if name == "NTH_VALUE":
            nth = int(_const_of(item.args[1]))
            if nth < 1:
                raise ValueError("NTH_VALUE position must be >= 1")
            idx = pstart + nth - 1
            # default frame end: peers with ORDER BY, else partition end
            end = last_of_peer() if item.order else pend
            ok = idx <= end
            idx = np.minimum(idx, np.maximum(end, pstart))
            vals = np.where(ok, av[idx], 0)
            valid_out = np.where(ok, avl[idx], False)
        else:
            idx = pstart if name == "FIRST_VALUE" else last_of_peer()
            vals = av[idx]
            valid_out = avl[idx]
    elif name == "NTILE":
        k = int(_const_of(item.args[0]))
        if k < 1:
            raise ValueError("NTILE argument must be >= 1")
        r = iota - pstart
        small = psize // k
        big = psize % k
        cut = big * (small + 1)
        with np.errstate(divide="ignore", invalid="ignore"):
            in_big = r < cut
            vals = np.where(
                in_big,
                r // np.maximum(small + 1, 1),
                big + np.where(small > 0, (r - cut) // np.maximum(small, 1),
                               0)) + 1
        vals = vals.astype(np.int64)
    elif name == "PERCENT_RANK":
        first_peer = np.maximum.accumulate(
            np.where(peer_start, iota, 0)) if n else iota
        rank = first_peer - pstart
        denom = np.maximum(psize - 1, 1)
        vals = np.where(psize > 1, rank / denom, 0.0)
    elif name == "CUME_DIST":
        vals = (last_of_peer() - pstart + 1) / np.maximum(psize, 1)
    else:  # SUM / COUNT / AVG / MIN / MAX
        func = name.lower()
        if item.args:
            av, avl = ev.eval(item.args[0])
            av = np.asarray(av)[order]
            avl = np.asarray(avl)[order]
        else:  # COUNT(*)
            av = np.ones(n, np.int64)
            avl = np.ones(n, bool)
        running = bool(item.order)
        cnts = _seg_cum(avl.astype(np.int64), starts, pstart, running)
        if func == "count":
            vals = cnts[last_of_peer()] if running and n else cnts
        elif func in ("sum", "avg"):
            if np.issubdtype(av.dtype, np.floating):
                masked = np.where(avl, av, 0.0)
            else:
                masked = np.where(avl, av.astype(np.int64), 0)
            sums = _seg_cum(masked, starts, pstart, running)
            if running and n:
                lp = last_of_peer()
                sums = sums[lp]
                cnts = cnts[lp]
            if func == "sum":
                vals = sums
                valid_out = cnts > 0
            else:
                col = _avg_column(
                    AggDesc("avg", item.args[0], out_t, False, ""),
                    out_t, sums, cnts)
                vals = col.data
                valid_out = col.validity
        else:  # min / max — running needs a segmented scan
            red = np.minimum if func == "min" else np.maximum
            if np.issubdtype(av.dtype, np.floating):
                sent = np.inf if func == "min" else -np.inf
                masked = np.where(avl, av, sent)
            else:
                sent = np.iinfo(np.int64).max if func == "min" else                     np.iinfo(np.int64).min
                masked = np.where(avl, av.astype(np.int64), sent)
            if running and n:
                vals = masked.copy()
                # segmented running reduce per partition slice
                bounds = np.nonzero(starts)[0]
                for b, e in zip(bounds, np.r_[bounds[1:], n]):
                    vals[b:e] = red.accumulate(masked[b:e])
                vals = vals[last_of_peer()]
            else:
                bounds = np.nonzero(starts)[0] if n else                     np.zeros(0, np.int64)
                totals = red.reduceat(masked, bounds) if n else masked
                seg = np.cumsum(starts) - 1 if n else iota
                vals = totals[seg] if n else masked
            valid_out = cnts[last_of_peer()] > 0 if running and n                 else (cnts > 0)
            vals = np.where(valid_out, vals, 0)

    out = np.zeros(n, dtype=out_t.np_dtype)
    out[order] = vals.astype(out_t.np_dtype)
    if valid_out is None:
        return out, None
    vo = np.zeros(n, bool)
    vo[order] = valid_out
    return out, vo


def _frame_bounds(frame, item, iota, pstart, pend, peer_start,
                  last_of_peer, okeys, order, n):
    """Inclusive frame [fs, fe] per row in sorted order (reference:
    executor/window.go frame builders rowFrameWindowProcessor /
    rangeFrameWindowProcessor). ROWS bounds are index arithmetic; RANGE
    bounds are key-offset searches within each partition's sorted run.
    Empty frames surface as fs > fe."""
    if frame.unit == "ROWS":
        def rows_bound(btype, val, is_start):
            if btype == "unbounded":
                return pstart
            if btype == "unbounded_following":
                return pend
            if btype == "current":
                return iota
            off = val if btype == "following" else -val
            return iota + off
        fs = rows_bound(frame.start_type, frame.start_value, True)
        fe = rows_bound(frame.end_type, frame.end_value, False)
        return np.maximum(fs, pstart), np.minimum(fe, pend)

    # RANGE: offsets move along the primary ORDER BY key; direction is
    # already folded into the encoded key (desc keys are negated), so
    # PRECEDING is always key - off in encoded space
    key = okeys[-1] if okeys else None  # primary key, pre-sort order
    key_s = key[order] if key is not None else None
    scale = 1
    if item.order and getattr(item.order[0][0].ftype, "is_decimal", False):
        scale = 10 ** item.order[0][0].ftype.scale

    def range_bound(btype, val, is_start):
        if btype == "unbounded":
            return pstart
        if btype == "unbounded_following":
            return pend
        if btype == "current":
            if is_start:  # first peer
                return np.maximum.accumulate(np.where(peer_start, iota, 0))
            return last_of_peer()
        off = val * scale * (1 if btype == "following" else -1)
        out = np.empty(n, np.int64)
        bnds = np.nonzero(np.r_[True, pstart[1:] != pstart[:-1]])[0]
        for b, e in zip(bnds, np.r_[bnds[1:], n]):
            seg = key_s[b:e]
            target = key_s[b:e] + off
            if is_start:
                out[b:e] = b + np.searchsorted(seg, target, side="left")
            else:
                out[b:e] = b + np.searchsorted(seg, target,
                                               side="right") - 1
        return out

    fs = range_bound(frame.start_type, frame.start_value, True)
    fe = range_bound(frame.end_type, frame.end_value, False)
    return np.maximum(fs, pstart), np.minimum(fe, pend)


def _sparse_minmax(vals, fs, fe, fn, empty):
    """Vectorized range min/max over inclusive [fs, fe] via a sparse
    table (O(n log n) build, O(1) per query)."""
    n = len(vals)
    table = [vals]
    k = 1
    while (1 << k) <= n:
        prev = table[-1]
        half = 1 << (k - 1)
        m = n - (1 << k) + 1
        table.append(fn(prev[:m], prev[half:half + m]))
        k += 1
    length = np.maximum(fe - fs + 1, 1)
    kq = np.floor(np.log2(length)).astype(np.int64)
    out = np.full(n, empty, dtype=vals.dtype)
    for kk in range(len(table)):
        mask = kq == kk
        if not mask.any():
            continue
        s = fs[mask]
        e = fe[mask]
        out[mask] = fn(table[kk][s], table[kk][e - (1 << kk) + 1])
    return out


def _frame_agg(name, item, out_t, ev, order, fs, fe, n):
    """Apply an aggregate/value function over per-row frames [fs, fe]
    (sorted order); returns (vals, valid) in sorted order."""
    nonempty = fs <= fe
    fs_c = np.minimum(fs, n - 1)
    fe_c = np.clip(fe, 0, n - 1)
    if item.args:
        av, avl = ev.eval(item.args[0])
        av = np.asarray(av)[order]
        avl = np.asarray(avl)[order]
    else:  # COUNT(*)
        av = np.ones(n, np.int64)
        avl = np.ones(n, bool)

    if name == "FIRST_VALUE":
        return (np.where(nonempty, av[fs_c], 0),
                np.where(nonempty, avl[fs_c], False))
    if name == "LAST_VALUE":
        return (np.where(nonempty, av[fe_c], 0),
                np.where(nonempty, avl[fe_c], False))
    if name == "NTH_VALUE":
        nth = int(_const_of(item.args[1]))
        if nth < 1:
            raise ValueError("NTH_VALUE position must be >= 1")
        idx = fs + nth - 1
        ok = nonempty & (idx <= fe)
        idx = np.clip(idx, 0, n - 1)
        return np.where(ok, av[idx], 0), np.where(ok, avl[idx], False)

    cnt_ps = np.r_[0, np.cumsum(avl.astype(np.int64))]
    cnts = np.where(nonempty, cnt_ps[fe_c + 1] - cnt_ps[fs_c], 0)
    if name == "COUNT":
        return cnts.astype(np.int64), None
    if name in ("SUM", "AVG"):
        if np.issubdtype(av.dtype, np.floating):
            masked = np.where(avl, av, 0.0)
        else:
            masked = np.where(avl, av.astype(np.int64), 0)
        ps = np.r_[masked.dtype.type(0), np.cumsum(masked)]
        sums = np.where(nonempty, ps[fe_c + 1] - ps[fs_c], 0)
        if name == "SUM":
            valid = cnts > 0
            return sums, valid
        col = _avg_column(AggDesc("avg", item.args[0], out_t, False, ""),
                          out_t, sums, cnts)
        return col.data, (col.validity if col.valid is not None
                          else cnts > 0)
    # MIN / MAX
    red = np.minimum if name == "MIN" else np.maximum
    if np.issubdtype(av.dtype, np.floating):
        sent = np.inf if name == "MIN" else -np.inf
        masked = np.where(avl, av, sent)
    else:
        sent = np.iinfo(np.int64).max if name == "MIN" else \
            np.iinfo(np.int64).min
        masked = np.where(avl, av.astype(np.int64), sent)
    vals = _sparse_minmax(masked, fs_c, fe_c, red, sent)
    valid = cnts > 0
    return np.where(valid, vals, 0), valid


def _seg_cum(vals, starts, pstart, running):
    """Per-partition cumulative (running) or total (not) sums."""
    n = len(vals)
    if n == 0:
        return vals
    cum = np.cumsum(vals)
    run = cum - cum[pstart] + vals[pstart]
    if running:
        return run
    # whole-partition totals: value of the run at the partition's last row
    bounds = np.nonzero(starts)[0]
    last = np.r_[bounds[1:], n] - 1
    seg = np.cumsum(starts) - 1
    return run[last][seg]


def _const_of(e):
    if isinstance(e, Const):
        return e.value
    raise ValueError("LEAD/LAG offset and default must be literals")


# ==================== aggregation ====================

def _run_agg(plan: PhysHashAgg, ctx: ExecContext) -> Chunk:
    child = run_physical(plan.children[0], ctx)
    if plan.mode == "final":
        return _merge_partials(plan, child)
    plan = PhysHashAgg(
        plan.mode,
        [_subst_subq(g, ctx) for g in plan.group_by],
        [AggDesc(d.func, None if d.arg is None else _subst_subq(d.arg, ctx),
                 d.ftype, d.distinct, d.name, d.params)
         for d in plan.aggs],
        plan.schema, plan.children)
    # group-id working set: sort order + unique + inverse over all rows
    if plan.group_by and child.num_rows and \
            _overflow(ctx, child.nbytes * 2, "HashAgg"):
        return _spill_agg(plan, child, ctx)
    return _complete_agg(plan, child)


def _spill_agg(plan: PhysHashAgg, child: Chunk, ctx: ExecContext) -> Chunk:
    """Hash-partitioned aggregation: rows split by group-key hash into
    on-disk partitions, each aggregated independently, results
    concatenated — group keys are disjoint across partitions, so the
    union of per-partition groups IS the global answer (the same
    disjointness the mesh hc-agg exchange relies on; reference:
    executor/aggregate.go spill + parallel partial workers)."""
    ev = _evaluator(child)
    n = child.num_rows
    enc = []
    for g in plan.group_by:
        if g.ftype.is_string and not isinstance(g, Col):
            sv, svl = ev.eval_str(g)
            e = np.fromiter(
                (hash(s) if ok else _NULL_KEY for s, ok in zip(sv, svl)),
                np.int64, count=n)
        else:
            v, vl = ev.eval(g)
            v = np.asarray(v)
            if g.ftype.is_string and isinstance(g, Col) and g.ftype.is_ci:
                d = child.columns[g.idx].dictionary
                if d is not None and len(d):
                    v = d.ci_canonical()[np.clip(v, 0, len(d) - 1)]
            if np.issubdtype(v.dtype, np.floating):
                e = v.astype(np.float64).view(np.int64)
            else:
                e = v.astype(np.int64)
            e = np.where(np.asarray(vl), e, _NULL_KEY)
        enc.append(e)
    stack = np.stack(enc, axis=1)
    need = child.nbytes * 2
    parts = int(min(64, max(2, -(-need * 2 // max(ctx.mem.available(), 1)))))
    pid = (_key_hash(stack) % np.uint64(parts)).astype(np.int64)
    del stack, enc, ev
    files = []
    for p in range(parts):
        idx = np.nonzero(pid == p)[0]
        if len(idx):
            files.append(ctx.spill.spill(child.take(idx)))
    del child, pid
    outs = []
    for f in files:
        part = f.read()
        ctx.mem.consume(part.nbytes)
        outs.append(_complete_agg(plan, part))
        ctx.mem.release(part.nbytes)
    if not outs:
        return _complete_agg(plan, Chunk([]))
    return Chunk.concat(outs)


def _group_ids(key_cols: list[tuple[np.ndarray, np.ndarray]], n: int):
    """(inverse ids, unique-first row indices); NULLs group together."""
    if not key_cols:
        return np.zeros(n, np.int64), np.zeros(1 if n else 0, np.int64)
    enc = []
    for v, vl in key_cols:
        v = np.asarray(v)
        if np.issubdtype(v.dtype, np.floating):
            e = v.astype(np.float64).view(np.int64)
        else:
            e = v.astype(np.int64)
        enc.append(np.where(vl, e, _NULL_KEY))
    stacked = np.stack(enc, axis=1)
    _, first, inv = np.unique(stacked, axis=0, return_index=True,
                              return_inverse=True)
    return inv.reshape(-1), first


def _merge_partials(plan: PhysHashAgg, child: Chunk) -> Chunk:
    """Merge device/host partials: [gk..., (val,cnt)...] -> final schema."""
    ngroups = len(plan.group_by)
    n = child.num_rows
    key_cols = [(child.columns[i].data, child.columns[i].validity)
                for i in range(ngroups)]
    inv, first = _group_ids(key_cols, n)
    n_seg = len(first)
    if n == 0:
        n_seg = 0
    order = np.argsort(inv[:n], kind="stable") if n else np.empty(0, np.int64)
    sorted_inv = inv[order]
    bounds = np.nonzero(np.r_[True, sorted_inv[1:] != sorted_inv[:-1]])[0] \
        if n else np.empty(0, np.int64)

    out_cols: list[Column] = []
    for gi in range(ngroups):
        src = child.columns[gi]
        f = plan.schema.fields[gi]
        gidx = order[bounds] if n else np.empty(0, np.int64)
        data = src.data[gidx]
        valid = src.validity[gidx]
        out_cols.append(Column(f.ftype, data.astype(f.ftype.np_dtype),
                               None if valid.all() else valid,
                               src.dictionary))

    from ..plan.dag import HLL_WORDS, agg_partial_starts
    starts = agg_partial_starts(plan.aggs, ngroups)
    for ai, d in enumerate(plan.aggs):
        out_t = plan.schema.fields[ngroups + ai].ftype
        if d.func == "approx_count_distinct":
            from ..copr.analyze import hll_ndv, hll_unpack_words
            words = np.stack(
                [child.columns[starts[ai] + w].data.astype(np.int64)
                 for w in range(HLL_WORDS)], axis=1)
            ccol = child.columns[starts[ai] + HLL_WORDS]
            cnts = _seg_reduce(np.add, ccol.data.astype(np.int64),
                               order, bounds)
            regs = hll_unpack_words(words)
            merged = _seg_reduce(np.maximum, regs, order, bounds) \
                if n else np.zeros((0, regs.shape[1]), np.int32)
            vals = np.array(
                [hll_ndv(merged[i], cnts[i]) if cnts[i] else 0
                 for i in range(len(cnts))], np.int64)
            out_cols.append(Column(out_t, vals))
            continue
        vcol = child.columns[starts[ai]]
        ccol = child.columns[starts[ai] + 1]
        cnts = _seg_reduce(np.add, ccol.data.astype(np.int64), order, bounds)
        if d.func == "count":
            out_cols.append(Column(out_t, cnts))
            continue
        vdata = vcol.data
        vvalid = vcol.validity
        if d.func in ("sum", "avg"):
            if np.issubdtype(vdata.dtype, np.floating):
                masked = np.where(vvalid, vdata, 0.0)
            else:
                masked = np.where(vvalid, vdata.astype(np.int64), 0)
            sums = _seg_reduce(np.add, masked, order, bounds)
            if d.func == "sum":
                valid = cnts > 0
                out_cols.append(Column(out_t, sums.astype(out_t.np_dtype),
                                       None if valid.all() else valid))
            else:
                out_cols.append(_avg_column(d, out_t, sums, cnts))
        elif d.func in ("min", "max"):
            if np.issubdtype(vdata.dtype, np.floating):
                sentinel = np.inf if d.func == "min" else -np.inf
                masked = np.where(vvalid, vdata, sentinel)
            else:
                sentinel = np.iinfo(np.int64).max if d.func == "min" else \
                    np.iinfo(np.int64).min
                masked = np.where(vvalid, vdata.astype(np.int64), sentinel)
            fn = np.minimum if d.func == "min" else np.maximum
            vals = _seg_reduce(fn, masked, order, bounds)
            valid = cnts > 0
            vals = np.where(valid, vals, 0)
            out_cols.append(Column(out_t, vals.astype(out_t.np_dtype),
                                   None if valid.all() else valid))
        else:
            raise NotImplementedError(d.func)
    if not out_cols:
        return Chunk([])
    if ngroups == 0 and (n == 0 or out_cols[0].data.shape[0] == 0):
        # scalar aggregate over empty input: one row (count=0, sums NULL)
        return _scalar_agg_empty_row(plan)
    return Chunk(out_cols)


class _RawDec(str):
    """Marker for an exact decimal literal inside a JSON aggregate: the
    value dumps as a tagged string, then _raw_dumps strips the quotes so
    the EXACT number lands in the document (json floats cap at ~17
    significant digits)."""


def _raw_dumps(o) -> str:
    import json as _json
    import re as _re
    s = _json.dumps(o, sort_keys=True, separators=(", ", ": "))
    return _re.sub(r'"\\u0000RAWD:(-?[0-9.]+)"', r"\1", s)


def _gc_render(v, ft) -> str:
    """GROUP_CONCAT element rendering (MySQL text form of the value)."""
    from ..types.value import decode_date
    if ft.is_decimal:
        s = ft.scale
        u = int(v)
        if s <= 0:
            return str(u)
        sign = "-" if u < 0 else ""
        u = abs(u)
        return f"{sign}{u // 10 ** s}.{u % 10 ** s:0{s}d}"
    if ft.kind == TypeKind.DATE:
        return decode_date(int(v)).isoformat()
    if ft.is_float:
        return repr(float(v))
    return str(int(v))


def _seg_reduce(ufunc, values: np.ndarray, order: np.ndarray,
                bounds: np.ndarray) -> np.ndarray:
    if len(order) == 0:
        return np.empty(0, dtype=values.dtype if values.dtype != bool
                        else np.int64)
    return ufunc.reduceat(values[order], bounds)


def _avg_column(d: AggDesc, out_t: FieldType, sums: np.ndarray,
                cnts: np.ndarray) -> Column:
    assert d.arg is not None
    at = d.arg.ftype
    valid = cnts > 0
    if out_t.is_float:
        vals = np.where(valid, sums / np.maximum(cnts, 1), 0.0)
        return Column(out_t, vals, None if valid.all() else valid)
    # exact decimal average via host bignum per group (group count is small)
    src_scale = at.scale if at.is_decimal else 0
    out = np.zeros(len(sums), dtype=np.int64)
    for i in range(len(sums)):
        if not valid[i]:
            continue
        q = Decimal(int(sums[i]), src_scale).div(
            Decimal.from_int(int(cnts[i])))
        out[i] = q.rescale(out_t.scale).unscaled
    return Column(out_t, out, None if valid.all() else valid)


def _scalar_agg_empty_row(plan: PhysHashAgg) -> Chunk:
    cols = []
    for ai, d in enumerate(plan.aggs):
        f = plan.schema.fields[len(plan.group_by) + ai]
        if d.func in ("count", "approx_count_distinct"):
            cols.append(Column(f.ftype, np.array([0], np.int64)))
        else:
            cols.append(Column(f.ftype, np.zeros(1, f.ftype.np_dtype),
                               np.array([False])))
    return Chunk(cols)


def _complete_agg(plan: PhysHashAgg, child: Chunk) -> Chunk:
    """Host-only aggregation over an operator output chunk."""
    ev = _evaluator(child)
    n = child.num_rows
    key_vv = []
    key_dicts: list[Optional[Dictionary]] = []
    for g in plan.group_by:
        if g.ftype.is_string and not isinstance(g, Col):
            # computed string key (e.g. substring): group on fresh codes
            sv, svl = ev.eval_str(g)
            d = Dictionary()
            codes = np.fromiter(
                (d.encode(s) if ok else 0 for s, ok in zip(sv, svl)),
                np.int64, count=n)
            key_vv.append((codes, np.asarray(svl)))
            key_dicts.append(d)
        else:
            v, vl = ev.eval(g)
            v = np.asarray(v)
            d = child.columns[g.idx].dictionary \
                if g.ftype.is_string and isinstance(g, Col) else None
            if d is not None and len(d) and g.ftype.is_ci:
                # ci collation: group on canonical codes so case
                # variants merge; output shows the first-seen spelling
                v = d.ci_canonical()[np.clip(v, 0, len(d) - 1)]
            key_vv.append((v, np.asarray(vl)))
            key_dicts.append(d)
    inv, first = _group_ids(key_vv, n)
    n_seg = len(first) if n else 0
    order = np.argsort(inv[:n], kind="stable") if n else np.empty(0, np.int64)
    sorted_inv = inv[order]
    bounds = np.nonzero(np.r_[True, sorted_inv[1:] != sorted_inv[:-1]])[0] \
        if n else np.empty(0, np.int64)

    out_cols: list[Column] = []
    ngroups = len(plan.group_by)
    for gi, g in enumerate(plan.group_by):
        v, vl = key_vv[gi]
        f = plan.schema.fields[gi]
        gidx = order[bounds] if n else np.empty(0, np.int64)
        dictionary = key_dicts[gi]
        data = v[gidx]
        valid = vl[gidx]
        out_cols.append(Column(f.ftype, data.astype(f.ftype.np_dtype),
                               None if valid.all() else valid, dictionary))

    for ai, d in enumerate(plan.aggs):
        out_t = plan.schema.fields[ngroups + ai].ftype
        if d.arg is None:  # count(*)
            ones = np.ones(n, np.int64)
            cnts = _seg_reduce(np.add, ones, order, bounds)
            out_cols.append(Column(out_t, cnts))
            continue
        if d.func in ("json_arrayagg", "json_objectagg"):
            import json as _json
            from ..chunk.column import Dictionary as _Dct

            def jvals(e):
                """Per-row python JSON values for one expression."""
                if e.ftype.kind == TypeKind.JSON or e.ftype.is_string:
                    sv, svl = ev.eval_str(e)
                    if e.ftype.kind == TypeKind.JSON:
                        return [
                            _json.loads(s) if ok else None
                            for s, ok in zip(sv, svl)], np.asarray(svl)
                    return [s if ok else None
                            for s, ok in zip(sv, svl)], np.asarray(svl)
                vv, vl = ev.eval(e)
                vv = np.asarray(vv)
                out = []
                for i2 in range(n):
                    if not vl[i2]:
                        out.append(None)
                    elif e.ftype.is_decimal:
                        # exact: a float division would round >15
                        # significant digits; _RawDec embeds the exact
                        # literal at dump time
                        out.append(_RawDec(
                            "\x00RAWD:" + _gc_render(int(vv[i2]),
                                                     e.ftype)))
                    elif e.ftype.kind == TypeKind.DATE:
                        from ..types.value import decode_date
                        out.append(decode_date(int(vv[i2])).isoformat())
                    elif e.ftype.kind in (TypeKind.DATETIME,
                                          TypeKind.TIMESTAMP):
                        from ..types.value import decode_datetime
                        out.append(decode_datetime(int(vv[i2])).isoformat(
                            sep=" "))
                    elif e.ftype.is_float:
                        out.append(float(vv[i2]))
                    else:
                        out.append(int(vv[i2]))
                return out, np.asarray(vl)

            if d.func == "json_arrayagg":
                vals_py, _vl = jvals(d.arg)
                groups: list[list] = [[] for _ in range(n_seg)]
                for i2 in range(n):
                    # SQL NULLs become JSON nulls (MySQL semantics,
                    # func_json_arrayagg.go)
                    groups[inv[i2]].append(vals_py[i2])
                docs = [_raw_dumps(g2) for g2 in groups]
            else:
                keys_py, kvl = jvals(d.arg.args[0])
                vals_py, _vl = jvals(d.arg.args[1])
                objs: list[dict] = [{} for _ in range(n_seg)]
                for i2 in range(n):
                    if not kvl[i2]:
                        from ..session.session import SQLError
                        raise SQLError(
                            "JSON documents may not contain NULL member "
                            "names", errno=3158)
                    objs[inv[i2]][str(keys_py[i2])] = vals_py[i2]
                docs = [_raw_dumps(o) for o in objs]
            dct = _Dct()
            data = np.fromiter((dct.encode(s) for s in docs),
                               np.int64, count=n_seg)
            out_cols.append(Column(out_t, data, None, dct))
            continue
        av, avl = ev.eval(d.arg)
        av = np.asarray(av)
        avl = np.asarray(avl)
        if d.distinct:
            vals = _distinct_agg(d, av, avl, inv, n_seg, out_t)
            out_cols.append(vals)
            continue
        cnts = _seg_reduce(np.add, avl.astype(np.int64), order, bounds)
        if d.func == "count":
            out_cols.append(Column(out_t, cnts))
            continue
        if d.func == "approx_count_distinct":
            from ..copr.analyze import hll_group_registers_host, hll_ndv
            hsrc = _hll_hash_src(d, av, child)
            regs = hll_group_registers_host(hsrc, avl, inv, n_seg)
            vals = np.array(
                [hll_ndv(regs[i], cnts[i]) if cnts[i] else 0
                 for i in range(n_seg)], np.int64)
            out_cols.append(Column(out_t, vals))
            continue
        if d.func == "approx_percentile":
            # per-group percentile: the value at ceil(p% * n) in sort
            # order (reference: executor/aggfuncs/func_percentile.go
            # picks an element, not an interpolation)
            pct = float(d.params[0]) if d.params else 50.0
            vals = np.zeros(n_seg, av.dtype if not np.issubdtype(
                av.dtype, np.bool_) else np.int64)
            valid = np.zeros(n_seg, bool)
            srt_v = av[order]
            srt_l = avl[order]
            # rows are grouped contiguously along `order`; per-segment
            # slices keep this O(n log n) overall
            for gi2 in range(n_seg):
                lo = bounds[gi2]
                hi = bounds[gi2 + 1] if gi2 + 1 < n_seg else n
                g = np.sort(srt_v[lo:hi][srt_l[lo:hi]])
                if len(g):
                    k = max(int(np.ceil(pct / 100.0 * len(g))) - 1, 0)
                    vals[gi2] = g[k]
                    valid[gi2] = True
            out_cols.append(Column(out_t, vals.astype(out_t.np_dtype),
                                   None if valid.all() else valid))
            continue
        if d.func in ("sum", "avg"):
            if np.issubdtype(av.dtype, np.floating):
                masked = np.where(avl, av, 0.0)
            else:
                masked = np.where(avl, av.astype(np.int64), 0)
            sums = _seg_reduce(np.add, masked, order, bounds)
            if d.func == "sum":
                valid = cnts > 0
                out_cols.append(Column(out_t, sums.astype(out_t.np_dtype),
                                       None if valid.all() else valid))
            else:
                out_cols.append(_avg_column(d, out_t, sums, cnts))
            continue
        if d.func in ("min", "max"):
            is_f = np.issubdtype(av.dtype, np.floating)
            if d.func == "min":
                sentinel = np.inf if is_f else np.iinfo(np.int64).max
                fn = np.minimum
            else:
                sentinel = -np.inf if is_f else np.iinfo(np.int64).min
                fn = np.maximum
            masked = np.where(avl, av if is_f else av.astype(np.int64),
                              sentinel)
            vals = _seg_reduce(fn, masked, order, bounds)
            valid = cnts > 0
            vals = np.where(valid, vals, 0)
            dictionary = None
            if out_t.is_string and isinstance(d.arg, Col):
                dictionary = child.columns[d.arg.idx].dictionary
                if dictionary is not None and len(dictionary):
                    # min/max over dict codes is order-wrong; use ranks
                    ranks = dictionary.sort_ranks(ci=d.arg.ftype.is_ci)
                    rank_of = ranks[np.clip(av, 0, len(dictionary) - 1)]
                    masked_r = np.where(avl, rank_of.astype(np.int64),
                                        sentinel)
                    best_rank = _seg_reduce(fn, masked_r, order, bounds)
                    inv_rank = np.argsort(ranks)
                    vals = inv_rank[np.clip(best_rank, 0,
                                            len(dictionary) - 1)]
                    vals = np.where(valid, vals, 0)
            out_cols.append(Column(out_t, vals.astype(out_t.np_dtype),
                                   None if valid.all() else valid,
                                   dictionary))
            continue
        if d.func in ("std", "stddev", "stddev_pop", "stddev_samp",
                      "variance", "var_pop", "var_samp"):
            # population/sample moments (reference:
            # executor/aggfuncs/func_varpop.go): sum + sum of squares
            scale = 10.0 ** d.arg.ftype.scale if d.arg.ftype.is_decimal \
                else 1.0
            fv = np.where(avl, av.astype(np.float64) / scale, 0.0)
            sums = _seg_reduce(np.add, fv, order, bounds)
            sqs = _seg_reduce(np.add, fv * fv, order, bounds)
            mean = sums / np.maximum(cnts, 1)
            var = sqs / np.maximum(cnts, 1) - mean * mean
            var = np.maximum(var, 0.0)
            samp = d.func in ("stddev_samp", "var_samp")
            if samp:
                var = np.where(cnts > 1,
                               var * cnts / np.maximum(cnts - 1, 1), 0.0)
            if d.func in ("std", "stddev", "stddev_pop", "stddev_samp"):
                var = np.sqrt(var)
            valid = cnts > (1 if samp else 0)
            out_cols.append(Column(out_t, var,
                                   None if valid.all() else valid))
            continue
        if d.func in ("bit_and", "bit_or", "bit_xor"):
            # never NULL; empty-group identities match MySQL (reference:
            # executor/aggfuncs/func_bitfuncs.go)
            ident = -1 if d.func == "bit_and" else 0
            fn = {"bit_and": np.bitwise_and, "bit_or": np.bitwise_or,
                  "bit_xor": np.bitwise_xor}[d.func]
            masked = np.where(avl, av.astype(np.int64), ident)
            vals = _seg_reduce(fn, masked, order, bounds)
            out_cols.append(Column(out_t, vals.astype(np.int64)))
            continue
        if d.func == "any_value":
            gidx = order[bounds] if n else np.empty(0, np.int64)
            dictionary = child.columns[d.arg.idx].dictionary \
                if out_t.is_string and isinstance(d.arg, Col) else None
            vals = av[gidx]
            valid = avl[gidx]
            out_cols.append(Column(out_t, vals.astype(out_t.np_dtype),
                                   None if valid.all() else valid,
                                   dictionary))
            continue
        if d.func == "group_concat":
            if d.arg.ftype.is_string:
                sv, svl = ev.eval_str(d.arg)
            else:
                sv, svl = [_gc_render(x, d.arg.ftype) for x in av], avl
            dct = Dictionary()
            data = np.zeros(n_seg, np.int64)
            valid = np.zeros(n_seg, bool)
            parts: list[list[str]] = [[] for _ in range(n_seg)]
            for i in range(n):
                if svl[i]:
                    parts[inv[i]].append(str(sv[i]))
            for gi2 in range(n_seg):
                if parts[gi2]:
                    data[gi2] = dct.encode(",".join(parts[gi2]))
                    valid[gi2] = True
            out_cols.append(Column(out_t, data,
                                   None if valid.all() else valid, dct))
            continue
        raise NotImplementedError(d.func)
    if not out_cols:
        return Chunk([])
    if ngroups == 0 and (n == 0):
        return _scalar_agg_empty_row(plan)
    return Chunk(out_cols)


def _hll_hash_src(d: AggDesc, av: np.ndarray, child: Chunk) -> np.ndarray:
    """uint32 hash input per row for host-side APPROX_COUNT_DISTINCT.

    Integers in int32 range use their low 32 bits — bit-identical to the
    device sketch (copr/client.agg_partials), so the two paths agree.
    Wider ints and floats fold high bits in (plain truncation would
    collide every integral-valued double); dictionary strings hash the
    string bytes, stable across partition dictionaries."""
    import zlib
    if d.arg.ftype.is_string and isinstance(d.arg, Col):
        dct = child.columns[d.arg.idx].dictionary
        if dct is not None and len(dct):
            entry = np.array(
                [zlib.crc32(s.encode("utf-8")) for s in dct.values],
                np.uint32)
            return entry[np.clip(av.astype(np.int64), 0, len(dct) - 1)]
        return av.astype(np.int64).astype(np.uint32)
    from ..copr.analyze import float_bits_key, hll_hash_src_int
    if np.issubdtype(av.dtype, np.floating):
        bits = float_bits_key(av).view(np.uint64)
        return ((bits ^ (bits >> np.uint64(32))) &
                np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hll_hash_src_int(av)


def _distinct_agg(d: AggDesc, av, avl, inv, n_seg, out_t: FieldType) -> Column:
    is_float = np.issubdtype(av.dtype, np.floating)
    if is_float:
        # dedup on exact bit patterns (copr/analyze.float_bits_key
        # normalizes -0.0 so it equals 0.0)
        from ..copr.analyze import float_bits_key
        enc = float_bits_key(av)
    else:
        enc = av.astype(np.int64)
    enc = np.where(avl, enc, _NULL_KEY)
    pairs = np.stack([inv, enc], axis=1)[avl]
    if out_t.is_float:
        out = np.zeros(n_seg, np.float64)
    else:
        out = np.zeros(n_seg, np.int64)
    if len(pairs):
        upairs = np.unique(pairs, axis=0)
        if d.func == "count":
            segs, c = np.unique(upairs[:, 0], return_counts=True)
            out[segs] = c
        elif d.func == "sum":
            order2 = np.argsort(upairs[:, 0], kind="stable")
            sp = upairs[order2]
            b2 = np.nonzero(np.r_[True, sp[1:, 0] != sp[:-1, 0]])[0]
            vals = sp[:, 1].copy().view(np.float64) if is_float else sp[:, 1]
            sums = np.add.reduceat(vals, b2)
            out[sp[b2, 0]] = sums
        else:
            raise NotImplementedError(f"distinct {d.func}")
    return Column(out_t, out.astype(out_t.np_dtype))


# ==================== sort ====================

def _sort_key(chunk: Chunk, e: PlanExpr, desc: bool,
              ev: Optional[NumpyEval] = None) -> np.ndarray:
    """One encoded sort key: larger-encodes-later, desc folded in, NULLs
    first (MySQL NULL ordering)."""
    if ev is None:
        ev = _evaluator(chunk)
    v, vl = ev.eval(e)
    v = np.asarray(v)
    vl = np.asarray(vl)
    if e.ftype.is_string and isinstance(e, Col):
        d = chunk.columns[e.idx].dictionary
        if d is not None and len(d):
            ranks = d.sort_ranks(ci=e.ftype.is_ci)
            v = ranks[np.clip(v, 0, len(d) - 1)].astype(np.int64)
    if np.issubdtype(v.dtype, np.floating):
        key = np.where(vl, v.astype(np.float64), -np.inf)
    else:
        key = np.where(vl, v.astype(np.int64), _NULL_KEY + 1)
    return -key if desc else key


def _sort_order(chunk: Chunk, items: list[tuple[PlanExpr, bool]]) -> np.ndarray:
    ev = _evaluator(chunk)
    keys = [_sort_key(chunk, e, desc, ev)
            for e, desc in reversed(items)]  # lexsort: last key is primary
    if not keys:
        return np.arange(chunk.num_rows)
    return np.lexsort(keys)


def _spill_sort(child: Chunk, items: list[tuple[PlanExpr, bool]],
                ctx: ExecContext) -> Chunk:
    """External sample sort: range-partition on the primary key into
    on-disk buckets, sort each bucket in memory, emit in bucket order.

    Counterpart of the reference's sort spill (executor/sort.go:176 +
    row_container.go:493 SortAndSpillDiskAction) re-shaped for the
    vectorized engine: sorted runs + k-way merge become quantile
    buckets + per-bucket lexsort — same bounded working set, and the
    output equals the in-memory path bit-for-bit (equal primary keys
    land in one bucket, lexsort stability does the rest).
    """
    n = child.num_rows
    key0 = _sort_key(child, items[0][0], items[0][1])
    need = child.nbytes + n * 8 * max(1, len(items))
    parts = int(min(64, max(2, -(-need * 2 // max(ctx.mem.available(), 1)))))
    sample = key0[:: max(1, n // 4096)]
    qs = np.quantile(sample, np.linspace(0, 1, parts + 1)[1:-1])
    bucket = np.searchsorted(qs, key0, side="right")
    files = []
    for b in range(parts):
        idx = np.nonzero(bucket == b)[0]
        if len(idx):
            files.append(ctx.spill.spill(child.take(idx)))
    del child, key0, bucket
    pieces = []
    for f in files:
        part = f.read()
        ctx.mem.consume(part.nbytes)
        order = _sort_order(part, items)
        pieces.append(part.take(order))
        ctx.mem.release(part.nbytes)
    return Chunk.concat(pieces)


# ==================== join ====================

def _run_index_join(plan, ctx: ExecContext) -> Chunk:
    """Outer-driven index probe (reference: executor/index_lookup_join.go
    innerWorker buildTask): evaluate the outer child, look the keys up in
    the inner table's sorted-permutation epoch index (one vectorized
    searchsorted pass) plus the overlay, gather only matching inner rows,
    then apply the inner scan's pushed-down filters and residual ON
    conditions."""
    from ..store.index import epoch_column_order, epoch_index_order

    outer = run_physical(plan.children[0], ctx)
    inner_tr = plan.children[1]
    snap = ctx.txn.snapshot(inner_tr.table.id)
    oi, ii = plan.eq_conditions[0]
    okey = outer.columns[oi]
    keys = okey.data.astype(np.int64)
    kvalid = okey.validity

    epoch = snap.epoch
    off = plan.inner_offset
    # epoch side: the table's LAZY sorted-permutation — built once per
    # (epoch, column) and cached on the store (store/index.py), so
    # repeated probes pay only the searchsorted. NULL rows sort first;
    # the search runs over the non-NULL suffix only.
    store = ctx.txn.storage.tables[inner_tr.table.id]
    index = next((ix for ix in inner_tr.table.indices
                  if ix.visible and ix.col_offsets == [off]), None)
    li_parts = []
    pos_parts = []
    if epoch.num_rows:
        data = epoch.columns[off]
        valid = epoch.valids[off]
        if index is not None:
            order = epoch_index_order(store, epoch, index)
            start = 0 if valid is None else int(
                np.searchsorted(valid[order], True, "left"))
        else:  # PK-handle column (no named index object)
            order, start = epoch_column_order(store, epoch, off)
        order = order[start:]
        sorted_vals = data[order]
        lo = np.searchsorted(sorted_vals, keys, side="left")
        hi = np.searchsorted(sorted_vals, keys, side="right")
        counts = np.where(kvalid, hi - lo, 0)
        total = int(counts.sum())
        li = np.repeat(np.arange(outer.num_rows), counts)
        starts = np.repeat(lo, counts)
        offs = np.arange(total) - np.repeat(
            np.cumsum(counts) - counts, counts)
        pos = order[starts + offs]
        keep = snap.base_visible[pos]
        li_parts.append(li[keep])
        pos_parts.append(pos[keep])
    # overlay side (uncommitted / unfolded rows): small — match by scan
    n_over = len(snap.overlay_handles)
    ov_li = ov_rows = None
    if n_over:
        od = snap.overlay_columns[off].astype(np.int64)
        ovl = snap.overlay_valids[off]
        om = np.ones(n_over, bool) if ovl is None else ovl
        oorder = np.argsort(od, kind="stable")
        osorted = od[oorder]
        lo = np.searchsorted(osorted, keys, side="left")
        hi = np.searchsorted(osorted, keys, side="right")
        counts = np.where(kvalid, hi - lo, 0)
        total = int(counts.sum())
        ov_li = np.repeat(np.arange(outer.num_rows), counts)
        starts = np.repeat(lo, counts)
        offs = np.arange(total) - np.repeat(
            np.cumsum(counts) - counts, counts)
        orows = oorder[starts + offs]
        keep = om[orows]
        ov_li, ov_rows = ov_li[keep], orows[keep]

    # inner chunk in the scan's column order
    col_offsets = inner_tr.dag.scan.col_offsets
    cols = []
    for ci, coff in enumerate(col_offsets):
        parts_d, parts_v = [], []
        if pos_parts:
            d = epoch.columns[coff][pos_parts[0]]
            v = epoch.valids[coff]
            parts_d.append(d)
            parts_v.append(np.ones(len(d), bool) if v is None
                           else v[pos_parts[0]])
        if ov_rows is not None and len(ov_rows):
            d = snap.overlay_columns[coff][ov_rows]
            v = snap.overlay_valids[coff]
            parts_d.append(d)
            parts_v.append(np.ones(len(d), bool) if v is None
                           else v[ov_rows])
        ft = inner_tr.dag.output_types[ci]
        if parts_d:
            data = np.concatenate(parts_d)
            vv = np.concatenate(parts_v)
        else:
            data = np.empty(0, ft.np_dtype)
            vv = np.empty(0, bool)
        cols.append(Column(ft, data.astype(ft.np_dtype),
                           None if vv.all() else vv,
                           snap.dictionaries[coff]))
    inner = Chunk(cols)
    li = np.concatenate(li_parts + ([ov_li] if ov_li is not None
                                    and len(ov_li) else []))         if (li_parts or ov_li is not None) else np.empty(0, np.int64)
    ri = np.arange(inner.num_rows)

    # inner pushed-down filters (the scan's dag.selection)
    if inner_tr.dag.selection is not None and inner.num_rows:
        ev = _evaluator(inner)
        mask = np.ones(inner.num_rows, bool)
        for c in inner_tr.dag.selection.conditions:
            v, vl = ev.eval(_subst_subq(c, ctx))
            mask &= _truthy(np.asarray(v)) & vl
        sel = np.nonzero(mask)[0]
        inner = inner.take(sel)
        keepm = mask[ri[: len(li)]] if len(li) else mask[:0]
        li = li[keepm]
        ri = np.arange(inner.num_rows)

    if plan.other_conditions:
        joined = _merge_chunks(outer.take(li), inner)
        ev = _evaluator(joined)
        mask = np.ones(len(li), dtype=bool)
        for c in plan.other_conditions:
            v, vl = ev.eval(_subst_subq(c, ctx))
            mask &= _truthy(np.asarray(v)) & vl
        li = li[mask]
        inner = inner.take(np.nonzero(mask)[0])

    if plan.kind == "SEMI":
        return outer.take(np.unique(li))
    return _merge_chunks(outer.take(li), inner)


def _run_join(plan, ctx: ExecContext) -> Chunk:
    left = run_physical(plan.children[0], ctx)
    right = run_physical(plan.children[1], ctx)
    nleft = len(left.columns)

    if plan.kind == "ANTI_NULL":
        # null-aware NOT IN semantics (reference: planner NAAJ):
        # any NULL in the subquery side means no outer row qualifies;
        # outer rows with a NULL key never qualify.
        ri_idx = plan.eq_conditions[0][1]
        if right.num_rows and not right.columns[ri_idx].validity.all():
            return left.take(np.empty(0, np.int64))

    if not plan.eq_conditions:
        li = np.repeat(np.arange(left.num_rows), right.num_rows)
        ri = np.tile(np.arange(right.num_rows), left.num_rows)
    else:
        # key-unify working set: ~4 int64 copies per key column per row
        # (stack, concat, unique, inverse) on both sides
        est = (left.num_rows + right.num_rows) * \
            (len(plan.eq_conditions) * 8 * 4 + 16)
        if _overflow(ctx, est, "HashJoin"):
            return _grace_join(plan, left, right, ctx)
        li, ri = _equi_match(plan, left, right)

    # residual ON conditions filter matched pairs
    if plan.other_conditions:
        joined = _merge_chunks(left.take(li), right.take(ri))
        ev = _evaluator(joined)
        mask = np.ones(len(li), dtype=bool)
        for c in plan.other_conditions:
            v, vl = ev.eval(_subst_subq(c, ctx))
            mask &= _truthy(np.asarray(v)) & vl
        li, ri = li[mask], ri[mask]

    if plan.kind == "SEMI":
        return left.take(np.unique(li))
    if plan.kind in ("ANTI", "ANTI_NULL"):
        keep = np.ones(left.num_rows, dtype=bool)
        keep[li] = False
        if plan.kind == "ANTI_NULL" and right.num_rows:
            # NULL lhs vs a non-empty set is UNKNOWN -> filtered;
            # NOT IN (empty set) is TRUE even for a NULL lhs
            li_idx = plan.eq_conditions[0][0]
            keep &= left.columns[li_idx].validity
        return left.take(np.nonzero(keep)[0])
    if plan.kind == "LEFT":
        matched = np.zeros(left.num_rows, dtype=bool)
        matched[li] = True
        extra = np.nonzero(~matched)[0]
        return _merge_chunks(
            left.take(np.concatenate([li, extra])),
            _append_nulls(right.take(ri), len(extra)),
        )
    if plan.kind == "RIGHT":
        matched = np.zeros(right.num_rows, dtype=bool)
        matched[ri] = True
        extra = np.nonzero(~matched)[0]
        return _merge_chunks(
            _append_nulls(left.take(li), len(extra)),
            right.take(np.concatenate([ri, extra])),
        )
    return _merge_chunks(left.take(li), right.take(ri))


def _encode_join_keys(plan: PhysHashJoin, left: Chunk, right: Chunk):
    """Per-side comparable int64 key stacks [n, nkeys] + validity masks.

    Encodings unify the key domains across sides (dictionary remap,
    decimal rescale, float bit patterns) so equal SQL values encode to
    equal int64s; both the in-memory unify and the grace partitioner
    hash these."""
    lkeys = []
    rkeys = []
    lvalid = np.ones(left.num_rows, dtype=bool)
    rvalid = np.ones(right.num_rows, dtype=bool)
    for li_idx, ri_idx in plan.eq_conditions:
        lc = left.columns[li_idx]
        rc = right.columns[ri_idx]
        lv = lc.data
        rv = rc.data
        if lc.ftype.is_string and lc.dictionary is not None and \
                rc.dictionary is not None:
            ci = lc.ftype.is_ci or rc.ftype.is_ci
            ld = lc.dictionary
            # dictionary columns across different dicts: remap right into
            # left's (ci: casefold-equal values unify)
            if rc.dictionary is not ld:
                lookup = ld.lookup_ci if ci else ld.lookup
                remap = np.fromiter(
                    (lookup(s) for s in rc.dictionary.values),
                    dtype=np.int64, count=len(rc.dictionary))
                rv = remap[rc.data] if len(rc.dictionary) else rc.data
            if ci and len(ld):
                canon = ld.ci_canonical()
                lv = canon[np.clip(lv, 0, len(ld) - 1)]
                rv = np.where(np.asarray(rv) >= 0,
                              canon[np.clip(rv, 0, len(ld) - 1)],
                              np.asarray(rv))
        # unify key domains: if either side is float, compare both as
        # float64 bit patterns (with -0.0 normalized); otherwise align
        # decimal scales and compare as int64
        l_float = np.issubdtype(lv.dtype, np.floating)
        r_float = np.issubdtype(rv.dtype, np.floating)
        if l_float or r_float:
            def to_f(v, ft):
                f = v.astype(np.float64)
                if ft.is_decimal:
                    f = f / 10 ** ft.scale
                return np.where(f == 0, 0.0, f).view(np.int64)
            lv = to_f(lv, lc.ftype)
            rv = to_f(rv, rc.ftype)
        else:
            ls = lc.ftype.scale if lc.ftype.is_decimal else 0
            rs = rc.ftype.scale if rc.ftype.is_decimal else 0
            lv = lv.astype(np.int64)
            rv = rv.astype(np.int64)
            if ls < rs:
                lv = lv * 10 ** (rs - ls)
            elif rs < ls:
                rv = rv * 10 ** (ls - rs)
        lkeys.append(lv)
        rkeys.append(rv)
        lvalid &= lc.validity
        rvalid &= rc.validity
    return (np.stack(lkeys, axis=1), np.stack(rkeys, axis=1),
            lvalid, rvalid)


def _equi_match(plan, left: Chunk, right: Chunk):
    """Vectorized equi-join: sort-merge expand over unified key ids.

    Single-column keys skip the np.unique id-unification entirely (the
    encoded int64 values are directly comparable — this is the sort-merge
    join inner loop, reference: executor/merge_join.go); multi-column
    keys unify via unique-row ids first."""
    lstack, rstack, lvalid, rvalid = _encode_join_keys(plan, left, right)
    if lstack.shape[1] == 1:
        # NULL rows are excluded from the domains outright — no sentinel
        # values that a real key could collide with
        lids = lstack[:, 0]
        rvalid_idx = np.nonzero(rvalid)[0]
        rvals = rstack[rvalid_idx, 0]
        ro = np.argsort(rvals, kind="stable")
        rorder = rvalid_idx[ro]
        rsorted = rvals[ro]
        null_gate = lvalid
    else:
        all_keys = np.concatenate([lstack, rstack], axis=0)
        _, inv = np.unique(all_keys, axis=0, return_inverse=True)
        inv = inv.reshape(-1)
        lids = np.where(lvalid, inv[: left.num_rows], -1)
        rids = np.where(rvalid, inv[left.num_rows:], -2)
        null_gate = lids >= 0
        rorder = np.argsort(rids, kind="stable")
        rsorted = rids[rorder]
    lo = np.searchsorted(rsorted, lids, side="left")
    hi = np.searchsorted(rsorted, lids, side="right")
    counts = np.where(null_gate, hi - lo, 0)
    total = int(counts.sum())
    li = np.repeat(np.arange(left.num_rows), counts)
    starts = np.repeat(lo, counts)
    offsets = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
    ri = rorder[starts + offsets]
    return li, ri


def _key_hash(stack: np.ndarray) -> np.ndarray:
    """FNV-1a-style mix of an [n, k] int64 key stack to uint64."""
    h = np.full(stack.shape[0], 14695981039346656037, np.uint64)
    for j in range(stack.shape[1]):
        h = (h ^ stack[:, j].astype(np.uint64)) * np.uint64(1099511628211)
    return h


def _grace_join(plan: PhysHashJoin, left: Chunk, right: Chunk,
                ctx: ExecContext) -> Chunk:
    """Partitioned (grace) hash join: hash both sides by join key into
    on-disk partitions, free the inputs, join partition pairs one at a
    time, then restore the in-memory path's row order from the global
    row indices carried with each partition.

    Counterpart of the reference's spilling hash join
    (executor/join.go + util/chunk/row_container.go:63); partition
    co-location is sound because matching pairs encode to equal int64
    keys (see _encode_join_keys) and therefore equal hashes.
    """
    lstack, rstack, lvalid, rvalid = _encode_join_keys(plan, left, right)
    need = (lstack.nbytes + rstack.nbytes) * 4
    parts = int(min(64, max(2, -(-need * 2 // max(ctx.mem.available(), 1)))))
    lh = (_key_hash(lstack) % np.uint64(parts)).astype(np.int64)
    rh = (_key_hash(rstack) % np.uint64(parts)).astype(np.int64)
    del lstack, rstack, lvalid, rvalid
    part_files = []
    for p in range(parts):
        lidx = np.nonzero(lh == p)[0]
        ridx = np.nonzero(rh == p)[0]
        if not len(lidx) and not len(ridx):
            continue  # nothing to join or null-fill from this partition
        part_files.append((lidx, ctx.spill.spill(left.take(lidx)),
                           ridx, ctx.spill.spill(right.take(ridx))))
    n_right_total = right.num_rows
    del left, right, lh, rh

    matched: list[tuple[np.ndarray, np.ndarray, Chunk]] = []
    extras: list[tuple[np.ndarray, Chunk]] = []  # LEFT/RIGHT outer fill
    plains: list[tuple[np.ndarray, Chunk]] = []  # SEMI/ANTI left rows
    for lidx, lf, ridx, rf in part_files:
        lpart = lf.read()
        rpart = rf.read()
        ctx.mem.consume(lpart.nbytes + rpart.nbytes)
        li, ri = _equi_match(plan, lpart, rpart)
        if plan.other_conditions:
            joined = _merge_chunks(lpart.take(li), rpart.take(ri))
            ev = _evaluator(joined)
            mask = np.ones(len(li), dtype=bool)
            for c in plan.other_conditions:
                v, vl = ev.eval(_subst_subq(c, ctx))
                mask &= _truthy(np.asarray(v)) & vl
            li, ri = li[mask], ri[mask]
        if plan.kind == "SEMI":
            ul = np.unique(li)
            plains.append((lidx[ul], lpart.take(ul)))
        elif plan.kind in ("ANTI", "ANTI_NULL"):
            keep = np.ones(lpart.num_rows, dtype=bool)
            keep[li] = False
            if plan.kind == "ANTI_NULL" and n_right_total:
                keep &= lpart.columns[plan.eq_conditions[0][0]].validity
            kidx = np.nonzero(keep)[0]
            plains.append((lidx[kidx], lpart.take(kidx)))
        elif plan.kind == "LEFT":
            matched.append((lidx[li], ridx[ri],
                            _merge_chunks(lpart.take(li), rpart.take(ri))))
            um = np.zeros(lpart.num_rows, dtype=bool)
            um[li] = True
            extra = np.nonzero(~um)[0]
            extras.append((lidx[extra], _merge_chunks(
                lpart.take(extra),
                _append_nulls(rpart.take(np.empty(0, np.int64)),
                              len(extra)))))
        elif plan.kind == "RIGHT":
            matched.append((lidx[li], ridx[ri],
                            _merge_chunks(lpart.take(li), rpart.take(ri))))
            um = np.zeros(rpart.num_rows, dtype=bool)
            um[ri] = True
            extra = np.nonzero(~um)[0]
            extras.append((ridx[extra], _merge_chunks(
                _append_nulls(lpart.take(np.empty(0, np.int64)),
                              len(extra)),
                rpart.take(extra))))
        else:  # INNER
            matched.append((lidx[li], ridx[ri],
                            _merge_chunks(lpart.take(li), rpart.take(ri))))
        ctx.mem.release(lpart.nbytes + rpart.nbytes)

    if plan.kind in ("SEMI", "ANTI", "ANTI_NULL"):
        gli = np.concatenate([g for g, _ in plains])
        out = Chunk.concat([c for _, c in plains])
        return out.take(np.argsort(gli, kind="stable"))
    gli = np.concatenate([g for g, _, _ in matched])
    gri = np.concatenate([r for _, r, _ in matched])
    out = Chunk.concat([c for _, _, c in matched])
    out = out.take(np.lexsort((gri, gli)))
    if plan.kind in ("LEFT", "RIGHT"):
        gex = np.concatenate([g for g, _ in extras])
        ex = Chunk.concat([c for _, c in extras])
        ex = ex.take(np.argsort(gex, kind="stable"))
        return Chunk.concat([out, ex])
    return out


def _merge_chunks(a: Chunk, b: Chunk) -> Chunk:
    return Chunk(a.columns + b.columns)


def _append_nulls(side: Chunk, n_null: int) -> Chunk:
    """side's rows followed by n_null NULL-extended rows (outer join fill)."""
    cols = []
    for c in side.columns:
        data = np.concatenate([c.data, np.zeros(n_null, c.data.dtype)])
        valid = np.concatenate([c.validity, np.zeros(n_null, bool)])
        cols.append(Column(c.ftype, data, valid, c.dictionary))
    return Chunk(cols)


__all__ = ["ExecContext", "run_physical"]
