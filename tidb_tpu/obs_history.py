"""Workload history plane: persistent per-digest plan/perf history.

Every sensor PRs 2-13 built sees only the CURRENT process: Top SQL
windows rotate away, MetricsHistory dies with the process, and nothing
records which plan a digest ran yesterday. This module is the memory —
the counterpart of the reference's eviction-safe
`statements_summary_history` (util/stmtsummary's windowed persistence
behind INFORMATION_SCHEMA.STATEMENTS_SUMMARY_HISTORY) plus the
plan-digest tracking its SPM/plan-binding tier uses to notice a plan
flip (bindinfo's baseline capture keys on (sql_digest, plan_digest)).

Shape: one `WorkloadHistory` per Storage. While `history.enabled` is
false it is ZERO work on the statement path — the session call site
gates on `.enabled` before hashing anything (the Top SQL contract).
Enabled, every completed statement feeds `observe()` with its SQL
digest, wall time, stage split, engine tags (`Session.last_engines` —
the device/host path decision with the fragment mode embedded), rows
and mesh skew; observations aggregate into the LIVE window keyed by
(sql_digest, plan_digest), and a closed window rotates into the bounded
durable record list, persisted under `<storage-dir>/history/` with the
PR 4 crash-atomic discipline (tmp + fsync + rename + dir fsync) so the
records survive kill -9 and read back verbatim on reopen.

The plan digest is derived from the statement's engine-tag set: the
same query re-planned onto a different execution path (device[group] ->
host(...), point -> full dispatch, device -> device@mesh8) gets a new
plan digest, which is exactly the event the detection tier watches for:

* plan_change — a throttled structured event the first time a digest
  executes with a plan digest (or a DEGRADED engine class) different
  from its history; severity `warn` when the engine class degraded
  (device -> host, fast path -> full dispatch), `info` otherwise.
* plan-regression / stmt-perf-regression — inspection rules
  (obs_inspect.py) over `regression_findings()`: a new plan at least
  `history.regression-ratio` slower than the historical p50 of the
  plan it replaced, and a same-plan sustained latency drift against
  the digest's own baseline records.

Surfaces: information_schema.statements_summary_history (one row per
rotated window x digest x plan) and tidb_plan_history (one row per
digest x plan, the "which plan won" view), their cluster_ variants
over the PR 3 diag fan-out, /debug/history, and the
tidb_history_* metric families.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Optional

RECORDS_FILE = "records.json"
FORMAT_VERSION = 1

# engine classes, best first: the DEGRADATION detector compares the
# best class a digest's history reached against the class it just ran
# with. 3 = the OLTP point fast path (plan/fastpath.py bypass),
# 2 = device/coprocessor paths (incl. mesh + replica-routed reads),
# 1 = host-side ranged index reads, 0 = the host interpreter fallback.
_CLASS_HOST = 0
_CLASS_RANGED = 1
_CLASS_DEVICE = 2
_CLASS_POINT = 3


def engine_class(engines) -> int:
    """Collapse a statement's engine-tag list to one ordinal class.
    Statements without a coprocessor read (DDL, SET, metadata) class
    as device — there is no path to regress off."""
    if not engines:
        return _CLASS_DEVICE
    tags = list(engines)
    if any(str(t).startswith("host(") for t in tags):
        return _CLASS_HOST
    if all(str(t) == "point" for t in tags):
        return _CLASS_POINT
    if any(str(t).startswith(("device", "replica@", "point"))
           for t in tags):
        return _CLASS_DEVICE
    return _CLASS_RANGED


def plan_digest_of(engines) -> str:
    """Plan identity from the statement's engine-tag set: stable under
    plan-node enumeration order (sorted unique tags), sensitive to the
    execution path + fragment mode (`device[group]` vs `host(...)` vs
    `point`) — which is the granularity the plan-flip detector needs."""
    key = "|".join(sorted(set(str(t) for t in (engines or ()))))
    return hashlib.sha256(key.encode()).hexdigest()[:16]


def fragment_modes(engines) -> list[str]:
    """The bracketed device fragment modes of a tag set
    (['group', 'rows+semi'] from device[group]@mesh8 ...) — the
    strategy record ROADMAP item 5's adaptive placement learns from."""
    out = set()
    for t in engines or ():
        t = str(t)
        if not t.startswith("device"):
            continue
        i = t.find("[")
        j = t.find("]", i)
        if 0 <= i < j:
            out.add(t[i + 1:j])
    return sorted(out)


class WorkloadHistory:
    """Per-storage windowed (sql_digest, plan_digest) history with
    crash-safe persistence and plan-change detection. Thread-safe: one
    lock guards the live window, the record list and the plan-seen
    index; persistence happens outside the statement's observe() call
    only at window rotation (one atomic file write per closed window)."""

    DEFAULT_WINDOW_S = 60
    DEFAULT_CAP = 512
    DEFAULT_RATIO = 1.5
    # at most one plan_change event per digest per window — a flapping
    # plan must not flood the event ring
    _THROTTLE_CAP = 512

    def __init__(self, path: Optional[str] = None, metrics=None,
                 events=None) -> None:
        self.enabled = False
        self.window_seconds = float(self.DEFAULT_WINDOW_S)
        self.history_cap = int(self.DEFAULT_CAP)
        self.regression_ratio = float(self.DEFAULT_RATIO)
        self.dir = os.path.join(path, "history") if path else None
        self.events = events
        self._lock = threading.Lock()
        # serializes the FILE write only (tmp+rename pair), never held
        # with _lock: persistence must not block the statement path.
        # The generation pair orders concurrent rotation writes — a
        # preempted older snapshot must never overwrite a newer one.
        self._persist_lock = threading.Lock()
        self._gen = 0
        self._persisted_gen = 0
        self._records: list[dict] = []   # rotated windows, oldest first
        self._live: dict[tuple, dict] = {}
        self._win_start: Optional[int] = None
        self._loaded = False
        # sql_digest -> (last plan_digest, best engine class seen)
        self._plan_seen: dict[str, tuple] = {}
        # sql_digest -> window start of the last plan_change event
        self._change_fired: dict[str, int] = {}
        if metrics is not None:
            self.records_gauge = metrics.gauge(
                "tidb_history_records",
                "durable workload-history records retained (rotated "
                "(sql_digest, plan_digest) windows, bounded by "
                "history.history-cap)")
            self.rotations = metrics.counter(
                "tidb_history_rotations_total",
                "workload-history windows closed and rotated into the "
                "durable record list")
            self.plan_changes = metrics.counter(
                "tidb_history_plan_changes_total",
                "statements that executed with a plan digest (or a "
                "degraded engine class) different from their recorded "
                "history, by kind (changed / degraded)")
            self.persist_failures = metrics.counter(
                "tidb_history_persist_failures_total",
                "workload-history persistence attempts that failed "
                "(records stay in memory; the next rotation retries)")
        else:
            self.records_gauge = None
            self.rotations = None
            self.plan_changes = None
            self.persist_failures = None

    # ==================== config ====================
    def configure(self, enabled: Optional[bool] = None,
                  window_seconds: Optional[float] = None,
                  history_cap: Optional[int] = None,
                  regression_ratio: Optional[float] = None) -> None:
        """Apply the [history] config knobs (startup + SIGHUP hot
        reload; safe while running — a shrunk cap drops the oldest
        records at the next rotation)."""
        if window_seconds is not None:
            self.window_seconds = max(float(window_seconds), 1.0)
        if history_cap is not None:
            self.history_cap = max(int(history_cap), 1)
        if regression_ratio is not None:
            self.regression_ratio = max(float(regression_ratio), 1.0)
        if enabled is not None:
            was = self.enabled
            self.enabled = bool(enabled)
            if self.enabled and not was:
                self._ensure_loaded()

    # ==================== persistence ====================
    def _records_path(self) -> Optional[str]:
        return os.path.join(self.dir, RECORDS_FILE) if self.dir else None

    def _ensure_loaded(self) -> None:
        """Read the durable records back (once, at first enable): a
        corrupt or missing file degrades to empty history, never an
        error — history is derived data with a fresh start as the
        worst case."""
        if self._loaded:
            # unlocked fast path: set-once flag, checked per statement
            # on the enabled path — observe() must not pay a second
            # mutex round-trip just to learn the load already happened
            return
        with self._lock:
            if self._loaded:
                return
            self._loaded = True
            path = self._records_path()
            if path is None:
                return
            try:
                with open(path, encoding="utf-8") as f:
                    raw = json.load(f)
            except (OSError, ValueError):
                return
            recs = raw.get("records") if isinstance(raw, dict) else None
            if not isinstance(recs, list):
                return
            self._records = [r for r in recs if isinstance(r, dict)
                             and r.get("digest")][-self.history_cap:]
            for r in self._records:  # oldest first: last write wins
                cls = int(r.get("engine_class", _CLASS_DEVICE))
                prev = self._plan_seen.get(r["digest"])
                best = cls if prev is None else max(prev[1], cls)
                self._plan_seen[r["digest"]] = (
                    str(r.get("plan_digest", "")), best)
            if self.records_gauge is not None:
                self.records_gauge.set(len(self._records))

    def _persist(self, gen: int, records: list[dict]) -> None:
        """Atomic tmp + fsync + rename + dir-fsync write of a record
        snapshot (the PR 4 crash-atomic discipline): a reader after
        kill -9 sees the previous complete file or the new complete
        file, never a torn one. Runs OUTSIDE the statement-path lock —
        the fsync must not block concurrent observes (the lock-held
        fsync was exactly the PR 12 native-store bug); _persist_lock
        serializes the tmp+rename pair between concurrent rotations,
        and the generation check drops a snapshot that lost the race
        to a NEWER one (an older write landing last would silently
        un-persist the newest window)."""
        path = self._records_path()
        if path is None:
            return
        from .kv.mvcc import fsync_dir
        try:
            with self._persist_lock:
                if gen <= self._persisted_gen:
                    return  # a newer snapshot already reached disk
                os.makedirs(self.dir, exist_ok=True)
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"version": FORMAT_VERSION,
                               "saved": round(time.time(), 3),
                               "records": records}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                fsync_dir(self.dir)
                self._persisted_gen = gen
        except OSError:
            if self.persist_failures is not None:
                self.persist_failures.inc()

    # ==================== the statement feed ====================
    def observe(self, digest: str, digest_text: str, db: str,
                wall_s: float, engines=None,
                stages: Optional[dict] = None, rows: int = 0,
                failed: bool = False,
                op_mesh: Optional[dict] = None,
                now: Optional[float] = None) -> None:
        """One completed statement. The session gates on `.enabled`
        before computing the digest, so this is never reached while
        disabled; the internal guard keeps direct callers honest."""
        if not self.enabled:
            return
        self._ensure_loaded()
        ts = time.time() if now is None else float(now)
        if failed:
            # an interrupted/failed statement has neither a trustworthy
            # plan (note_engine stops at the dispatch that died — a
            # truncated tag set would derive a bogus plan digest and
            # fire spurious plan_change events) nor a representative
            # latency (it must not pollute the regression baselines):
            # count the error against the digest's KNOWN plan, if any
            with self._lock:
                persist = self._rotate_locked(ts)
                seen = self._plan_seen.get(digest)
                if seen is not None:
                    ent = self._live.get((digest, seen[0]))
                    if ent is not None:
                        ent["errors"] += 1
            if persist is not None:
                self._persist(*persist)
            return
        plan = plan_digest_of(engines)
        cls = engine_class(engines)
        modes = fragment_modes(engines)
        change = None
        with self._lock:
            persist = self._rotate_locked(ts)
            seen = self._plan_seen.get(digest)
            if seen is not None and seen[0] != plan:
                degraded = cls < seen[1]
                win = self._win_start or 0
                if self._change_fired.get(digest) != win:
                    if len(self._change_fired) >= self._THROTTLE_CAP:
                        self._change_fired.clear()
                    self._change_fired[digest] = win
                    change = ("degraded" if degraded else "changed",
                              seen[0])
            best = cls if seen is None else max(seen[1], cls)
            self._plan_seen[digest] = (plan, best)
            key = (digest, plan)
            ent = self._live.get(key)
            if ent is None:
                ent = self._live[key] = {
                    "window_start": self._win_start,
                    "digest": digest, "digest_text": digest_text[:512],
                    "schema_name": db, "plan_digest": plan,
                    "engines": sorted(set(str(t)
                                          for t in (engines or ()))),
                    "modes": modes, "engine_class": cls,
                    "exec_count": 0, "errors": 0,
                    "sum_wall_ms": 0.0, "max_wall_ms": 0.0,
                    "sum_rows": 0, "stages_ms": {},
                    "max_skew": 0.0, "max_shard_share": 0.0,
                    "last_ts": 0.0,
                }
            # last-execution order: an intra-window plan flap must
            # leave the LAST-run plan as the digest's current one on
            # every read surface, not the first-seen one
            ent["last_ts"] = max(ent.get("last_ts", 0.0),
                                 round(ts, 3))
            ent["exec_count"] += 1
            ms = wall_s * 1e3
            ent["sum_wall_ms"] += ms
            ent["max_wall_ms"] = max(ent["max_wall_ms"], ms)
            ent["sum_rows"] += int(rows)
            if stages:
                st = ent["stages_ms"]
                for k, v in stages.items():
                    st[k] = round(st.get(k, 0.0) + v * 1e3, 3)
            if op_mesh:
                for share, skew in op_mesh.values():
                    ent["max_shard_share"] = max(ent["max_shard_share"],
                                                 float(share))
                    ent["max_skew"] = max(ent["max_skew"], float(skew))
        if persist is not None:
            self._persist(*persist)
        if change is not None:
            kind, old_plan = change
            if self.plan_changes is not None:
                self.plan_changes.inc(kind=kind)
            if self.events is not None:
                self.events.record(
                    "plan_change",
                    severity="warn" if kind == "degraded" else "info",
                    digest=digest,
                    detail=f"plan {old_plan} -> {plan} "
                           f"({kind}; engines "
                           f"{','.join(sorted(set(str(t) for t in (engines or ())))) or '(none)'}): "
                           f"{digest_text[:200]}")

    def _rotate_locked(self, ts: float) -> Optional[tuple]:
        """Close the live window if `ts` has moved past it. Returns a
        (generation, records snapshot) pair to persist (caller writes
        it AFTER releasing the lock) or None when nothing rotated."""
        win = int(ts - (ts % self.window_seconds))
        if self._win_start is None:
            self._win_start = win
            return None
        if win <= self._win_start:
            return None
        closed_start = self._win_start
        self._win_start = win
        if not self._live:
            return None
        end = time.strftime(
            "%Y-%m-%d %H:%M:%S",
            time.localtime(closed_start + self.window_seconds))
        for ent in sorted(self._live.values(),
                          key=lambda e: e.get("last_ts", 0.0)):
            ent["window_end"] = end
            self._records.append(ent)
        self._live = {}
        del self._records[:-self.history_cap]
        if self.rotations is not None:
            self.rotations.inc()
        if self.records_gauge is not None:
            self.records_gauge.set(len(self._records))
        self._gen += 1
        return (self._gen, [dict(r) for r in self._records])

    def flush(self, now: Optional[float] = None) -> None:
        """Rotate the live window (if any) into the records and
        persist — Storage.close() calls this so a clean shutdown keeps
        the newest partial window too."""
        if not self.enabled:
            return
        with self._lock:
            if self._live:
                # force-close regardless of wall clock: the window is
                # over because the server is
                persist = self._rotate_locked(
                    (self._win_start or 0) + self.window_seconds
                    if now is None else float(now))
            else:
                self._gen += 1
                persist = (self._gen, [dict(r) for r in self._records])
        if persist is not None:
            self._persist(*persist)

    # ==================== read surfaces ====================
    def snapshot(self) -> dict:
        """Copies safe to read unlocked: rotated records are immutable
        after rotation (shallow copy suffices), but LIVE entries keep
        mutating under the lock — their nested dicts (stages_ms) must
        be deep-copied or a reader iterating them races a concurrent
        observe()'s insert."""
        import copy
        with self._lock:
            return {
                "records": [dict(r) for r in self._records],
                "live": [copy.deepcopy(e) for e in self._live.values()],
                "window_start": self._win_start,
            }

    @staticmethod
    def _fmt_win(win) -> str:
        return time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(int(win or 0)))

    def table_rows(self) -> list[list]:
        """information_schema.statements_summary_history rows: durable
        records oldest first, then the live (still-open) window."""
        snap = self.snapshot()
        rows = []
        for r in snap["records"] + snap["live"]:
            n = max(int(r.get("exec_count", 0)), 1)
            rows.append([
                self._fmt_win(r.get("window_start")),
                r.get("window_end", ""),
                r.get("digest", ""), r.get("schema_name", ""),
                str(r.get("digest_text", ""))[:512],
                r.get("plan_digest", ""),
                ",".join(r.get("engines", ())),
                ",".join(r.get("modes", ())),
                int(r.get("exec_count", 0)), int(r.get("errors", 0)),
                round(float(r.get("sum_wall_ms", 0.0)) / n, 3),
                round(float(r.get("max_wall_ms", 0.0)), 3),
                int(r.get("sum_rows", 0)),
                _fmt_stages_ms(r.get("stages_ms")),
                round(float(r.get("max_skew", 0.0)), 2),
            ])
        return rows

    def plan_rows(self) -> list[list]:
        """information_schema.tidb_plan_history rows: one row per
        (digest, plan_digest) across the whole retained history —
        execs, avg/p50 latency, engine tags/modes, first/last window,
        and whether this is the digest's CURRENT plan."""
        snap = self.snapshot()
        agg: dict[tuple, dict] = {}
        latest: dict[str, tuple] = {}  # digest -> (order key, plan)
        for r in snap["records"] + snap["live"]:
            key = (r.get("digest", ""), r.get("plan_digest", ""))
            okey = _order_key(r)
            if okey >= latest.get(key[0], ((-1, -1.0), ""))[0]:
                latest[key[0]] = (okey, key[1])
            a = agg.get(key)
            if a is None:
                a = agg[key] = {
                    "digest_text": r.get("digest_text", ""),
                    "engines": r.get("engines", ()),
                    "modes": r.get("modes", ()),
                    "windows": 0, "exec_count": 0, "errors": 0,
                    "sum_ms": 0.0, "max_ms": 0.0, "avgs": [],
                    "first": r.get("window_start"),
                    "last": r.get("window_start"),
                }
            n = max(int(r.get("exec_count", 0)), 1)
            a["windows"] += 1
            a["exec_count"] += int(r.get("exec_count", 0))
            a["errors"] += int(r.get("errors", 0))
            a["sum_ms"] += float(r.get("sum_wall_ms", 0.0))
            a["max_ms"] = max(a["max_ms"],
                              float(r.get("max_wall_ms", 0.0)))
            a["avgs"].append(float(r.get("sum_wall_ms", 0.0)) / n)
            a["last"] = r.get("window_start")
        rows = []
        for (digest, plan), a in sorted(agg.items()):
            n = max(a["exec_count"], 1)
            rows.append([
                digest, plan, str(a["digest_text"])[:512],
                ",".join(a["engines"]), ",".join(a["modes"]),
                a["windows"], a["exec_count"], a["errors"],
                round(a["sum_ms"] / n, 3),
                round(_median(a["avgs"]), 3),
                round(a["max_ms"], 3),
                self._fmt_win(a["first"]), self._fmt_win(a["last"]),
                1 if latest.get(digest, (None, None))[1] == plan else 0,
            ])
        return rows

    def debug_payload(self) -> dict:
        out = {
            "enabled": self.enabled,
            "window_seconds": self.window_seconds,
            "history_cap": self.history_cap,
            "regression_ratio": self.regression_ratio,
            "dir": self.dir,
        }
        if not self.enabled:
            return out
        out.update(self.snapshot())
        out["regressions"] = self.regression_findings()
        return out

    # ==================== regression detection ====================
    def regression_findings(self) -> list[dict]:
        """The rule bodies behind the plan-regression and
        stmt-perf-regression inspection rules, computed over one
        snapshot: each finding is a plain dict {rule, item, severity,
        value, details} obs_inspect converts. Empty while disabled."""
        if not self.enabled:
            return []
        snap = self.snapshot()
        ratio = self.regression_ratio
        by_digest: dict[str, list[dict]] = {}
        for r in snap["records"] + snap["live"]:
            if r.get("exec_count"):
                by_digest.setdefault(r["digest"], []).append(r)
        out: list[dict] = []
        for digest, recs in sorted(by_digest.items()):
            # "current" = the LAST-executed plan, not first-seen-in-
            # window order (an intra-window plan flap must not grade
            # the wrong plan against the wrong history)
            recs = sorted(recs, key=_order_key)
            cur = recs[-1]
            cur_plan = cur.get("plan_digest", "")
            cur_entries = [r for r in recs
                           if r.get("plan_digest") == cur_plan]
            cur_avg = _avg_ms(cur_entries[-1])
            base = [r for r in recs if r.get("plan_digest") != cur_plan]
            text = str(cur.get("digest_text", ""))[:160]
            if base:
                # the digest switched plans: new plan's latest window
                # vs the REPLACED plans' p50 over their history
                p50 = _median([_avg_ms(r) for r in base])
                if p50 > 0 and cur_avg >= ratio * p50:
                    sev = "critical" if cur_avg >= 2 * ratio * p50 \
                        else "warning"
                    out.append({
                        "rule": "plan-regression", "item": digest,
                        "severity": sev,
                        "value": f"{cur_avg / p50:.1f}x",
                        "details":
                            f"new plan {cur_plan} runs {cur_avg:.1f}ms "
                            f"vs {p50:.1f}ms historical p50 of the "
                            f"replaced plan "
                            f"({cur_avg / p50:.1f}x >= "
                            f"{ratio:g}; engines "
                            f"{','.join(cur.get('engines', ())) or '(none)'}): "
                            f"{text}"})
            if len(cur_entries) >= 3:
                # same plan, sustained drift: the newest window vs the
                # digest's own earlier windows on this plan
                baseline = _median([_avg_ms(r)
                                    for r in cur_entries[:-1]])
                if baseline > 0 and cur_avg >= ratio * baseline:
                    sev = "critical" \
                        if cur_avg >= 2 * ratio * baseline else "warning"
                    out.append({
                        "rule": "stmt-perf-regression", "item": digest,
                        "severity": sev,
                        "value": f"{cur_avg / baseline:.1f}x",
                        "details":
                            f"plan {cur_plan} drifted to "
                            f"{cur_avg:.1f}ms vs its own "
                            f"{baseline:.1f}ms baseline p50 over "
                            f"{len(cur_entries) - 1} windows "
                            f"({cur_avg / baseline:.1f}x >= {ratio:g}): "
                            f"{text}"})
        return out


def _order_key(rec: dict) -> tuple:
    """Execution-recency order of a history entry: window first, then
    the entry's last observation inside it."""
    return (int(rec.get("window_start") or 0),
            float(rec.get("last_ts") or 0.0))


def _avg_ms(rec: dict) -> float:
    return float(rec.get("sum_wall_ms", 0.0)) / \
        max(int(rec.get("exec_count", 0)), 1)


def _median(vals: list[float]) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def _fmt_stages_ms(stages_ms) -> str:
    from . import obs
    return obs.fmt_stages_ms(stages_ms)[:256] if stages_ms else ""


__all__ = ["WorkloadHistory", "engine_class", "plan_digest_of",
           "fragment_modes"]
