"""SQL plan management: statement bindings (reference: bindinfo/handle.go,
bindinfo/session_handle.go, mysql.bind_info).

A binding pairs a literal-normalized statement with a hinted variant of
the same statement. At planning time a SELECT whose normalized form (and
current database) matches a binding gets the binding's optimizer hints
injected — the user's literals are kept; only the hint set transfers
(reference: bindinfo/bind_record.go HintsSet).

GLOBAL bindings persist through the storage meta plane (the
mysql.bind_info analog) and are visible to every server over the shared
store; SESSION bindings live on the Session and win over GLOBAL ones
(reference: session handle shadowing, bindinfo/session_handle.go).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Any, Optional

_META_PREFIX = b"binding:"
_META_INDEX = b"binding:__digests__"


def normalize_binding_sql(sql: str) -> str:
    """Literal-normalized, hint-stripped statement text: the binding
    match key (reference: parser.NormalizeDigest; hints are excluded so
    `FOR` and `USING` statements compare equal modulo hints)."""
    from ..sql.lexer import Lexer, TokenKind

    out: list[str] = []
    for t in Lexer(sql).tokens():
        if t.kind == TokenKind.EOF:
            break
        if t.kind == TokenKind.HINT:
            continue
        if t.kind in (TokenKind.INT, TokenKind.DECIMAL,
                      TokenKind.FLOAT, TokenKind.STRING):
            out.append("?")
        else:
            out.append(t.text.lower())
    joined = " ".join(out)
    return joined[:-2].strip() if joined.endswith(" ;") else joined


def binding_digest(norm_sql: str, db: str) -> str:
    return hashlib.sha256(
        f"{db.lower()}\x00{norm_sql}".encode()).hexdigest()[:32]


def make_record(norm_sql: str, bind_sql: str, db: str,
                hints: list) -> dict:
    """One binding record — the SHOW BINDINGS row source for both
    scopes, so the shape is defined exactly once."""
    now = time.strftime("%Y-%m-%d %H:%M:%S")
    return {
        "original_sql": norm_sql, "bind_sql": bind_sql,
        "default_db": db, "status": "enabled",
        "create_time": now, "update_time": now,
        "hints": [list(h) if not isinstance(h, list) else h
                  for h in hints],
    }


class BindingManager:
    """GLOBAL binding registry over the meta plane; one per Storage.
    Safe under the server's thread-per-connection model: every public
    method loads/copies/iterates only while holding the lock."""

    def __init__(self, storage) -> None:
        self._storage = storage
        self._lock = threading.Lock()
        self._cache: Optional[dict[str, dict]] = None
        self._fp: Optional[int] = None  # memoized fingerprint()

    def _load_locked(self) -> dict[str, dict]:
        if self._cache is not None:
            return self._cache
        out: dict[str, dict] = {}
        raw = self._storage.get_meta(_META_INDEX)
        for digest in json.loads(raw) if raw else []:
            rec = self._storage.get_meta(_META_PREFIX + digest.encode())
            if rec:
                out[digest] = json.loads(rec)
        self._cache = out
        return out

    def create(self, norm_sql: str, bind_sql: str, db: str,
               hints: list) -> None:
        digest = binding_digest(norm_sql, db)
        rec = make_record(norm_sql, bind_sql, db, hints)
        with self._lock:
            recs = self._load_locked()
            recs[digest] = rec
            self._storage.put_meta(_META_PREFIX + digest.encode(),
                                   json.dumps(rec).encode())
            self._storage.put_meta(
                _META_INDEX, json.dumps(sorted(recs)).encode())
            self._fp = None

    def drop(self, norm_sql: str, db: str) -> bool:
        digest = binding_digest(norm_sql, db)
        with self._lock:
            recs = self._load_locked()
            if digest not in recs:
                return False
            del recs[digest]
            self._storage.put_meta(_META_PREFIX + digest.encode(), b"")
            self._storage.put_meta(
                _META_INDEX, json.dumps(sorted(recs)).encode())
            self._fp = None
            return True

    def match(self, norm_sql: str, db: str) -> Optional[dict]:
        with self._lock:
            return self._load_locked().get(binding_digest(norm_sql, db))

    def has_any(self) -> bool:
        """O(1) emptiness probe for the per-SELECT fast path."""
        with self._lock:
            return bool(self._load_locked())

    def invalidate(self) -> None:
        """Sibling servers reload on catalog refresh (the bind-info
        load loop analog, bindinfo/handle.go:139 Update)."""
        with self._lock:
            self._cache = None
            self._fp = None

    def fingerprint(self) -> int:
        """Content hash of the binding set (digests AND hint sets) —
        part of the plan-cache key, so cached plans can't outlive a
        binding change (including a same-second re-create with different
        hints) while an unchanged set keeps the cache warm. Memoized
        until the set mutates or a refresh invalidates."""
        with self._lock:
            if self._fp is None:
                recs = self._load_locked()
                self._fp = hash(tuple(sorted(
                    (d, json.dumps(r.get("hints", [])))
                    for d, r in recs.items())))
            return self._fp

    def all(self) -> list[dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._load_locked().values()]
