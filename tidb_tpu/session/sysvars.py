"""System-variable framework: registry, scopes, persistence.

Counterpart of the reference's sysvar subsystem (reference:
sessionctx/variable/sysvar.go — ~400 vars with scope flags;
session/session.go:1048 loads GLOBAL values from mysql.global_variables;
SET handling in executor/set.go). Scaled to the variables real clients,
ORMs and BI tools actually touch on connect, plus the engine's own knobs.

GLOBAL writes persist through the meta keyspace of the storage (the
mysql.global_variables analog), so SET GLOBAL survives restarts on a
durable store. SESSION reads fall back GLOBAL -> default.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

SCOPE_GLOBAL = 1
SCOPE_SESSION = 2
SCOPE_BOTH = SCOPE_GLOBAL | SCOPE_SESSION


@dataclass(frozen=True)
class SysVar:
    name: str
    default: Any
    scope: int = SCOPE_BOTH
    read_only: bool = False


def _v(name, default, scope=SCOPE_BOTH, read_only=False):
    return SysVar(name, default, scope, read_only)


# the connect-time surface of MySQL clients/ORMs + engine knobs
_VARS = [
    _v("version", "5.7.25-TiDB-TPU", read_only=True),
    _v("version_comment", "TiDB-TPU Server (tidb_tpu)", read_only=True),
    _v("version_compile_os", "linux", read_only=True),
    _v("version_compile_machine", "tpu", read_only=True),
    _v("protocol_version", 10, read_only=True),
    _v("license", "Apache License 2.0", read_only=True),
    _v("port", 4000, scope=SCOPE_GLOBAL, read_only=True),
    _v("socket", "", scope=SCOPE_GLOBAL, read_only=True),
    _v("datadir", "/tmp/tidb_tpu", scope=SCOPE_GLOBAL, read_only=True),
    _v("hostname", "localhost", scope=SCOPE_GLOBAL, read_only=True),
    _v("autocommit", 1),
    _v("sql_mode", "ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES,"
       "NO_ZERO_IN_DATE,NO_ZERO_DATE,ERROR_FOR_DIVISION_BY_ZERO,"
       "NO_AUTO_CREATE_USER,NO_ENGINE_SUBSTITUTION"),
    _v("sql_select_limit", 2 ** 64 - 1),
    _v("max_allowed_packet", 67108864),
    _v("net_buffer_length", 16384),
    _v("net_write_timeout", 60),
    _v("net_read_timeout", 30),
    _v("interactive_timeout", 28800),
    _v("wait_timeout", 28800),
    _v("lock_wait_timeout", 31536000),
    _v("innodb_lock_wait_timeout", 50),
    _v("max_execution_time", 0),
    _v("character_set_client", "utf8mb4"),
    _v("character_set_connection", "utf8mb4"),
    _v("character_set_results", "utf8mb4"),
    _v("character_set_server", "utf8mb4"),
    _v("character_set_database", "utf8mb4"),
    _v("character_set_system", "utf8", read_only=True),
    _v("collation_connection", "utf8mb4_bin"),
    _v("collation_server", "utf8mb4_bin"),
    _v("collation_database", "utf8mb4_bin"),
    _v("init_connect", "", scope=SCOPE_GLOBAL),
    _v("time_zone", "SYSTEM"),
    _v("system_time_zone", "UTC", read_only=True),
    _v("lower_case_table_names", 2, scope=SCOPE_GLOBAL, read_only=True),
    _v("explicit_defaults_for_timestamp", 1),
    _v("foreign_key_checks", 0),
    _v("unique_checks", 1),
    _v("auto_increment_increment", 1),
    _v("auto_increment_offset", 1),
    _v("last_insert_id", 0, scope=SCOPE_SESSION),
    _v("identity", 0, scope=SCOPE_SESSION),
    _v("warning_count", 0, scope=SCOPE_SESSION, read_only=True),
    _v("error_count", 0, scope=SCOPE_SESSION, read_only=True),
    _v("tx_isolation", "REPEATABLE-READ"),
    _v("transaction_isolation", "REPEATABLE-READ"),
    _v("tx_read_only", 0),
    _v("transaction_read_only", 0),
    _v("performance_schema", 0, scope=SCOPE_GLOBAL, read_only=True),
    _v("query_cache_type", "OFF", scope=SCOPE_GLOBAL, read_only=True),
    _v("query_cache_size", 0, scope=SCOPE_GLOBAL, read_only=True),
    _v("have_openssl", "DISABLED", read_only=True),
    _v("have_ssl", "DISABLED", read_only=True),
    # default mirrors config max-connections (the config-knob-drift
    # rule pins registry default == config-seeded default, so SHOW
    # VARIABLES on an embedded store matches a default server's)
    _v("max_connections", 512, scope=SCOPE_GLOBAL),
    _v("default_storage_engine", "InnoDB", read_only=True),
    _v("default_authentication_plugin", "mysql_native_password",
       scope=SCOPE_GLOBAL, read_only=True),
    # engine knobs (reference: sessionctx/variable/tidb_vars.go)
    _v("tidb_slow_log_threshold", 300),
    _v("tidb_snapshot", ""),
    _v("tidb_distsql_scan_concurrency", 15),
    _v("tidb_index_lookup_concurrency", 4),
    _v("tidb_mem_quota_query", 1 << 30),
    _v("tidb_mem_oom_action", "SPILL"),  # SPILL | CANCEL (action.go:28)
    _v("tidb_enable_plan_cache", 1),
    # session plan-cache LRU capacity (physical plans + point
    # FastPlans); config performance.plan-cache-size seeds the default
    _v("tidb_plan_cache_size", 128),
    # the TryFastPlan point bypass (plan/fastpath.py): autocommit point
    # SELECT/DML executes against the KV layer with zero planner and
    # zero coprocessor work. Off forces every statement down the full
    # pipeline (debug/AB escape hatch).
    _v("tidb_enable_fast_path", 1),
    _v("tidb_txn_mode", "optimistic"),
    _v("tidb_retry_limit", 10),
    # follower read tier (rpc/replica.py): "follower" routes eligible
    # snapshot SELECTs to serving replicas; "leader" (default) keeps
    # every read local. Config [replica-read] prefer-follower seeds the
    # global default (reference: tidb_replica_read, tidb_vars.go)
    _v("tidb_replica_read", "leader"),
    # bounded-staleness reads: a NEGATIVE number of seconds (-5 = read
    # up to 5s stale, the reference's tidb_read_staleness semantics),
    # capped by replica-read.max-staleness-ms; relaxes the closed-ts
    # fence so a lagging replica can still serve. 0 = exact snapshot.
    _v("tidb_read_staleness", 0),
    _v("tidb_tile_rows", 1 << 22),
    _v("tidb_gc_life_time", "10m0s", scope=SCOPE_GLOBAL),
    _v("tidb_gc_run_interval", "10m0s", scope=SCOPE_GLOBAL),
    _v("tidb_auto_analyze_ratio", 0.5, scope=SCOPE_GLOBAL),
    # ---- file / transport security ------------------------------------
    _v("secure_file_priv", "", scope=SCOPE_GLOBAL, read_only=True),
    # LOAD DATA LOCAL INFILE opt-in: OFF keeps the typed 1235 rejection
    # (no wire sub-protocol). ON accepts LOCAL as a SERVER-side read:
    # authenticated users need FILE or a configured secure_file_priv
    # (which always confines the path); dup errors degrade to IGNORE
    _v("local_infile", 0, scope=SCOPE_GLOBAL),
    _v("require_secure_transport", 0, scope=SCOPE_GLOBAL),
    _v("ssl_ca", "", scope=SCOPE_GLOBAL, read_only=True),
    _v("ssl_cert", "", scope=SCOPE_GLOBAL, read_only=True),
    _v("ssl_key", "", scope=SCOPE_GLOBAL, read_only=True),
    # ---- SQL behavior toggles (accepted; engine behavior noted) -------
    _v("div_precision_increment", 4),
    _v("group_concat_max_len", 1024),
    _v("max_sort_length", 1024),
    _v("sql_safe_updates", 0),
    _v("sql_log_bin", 1),
    _v("sql_notes", 1),
    _v("sql_warnings", 0),
    _v("sql_quote_show_create", 1),
    _v("sql_auto_is_null", 0),
    _v("sql_big_selects", 1),
    _v("sql_buffer_result", 0),
    _v("timestamp", 0, scope=SCOPE_SESSION),
    _v("insert_id", 0, scope=SCOPE_SESSION),
    _v("pseudo_thread_id", 0, scope=SCOPE_SESSION),
    _v("rand_seed1", 0, scope=SCOPE_SESSION),
    _v("rand_seed2", 0, scope=SCOPE_SESSION),
    _v("default_week_format", 0),
    _v("lc_time_names", "en_US"),
    _v("lc_messages", "en_US"),
    _v("big_tables", 0),
    _v("low_priority_updates", 0),
    _v("completion_type", "NO_CHAIN"),
    _v("concurrent_insert", "AUTO", scope=SCOPE_GLOBAL, read_only=True),
    _v("delay_key_write", "ON", scope=SCOPE_GLOBAL, read_only=True),
    _v("character_set_filesystem", "binary"),
    # ---- buffers / limits (accepted for client compat) ----------------
    _v("max_heap_table_size", 16777216),
    _v("tmp_table_size", 16777216),
    _v("sort_buffer_size", 262144),
    _v("join_buffer_size", 262144),
    _v("read_buffer_size", 131072),
    _v("read_rnd_buffer_size", 262144),
    _v("bulk_insert_buffer_size", 8388608),
    _v("max_join_size", 2 ** 64 - 1),
    _v("max_seeks_for_key", 2 ** 64 - 1),
    _v("range_optimizer_max_mem_size", 8388608),
    _v("eq_range_index_dive_limit", 200),
    _v("optimizer_switch", "index_merge=on,index_merge_union=on",
       scope=SCOPE_BOTH),
    _v("optimizer_search_depth", 62),
    _v("table_open_cache", 2000, scope=SCOPE_GLOBAL, read_only=True),
    _v("table_definition_cache", 2000, scope=SCOPE_GLOBAL,
       read_only=True),
    _v("open_files_limit", 65535, scope=SCOPE_GLOBAL, read_only=True),
    _v("thread_cache_size", 0, scope=SCOPE_GLOBAL, read_only=True),
    _v("max_prepared_stmt_count", 16382, scope=SCOPE_GLOBAL),
    _v("max_user_connections", 0, scope=SCOPE_GLOBAL),
    _v("max_connect_errors", 100, scope=SCOPE_GLOBAL),
    _v("connect_timeout", 10, scope=SCOPE_GLOBAL),
    _v("skip_name_resolve", 1, scope=SCOPE_GLOBAL, read_only=True),
    # ---- replication-shaped surface (inert; single-plane engine) ------
    _v("log_bin", 0, scope=SCOPE_GLOBAL, read_only=True),
    _v("server_id", 0, scope=SCOPE_GLOBAL),
    _v("server_uuid", "00000000-0000-0000-0000-000000000000",
       scope=SCOPE_GLOBAL, read_only=True),
    _v("binlog_format", "ROW", scope=SCOPE_GLOBAL),
    _v("binlog_row_image", "FULL", scope=SCOPE_GLOBAL),
    _v("gtid_mode", "OFF", scope=SCOPE_GLOBAL, read_only=True),
    _v("enforce_gtid_consistency", "OFF", scope=SCOPE_GLOBAL,
       read_only=True),
    _v("read_only", 0, scope=SCOPE_GLOBAL),
    _v("super_read_only", 0, scope=SCOPE_GLOBAL),
    _v("offline_mode", 0, scope=SCOPE_GLOBAL),
    # ---- logging surface ----------------------------------------------
    _v("event_scheduler", "OFF", scope=SCOPE_GLOBAL, read_only=True),
    _v("log_output", "FILE", scope=SCOPE_GLOBAL),
    _v("general_log", 0, scope=SCOPE_GLOBAL),
    _v("slow_query_log", 1, scope=SCOPE_GLOBAL),
    _v("slow_query_log_file", "", scope=SCOPE_GLOBAL),
    _v("long_query_time", 10.0, scope=SCOPE_GLOBAL),
    _v("log_queries_not_using_indexes", 0, scope=SCOPE_GLOBAL),
    _v("profiling", 0, scope=SCOPE_SESSION),
    _v("profiling_history_size", 15, scope=SCOPE_SESSION),
    # host sampling-profiler tick rate (@@profiling, /debug/profile)
    _v("tidb_profiler_sample_hz", 97),
    # TRACE drops spans past this cap (bounded span trees)
    _v("tidb_trace_span_cap", 4096),
    # ---- innodb-shaped surface (inert; columnar-epoch engine) ---------
    _v("innodb_buffer_pool_size", 134217728, scope=SCOPE_GLOBAL,
       read_only=True),
    _v("innodb_flush_log_at_trx_commit", 1, scope=SCOPE_GLOBAL),
    _v("innodb_io_capacity", 200, scope=SCOPE_GLOBAL),
    _v("innodb_file_per_table", 1, scope=SCOPE_GLOBAL, read_only=True),
    _v("innodb_large_prefix", "ON", scope=SCOPE_GLOBAL, read_only=True),
    _v("innodb_strict_mode", 1, scope=SCOPE_GLOBAL),
    _v("innodb_print_all_deadlocks", 0, scope=SCOPE_GLOBAL),
    _v("innodb_read_io_threads", 4, scope=SCOPE_GLOBAL, read_only=True),
    _v("innodb_write_io_threads", 4, scope=SCOPE_GLOBAL, read_only=True),
    _v("innodb_page_size", 16384, scope=SCOPE_GLOBAL, read_only=True),
    _v("innodb_version", "5.7.25", scope=SCOPE_GLOBAL, read_only=True),
    _v("ft_min_word_len", 4, scope=SCOPE_GLOBAL, read_only=True),
    _v("ngram_token_size", 2, scope=SCOPE_GLOBAL, read_only=True),
    _v("default_tmp_storage_engine", "InnoDB"),
    _v("internal_tmp_disk_storage_engine", "InnoDB", scope=SCOPE_GLOBAL,
       read_only=True),
    # ---- engine knobs (reference: sessionctx/variable/tidb_vars.go) ---
    _v("tidb_current_ts", 0, scope=SCOPE_SESSION, read_only=True),
    _v("tidb_config", "", scope=SCOPE_SESSION, read_only=True),
    _v("tidb_general_log", 0, scope=SCOPE_GLOBAL),
    _v("tidb_enable_window_function", 1),
    _v("tidb_enable_vectorized_expression", 1),
    _v("tidb_enable_cascades_planner", 0),
    _v("tidb_enable_index_merge", 1),
    _v("tidb_enable_table_partition", "on"),
    _v("tidb_enable_list_partition", 0),
    _v("tidb_hash_join_concurrency", 5),
    _v("tidb_projection_concurrency", 4),
    _v("tidb_hashagg_partial_concurrency", 4),
    _v("tidb_hashagg_final_concurrency", 4),
    _v("tidb_window_concurrency", 4),
    _v("tidb_executor_concurrency", 5),
    _v("tidb_index_serial_scan_concurrency", 1),
    _v("tidb_index_join_batch_size", 25000),
    _v("tidb_index_lookup_size", 20000),
    _v("tidb_index_lookup_join_concurrency", 4),
    _v("tidb_init_chunk_size", 32),
    _v("tidb_max_chunk_size", 1024),
    _v("tidb_skip_utf8_check", 0),
    _v("tidb_skip_ascii_check", 0),
    _v("tidb_opt_agg_push_down", 1),
    _v("tidb_opt_distinct_agg_push_down", 0),
    _v("tidb_opt_join_reorder_threshold", 0),
    _v("tidb_opt_correlation_threshold", 0.9),
    _v("tidb_opt_correlation_exp_factor", 1),
    _v("tidb_opt_insubq_to_join_and_agg", 1),
    _v("tidb_opt_prefer_range_scan", 0),
    _v("tidb_ddl_reorg_worker_cnt", 4, scope=SCOPE_GLOBAL),
    _v("tidb_ddl_reorg_batch_size", 256, scope=SCOPE_GLOBAL),
    _v("tidb_ddl_error_count_limit", 512, scope=SCOPE_GLOBAL),
    _v("tidb_max_delta_schema_count", 1024, scope=SCOPE_GLOBAL),
    _v("tidb_scatter_region", 0, scope=SCOPE_GLOBAL),
    _v("tidb_wait_split_region_finish", 1),
    _v("tidb_wait_split_region_timeout", 300),
    _v("tidb_backoff_lock_fast", 100),
    _v("tidb_backoff_weight", 2),
    _v("tidb_dml_batch_size", 0),
    _v("tidb_batch_insert", 0),
    _v("tidb_batch_delete", 0),
    _v("tidb_batch_commit", 0),
    _v("tidb_constraint_check_in_place", 0),
    _v("tidb_checksum_table_concurrency", 4),
    _v("tidb_isolation_read_engines", "tpu,host", scope=SCOPE_SESSION),
    _v("tidb_store_limit", 0, scope=SCOPE_GLOBAL),
    _v("tidb_low_resolution_tso", 0, scope=SCOPE_SESSION),
    _v("tidb_replica_read", "leader", scope=SCOPE_SESSION),
    _v("tidb_allow_batch_cop", 1),
    _v("tidb_enable_stmt_summary", 1, scope=SCOPE_GLOBAL),
    _v("tidb_stmt_summary_refresh_interval", 1800, scope=SCOPE_GLOBAL),
    _v("tidb_stmt_summary_history_size", 24, scope=SCOPE_GLOBAL),
    _v("tidb_stmt_summary_max_stmt_count", 3000, scope=SCOPE_GLOBAL),
    _v("tidb_stmt_summary_internal_query", 0, scope=SCOPE_GLOBAL),
    _v("tidb_enable_collect_execution_info", 1),
    _v("tidb_enable_async_commit", 1),
    _v("tidb_enable_1pc", 1),
    _v("tidb_enable_clustered_index", "INT_ONLY"),
    _v("tidb_analyze_version", 1),
    _v("tidb_build_stats_concurrency", 4),
    _v("tidb_enable_fast_analyze", 0),
    _v("tidb_expensive_query_time_threshold", 60, scope=SCOPE_GLOBAL),
    _v("tidb_force_priority", "NO_PRIORITY"),
    _v("tidb_enable_noop_functions", 0),
    _v("tidb_row_format_version", 2, scope=SCOPE_GLOBAL),
    _v("tidb_enable_chunk_rpc", 1, scope=SCOPE_SESSION),
    _v("tidb_query_log_max_len", 4096, scope=SCOPE_GLOBAL),
    _v("last_plan_from_binding", 0, scope=SCOPE_SESSION, read_only=True),
    _v("tidb_use_plan_baselines", 1),
]

SYSVARS: dict[str, SysVar] = {v.name: v for v in _VARS}

_META_PREFIX = b"sysvar:"


class SysVarManager:
    """Process-wide GLOBAL values; owned by the Storage (one per 'cluster').

    put/get ride the meta keyspace, so on a durable store SET GLOBAL
    survives restart (mysql.global_variables analog)."""

    def __init__(self, storage) -> None:
        self._storage = storage
        self._globals: dict[str, Any] = {}
        # config-derived defaults: consulted after user SET GLOBALs but
        # before the registry defaults; never persisted (the config file
        # is their durable form)
        self._config_defaults: dict[str, Any] = {}
        self._loaded = False

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        for name, sv in SYSVARS.items():
            raw = self._storage.get_meta(_META_PREFIX + name.encode())
            if raw is not None:
                val: Any = raw.decode("utf-8")
                if isinstance(sv.default, int):
                    try:
                        val = int(val)
                    except ValueError:
                        pass
                self._globals[name] = val

    def get_global(self, name: str) -> Optional[Any]:
        self._load()
        if name in self._globals:  # includes tolerated unknown knobs
            return self._globals[name]
        if name in self._config_defaults:
            return self._config_defaults[name]
        v = SYSVARS.get(name)
        return v.default if v is not None else None

    def set_global(self, name: str, value: Any) -> None:
        self._load()
        self._globals[name] = value
        self._storage.put_meta(_META_PREFIX + name.encode(),
                               str(value).encode("utf-8"))

    def set_config_default(self, name: str, value: Any) -> None:
        """Config-file seeding: wins over registry defaults, loses to
        any persisted/user SET GLOBAL (reference: config feeds sysvar
        bootstrap values without overriding mysql.global_variables)."""
        self._config_defaults[name] = value

    def all_globals(self) -> dict[str, Any]:
        self._load()
        return {name: self._globals.get(
                    name, self._config_defaults.get(name, v.default))
                for name, v in SYSVARS.items()}
