"""System-variable framework: registry, scopes, persistence.

Counterpart of the reference's sysvar subsystem (reference:
sessionctx/variable/sysvar.go — ~400 vars with scope flags;
session/session.go:1048 loads GLOBAL values from mysql.global_variables;
SET handling in executor/set.go). Scaled to the variables real clients,
ORMs and BI tools actually touch on connect, plus the engine's own knobs.

GLOBAL writes persist through the meta keyspace of the storage (the
mysql.global_variables analog), so SET GLOBAL survives restarts on a
durable store. SESSION reads fall back GLOBAL -> default.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

SCOPE_GLOBAL = 1
SCOPE_SESSION = 2
SCOPE_BOTH = SCOPE_GLOBAL | SCOPE_SESSION


@dataclass(frozen=True)
class SysVar:
    name: str
    default: Any
    scope: int = SCOPE_BOTH
    read_only: bool = False


def _v(name, default, scope=SCOPE_BOTH, read_only=False):
    return SysVar(name, default, scope, read_only)


# the connect-time surface of MySQL clients/ORMs + engine knobs
_VARS = [
    _v("version", "5.7.25-TiDB-TPU", read_only=True),
    _v("version_comment", "TiDB-TPU Server (tidb_tpu)", read_only=True),
    _v("version_compile_os", "linux", read_only=True),
    _v("version_compile_machine", "tpu", read_only=True),
    _v("protocol_version", 10, read_only=True),
    _v("license", "Apache License 2.0", read_only=True),
    _v("port", 4000, scope=SCOPE_GLOBAL, read_only=True),
    _v("socket", "", scope=SCOPE_GLOBAL, read_only=True),
    _v("datadir", "/tmp/tidb_tpu", scope=SCOPE_GLOBAL, read_only=True),
    _v("hostname", "localhost", scope=SCOPE_GLOBAL, read_only=True),
    _v("autocommit", 1),
    _v("sql_mode", "ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES,"
       "NO_ZERO_IN_DATE,NO_ZERO_DATE,ERROR_FOR_DIVISION_BY_ZERO,"
       "NO_AUTO_CREATE_USER,NO_ENGINE_SUBSTITUTION"),
    _v("sql_select_limit", 2 ** 64 - 1),
    _v("max_allowed_packet", 67108864),
    _v("net_buffer_length", 16384),
    _v("net_write_timeout", 60),
    _v("net_read_timeout", 30),
    _v("interactive_timeout", 28800),
    _v("wait_timeout", 28800),
    _v("lock_wait_timeout", 31536000),
    _v("innodb_lock_wait_timeout", 50),
    _v("max_execution_time", 0),
    _v("character_set_client", "utf8mb4"),
    _v("character_set_connection", "utf8mb4"),
    _v("character_set_results", "utf8mb4"),
    _v("character_set_server", "utf8mb4"),
    _v("character_set_database", "utf8mb4"),
    _v("character_set_system", "utf8", read_only=True),
    _v("collation_connection", "utf8mb4_bin"),
    _v("collation_server", "utf8mb4_bin"),
    _v("collation_database", "utf8mb4_bin"),
    _v("init_connect", "", scope=SCOPE_GLOBAL),
    _v("time_zone", "SYSTEM"),
    _v("system_time_zone", "UTC", read_only=True),
    _v("lower_case_table_names", 2, scope=SCOPE_GLOBAL, read_only=True),
    _v("explicit_defaults_for_timestamp", 1),
    _v("foreign_key_checks", 0),
    _v("unique_checks", 1),
    _v("auto_increment_increment", 1),
    _v("auto_increment_offset", 1),
    _v("last_insert_id", 0, scope=SCOPE_SESSION),
    _v("identity", 0, scope=SCOPE_SESSION),
    _v("warning_count", 0, scope=SCOPE_SESSION, read_only=True),
    _v("error_count", 0, scope=SCOPE_SESSION, read_only=True),
    _v("tx_isolation", "REPEATABLE-READ"),
    _v("transaction_isolation", "REPEATABLE-READ"),
    _v("tx_read_only", 0),
    _v("transaction_read_only", 0),
    _v("performance_schema", 0, scope=SCOPE_GLOBAL, read_only=True),
    _v("query_cache_type", "OFF", scope=SCOPE_GLOBAL, read_only=True),
    _v("query_cache_size", 0, scope=SCOPE_GLOBAL, read_only=True),
    _v("have_openssl", "DISABLED", read_only=True),
    _v("have_ssl", "DISABLED", read_only=True),
    _v("max_connections", 0, scope=SCOPE_GLOBAL),
    _v("default_storage_engine", "InnoDB", read_only=True),
    _v("default_authentication_plugin", "mysql_native_password",
       scope=SCOPE_GLOBAL, read_only=True),
    # engine knobs (reference: sessionctx/variable/tidb_vars.go)
    _v("tidb_slow_log_threshold", 300),
    _v("tidb_snapshot", ""),
    _v("tidb_distsql_scan_concurrency", 15),
    _v("tidb_index_lookup_concurrency", 4),
    _v("tidb_mem_quota_query", 1 << 30),
    _v("tidb_mem_oom_action", "SPILL"),  # SPILL | CANCEL (action.go:28)
    _v("tidb_enable_plan_cache", 1),
    _v("tidb_txn_mode", "optimistic"),
    _v("tidb_retry_limit", 10),
    _v("tidb_tile_rows", 1 << 22),
    _v("tidb_gc_life_time", "10m0s", scope=SCOPE_GLOBAL),
    _v("tidb_gc_run_interval", "10m0s", scope=SCOPE_GLOBAL),
    _v("tidb_auto_analyze_ratio", 0.5, scope=SCOPE_GLOBAL),
]

SYSVARS: dict[str, SysVar] = {v.name: v for v in _VARS}

_META_PREFIX = b"sysvar:"


class SysVarManager:
    """Process-wide GLOBAL values; owned by the Storage (one per 'cluster').

    put/get ride the meta keyspace, so on a durable store SET GLOBAL
    survives restart (mysql.global_variables analog)."""

    def __init__(self, storage) -> None:
        self._storage = storage
        self._globals: dict[str, Any] = {}
        # config-derived defaults: consulted after user SET GLOBALs but
        # before the registry defaults; never persisted (the config file
        # is their durable form)
        self._config_defaults: dict[str, Any] = {}
        self._loaded = False

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        for name, sv in SYSVARS.items():
            raw = self._storage.get_meta(_META_PREFIX + name.encode())
            if raw is not None:
                val: Any = raw.decode("utf-8")
                if isinstance(sv.default, int):
                    try:
                        val = int(val)
                    except ValueError:
                        pass
                self._globals[name] = val

    def get_global(self, name: str) -> Optional[Any]:
        self._load()
        if name in self._globals:  # includes tolerated unknown knobs
            return self._globals[name]
        if name in self._config_defaults:
            return self._config_defaults[name]
        v = SYSVARS.get(name)
        return v.default if v is not None else None

    def set_global(self, name: str, value: Any) -> None:
        self._load()
        self._globals[name] = value
        self._storage.put_meta(_META_PREFIX + name.encode(),
                               str(value).encode("utf-8"))

    def set_config_default(self, name: str, value: Any) -> None:
        """Config-file seeding: wins over registry defaults, loses to
        any persisted/user SET GLOBAL (reference: config feeds sysvar
        bootstrap values without overriding mysql.global_variables)."""
        self._config_defaults[name] = value

    def all_globals(self) -> dict[str, Any]:
        self._load()
        return {name: self._globals.get(
                    name, self._config_defaults.get(name, v.default))
                for name, v in SYSVARS.items()}
