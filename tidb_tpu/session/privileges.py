"""User accounts + privilege checks, persisted in the meta keyspace.

Counterpart of the reference's privilege subsystem (reference:
privilege/privileges/cache.go — the mysql.user/db/tables_priv grant
tables cached in memory; checks hooked at plan build,
planner/optimize.go:246). Scaled to the statement surface this engine
executes: account management (CREATE/DROP USER, GRANT/REVOKE), the
mysql_native_password verification the wire server needs, and
table/db/global-scope privilege checks enforced by the session before
statements run.

Passwords store as SHA1(SHA1(password)) — MySQL's authentication_string
— so the server can verify the native-password scramble without ever
holding the cleartext: given client response R and salt s,
X := R xor SHA1(s + stored) recovers SHA1(password), and SHA1(X) must
equal stored (reference: server/auth semantics, conn.go:665)."""

from __future__ import annotations

import hashlib
import pickle
import threading
from typing import Optional

PRIVS = frozenset({
    "SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "DROP", "ALTER",
    "INDEX", "ALL", "USAGE", "FILE", "SUPER", "PROCESS", "RELOAD",
    "REFERENCES", "CREATE VIEW", "SHOW VIEW", "TRIGGER", "EXECUTE",
})

_META_KEY = b"priv:users"


def _hash2(password: str) -> bytes:
    return hashlib.sha1(
        hashlib.sha1(password.encode("utf-8")).digest()).digest()


from ..errno import ER_SPECIFIC_ACCESS_DENIED, CodedError


class PrivilegeError(CodedError):
    errno = ER_SPECIFIC_ACCESS_DENIED
    sqlstate = "42000"


class PrivilegeManager:
    """name -> {"auth": SHA1(SHA1(pwd)) bytes | b"" (empty password),
    "grants": set[(priv, db, tbl)]}; '*' wildcards both scopes.
    root@empty-password with ALL on *.* bootstraps (reference:
    session/bootstrap.go:461 creates the root row the same way)."""

    def __init__(self, storage) -> None:
        self._storage = storage
        self._lock = threading.Lock()
        self._users: Optional[dict] = None

    def _load(self) -> dict:
        with self._lock:
            if self._users is None:
                raw = self._storage.get_meta(_META_KEY)
                if raw is not None:
                    self._users = pickle.loads(raw)
                else:
                    self._users = {
                        "root": {"auth": b"",
                                 "grants": {("ALL", "*", "*")}},
                    }
            return self._users

    def _persist(self) -> None:
        self._storage.put_meta(_META_KEY, pickle.dumps(self._users))

    # ---- account management -------------------------------------------
    def create_user(self, name: str, password: str,
                    if_not_exists: bool = False) -> None:
        users = self._load()
        with self._lock:
            if name in users:
                if if_not_exists:
                    return
                raise PrivilegeError(
                    f"Operation CREATE USER failed for '{name}'")
            users[name] = {
                "auth": _hash2(password) if password else b"",
                "grants": set(),
            }
            self._persist()

    def drop_user(self, name: str, if_exists: bool = False) -> None:
        users = self._load()
        with self._lock:
            if name not in users:
                if if_exists:
                    return
                raise PrivilegeError(
                    f"Operation DROP USER failed for '{name}'")
            del users[name]
            self._persist()

    def set_password(self, name: str, password: str) -> None:
        users = self._load()
        with self._lock:
            if name not in users:
                raise PrivilegeError(f"unknown user '{name}'")
            users[name]["auth"] = _hash2(password) if password else b""
            self._persist()

    @staticmethod
    def _validate(privs: list[str]) -> list[str]:
        out = []
        for p in privs:
            p = p.upper()
            if p not in PRIVS:
                raise PrivilegeError(f"unknown privilege '{p}'")
            if p != "USAGE":  # USAGE = "no privileges" (MySQL): a no-op
                out.append(p)
        return out

    def grant(self, privs: list[str], db: str, tbl: str,
              name: str) -> None:
        privs = self._validate(privs)
        users = self._load()
        with self._lock:
            u = users.get(name)
            if u is None:
                raise PrivilegeError(f"unknown user '{name}'")
            for p in privs:
                u["grants"].add((p, db.lower(), tbl.lower()))
            self._persist()

    def revoke(self, privs: list[str], db: str, tbl: str,
               name: str) -> None:
        privs = self._validate(privs)
        users = self._load()
        with self._lock:
            u = users.get(name)
            if u is None:
                raise PrivilegeError(f"unknown user '{name}'")
            for p in privs:
                u["grants"].discard((p, db.lower(), tbl.lower()))
            self._persist()

    def grants_for(self, name: str) -> list[tuple[str, str, str]]:
        users = self._load()
        with self._lock:
            u = users.get(name)
            return sorted(u["grants"]) if u else []

    def exists(self, name: str) -> bool:
        users = self._load()
        with self._lock:
            return name in users

    # ---- checks --------------------------------------------------------
    def check(self, name: Optional[str], priv: str, db: str,
              tbl: str = "*") -> bool:
        """None user = internal session (unchecked); information_schema is
        world-readable (reference: infoschema needs no grants)."""
        if name is None:
            return True
        if priv == "SELECT" and db.lower() == "information_schema":
            return True
        users = self._load()
        with self._lock:
            u = users.get(name)
            # snapshot under the lock: grant/revoke mutate the set from
            # other connection threads (reference caches are swapped
            # atomically, privileges/cache.go)
            grants = list(u["grants"]) if u is not None else None
        if grants is None:
            return False
        db = db.lower()
        tbl = tbl.lower()
        for gp, gdb, gtbl in grants:
            if gp not in (priv, "ALL"):
                continue
            if gdb not in (db, "*"):
                continue
            if gtbl in (tbl, "*"):
                return True
        return False

    # ---- wire auth -----------------------------------------------------
    def verify_native(self, name: str, salt: bytes,
                      response: bytes) -> bool:
        """mysql_native_password check against the stored double-SHA1."""
        users = self._load()
        with self._lock:
            u = users.get(name)
            stored = u["auth"] if u is not None else None
        if stored is None:
            return False
        if stored == b"":
            # empty-password account: MySQL accepts only an EMPTY auth
            # response (a client that sent a scramble used a password)
            return response == b""
        if len(response) != 20:
            return False
        mask = hashlib.sha1(salt + stored).digest()
        candidate = bytes(a ^ b for a, b in zip(response, mask))
        import secrets
        return secrets.compare_digest(hashlib.sha1(candidate).digest(),
                                      stored)
