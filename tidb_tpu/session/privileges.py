"""User accounts + privilege checks, persisted in the meta keyspace.

Counterpart of the reference's privilege subsystem (reference:
privilege/privileges/cache.go — the mysql.user/db/tables_priv grant
tables cached in memory; checks hooked at plan build,
planner/optimize.go:246). Scaled to the statement surface this engine
executes: account management (CREATE/DROP USER, GRANT/REVOKE), the
mysql_native_password verification the wire server needs, and
table/db/global-scope privilege checks enforced by the session before
statements run.

Passwords store as SHA1(SHA1(password)) — MySQL's authentication_string
— so the server can verify the native-password scramble without ever
holding the cleartext: given client response R and salt s,
X := R xor SHA1(s + stored) recovers SHA1(password), and SHA1(X) must
equal stored (reference: server/auth semantics, conn.go:665)."""

from __future__ import annotations

import hashlib
import pickle
import threading
from typing import Optional

PRIVS = frozenset({
    "SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "DROP", "ALTER",
    "INDEX", "ALL", "USAGE", "FILE", "SUPER", "PROCESS", "RELOAD",
    "REFERENCES", "CREATE VIEW", "SHOW VIEW", "TRIGGER", "EXECUTE",
})

_META_KEY = b"priv:users"


def _hash2(password: str) -> bytes:
    return hashlib.sha1(
        hashlib.sha1(password.encode("utf-8")).digest()).digest()


from ..errno import ER_SPECIFIC_ACCESS_DENIED, CodedError


class PrivilegeError(CodedError):
    errno = ER_SPECIFIC_ACCESS_DENIED
    sqlstate = "42000"


class PrivilegeManager:
    """name -> {"auth": SHA1(SHA1(pwd)) bytes | b"" (empty password),
    "grants": set[(priv, db, tbl)]}; '*' wildcards both scopes.
    root@empty-password with ALL on *.* bootstraps (reference:
    session/bootstrap.go:461 creates the root row the same way)."""

    def __init__(self, storage) -> None:
        self._storage = storage
        self._lock = threading.Lock()
        self._users: Optional[dict] = None

    def _load(self) -> dict:
        with self._lock:
            if self._users is None:
                raw = self._storage.get_meta(_META_KEY)
                if raw is not None:
                    self._users = pickle.loads(raw)
                else:
                    self._users = {
                        "root": {"auth": b"",
                                 "grants": {("ALL", "*", "*")}},
                    }
            return self._users

    def _persist(self) -> None:
        self._storage.put_meta(_META_KEY, pickle.dumps(self._users))

    # ---- account management -------------------------------------------
    def create_user(self, name: str, password: str,
                    if_not_exists: bool = False) -> None:
        users = self._load()
        with self._lock:
            if name in users:
                if if_not_exists:
                    return
                raise PrivilegeError(
                    f"Operation CREATE USER failed for '{name}'")
            users[name] = {
                "auth": _hash2(password) if password else b"",
                "grants": set(),
            }
            self._persist()

    def drop_user(self, name: str, if_exists: bool = False) -> None:
        users = self._load()
        with self._lock:
            if name not in users:
                if if_exists:
                    return
                raise PrivilegeError(
                    f"Operation DROP USER failed for '{name}'")
            del users[name]
            # the account may have been a role (DROP USER drops roles in
            # MySQL too): clear edges so a future same-named role isn't
            # silently re-granted to old grantees
            for other in users.values():
                other.get("roles", set()).discard(name)
                other.get("default_roles", set()).discard(name)
            self._persist()

    # ---- roles (reference: privilege/privileges role graph; MySQL 8
    # roles are locked accounts linked by role edges) -------------------
    def create_role(self, names: list[str],
                    if_not_exists: bool = False) -> None:
        users = self._load()
        with self._lock:
            # validate FIRST: a mid-loop failure must not leave partial
            # mutations for a later unrelated _persist to commit
            todo = []
            for name in names:
                if name in users:
                    if if_not_exists:
                        continue
                    raise PrivilegeError(
                        f"Operation CREATE ROLE failed for '{name}'")
                todo.append(name)
            for name in todo:
                users[name] = {"auth": None, "grants": set(),
                               "is_role": True}
            self._persist()

    def drop_role(self, names: list[str], if_exists: bool = False) -> None:
        users = self._load()
        with self._lock:
            todo = []
            for name in names:
                u = users.get(name)
                if u is None or not u.get("is_role"):
                    if if_exists:
                        continue
                    raise PrivilegeError(
                        f"Operation DROP ROLE failed for '{name}'")
                todo.append(name)
            for name in todo:
                del users[name]
                for other in users.values():
                    other.get("roles", set()).discard(name)
                    other.get("default_roles", set()).discard(name)
            self._persist()

    def is_role(self, name: str) -> bool:
        users = self._load()
        with self._lock:
            u = users.get(name)
            return bool(u and u.get("is_role"))

    def grant_roles(self, roles: list[str], targets: list[str],
                    revoke: bool = False) -> None:
        users = self._load()
        with self._lock:
            for r in roles:
                ru = users.get(r)
                if ru is None or not ru.get("is_role"):
                    raise PrivilegeError(f"Unknown role '{r}'")
            for t in targets:  # validate all targets before any mutation
                if t not in users:
                    raise PrivilegeError(f"unknown user '{t}'")
            for t in targets:
                u = users[t]
                edges = u.setdefault("roles", set())
                for r in roles:
                    if revoke:
                        edges.discard(r)
                        u.get("default_roles", set()).discard(r)
                    else:
                        edges.add(r)
            self._persist()

    def roles_of(self, name: str) -> set[str]:
        users = self._load()
        with self._lock:
            u = users.get(name)
            return set(u.get("roles", ())) if u else set()

    def set_default_roles(self, user: str, mode: str,
                          roles: list[str]) -> None:
        users = self._load()
        with self._lock:
            u = users.get(user)
            if u is None:
                raise PrivilegeError(f"unknown user '{user}'")
            granted = u.get("roles", set())
            if mode == "ALL":
                u["default_roles"] = set(granted)
            elif mode == "NONE":
                u["default_roles"] = set()
            else:
                missing = [r for r in roles if r not in granted]
                if missing:
                    raise PrivilegeError(
                        f"role '{missing[0]}' is not granted to "
                        f"'{user}'")
                u["default_roles"] = set(roles)
            self._persist()

    def default_roles(self, name: str) -> set[str]:
        users = self._load()
        with self._lock:
            u = users.get(name)
            return set(u.get("default_roles", ())) if u else set()

    def _expand_roles(self, users: dict, roles) -> set[str]:
        """Transitive closure over role->role edges (roles can be
        granted to roles, MySQL 8 semantics)."""
        out: set[str] = set()
        stack = list(roles)
        while stack:
            r = stack.pop()
            if r in out:
                continue
            ru = users.get(r)
            if ru is None or not ru.get("is_role"):
                continue
            out.add(r)
            stack.extend(ru.get("roles", ()))
        return out

    def set_password(self, name: str, password: str) -> None:
        users = self._load()
        with self._lock:
            if name not in users:
                raise PrivilegeError(f"unknown user '{name}'")
            users[name]["auth"] = _hash2(password) if password else b""
            self._persist()

    @staticmethod
    def _validate(privs: list[str]) -> list[str]:
        out = []
        for p in privs:
            p = p.upper()
            if p not in PRIVS:
                raise PrivilegeError(f"unknown privilege '{p}'")
            if p != "USAGE":  # USAGE = "no privileges" (MySQL): a no-op
                out.append(p)
        return out

    @staticmethod
    def _paired(privs: list[str], cols: Optional[list]):
        """(PRIV, cols|None) pairs validated WITHOUT dropping entries,
        keeping priv<->column alignment (USAGE filtered pairwise); all
        validation happens before any mutation."""
        out = []
        for i, p in enumerate(privs):
            p = p.upper()
            if p not in PRIVS:
                raise PrivilegeError(f"unknown privilege '{p}'")
            if p == "USAGE":  # "no privileges" (MySQL): a no-op
                continue
            pc = cols[i] if cols is not None and i < len(cols) else None
            out.append((p, pc))
        return out

    def grant(self, privs: list[str], db: str, tbl: str,
              name: str, cols: Optional[list] = None) -> None:
        """cols[i] is an optional column list for privs[i] — the
        mysql.columns_priv analog (reference: executor/grant.go column
        scope; privilege/privileges/cache.go columnsPriv)."""
        pairs = self._paired(privs, cols)
        if any(pc for _, pc in pairs) and tbl in ("*", ""):
            raise PrivilegeError(
                "column privileges need a specific table")
        users = self._load()
        with self._lock:
            u = users.get(name)
            if u is None:
                raise PrivilegeError(f"unknown user '{name}'")
            for p, pc in pairs:
                if pc:
                    cg = u.setdefault("col_grants", set())
                    for c in pc:
                        cg.add((p, db.lower(), tbl.lower(), c.lower()))
                else:
                    u["grants"].add((p, db.lower(), tbl.lower()))
            self._persist()

    def revoke(self, privs: list[str], db: str, tbl: str,
               name: str, cols: Optional[list] = None) -> None:
        pairs = self._paired(privs, cols)
        users = self._load()
        with self._lock:
            u = users.get(name)
            if u is None:
                raise PrivilegeError(f"unknown user '{name}'")
            for p, pc in pairs:
                if pc:
                    cg = u.get("col_grants", set())
                    for c in pc:
                        cg.discard((p, db.lower(), tbl.lower(), c.lower()))
                else:
                    u["grants"].discard((p, db.lower(), tbl.lower()))
            self._persist()

    def grants_for(self, name: str) -> list[tuple[str, str, str]]:
        users = self._load()
        with self._lock:
            u = users.get(name)
            return sorted(u["grants"]) if u else []

    def col_grants_for(self, name: str) -> list[tuple[str, str, str, str]]:
        users = self._load()
        with self._lock:
            u = users.get(name)
            return sorted(u.get("col_grants", ())) if u else []

    def rename_users(self, pairs: list) -> None:
        """RENAME USER a TO b (reference: executor/simple.go
        executeRenameUser): validate every pair before mutating any."""
        users = self._load()
        with self._lock:
            taken = set(users)
            for old, new in pairs:
                if old not in taken:
                    raise PrivilegeError(f"unknown user '{old}'")
                if new in taken:  # includes earlier pairs' targets
                    raise PrivilegeError(
                        f"Operation RENAME USER failed for '{new}'")
                taken.discard(old)
                taken.add(new)
            for old, new in pairs:
                users[new] = users.pop(old)
                for other in users.values():
                    edges = other.get("roles")
                    if edges and old in edges:
                        edges.discard(old)
                        edges.add(new)
                    dflt = other.get("default_roles")
                    if dflt and old in dflt:
                        dflt.discard(old)
                        dflt.add(new)
            self._persist()

    def account_names(self) -> list[str]:
        """Sorted non-role account names (a locked snapshot — callers
        must never iterate the live users dict)."""
        users = self._load()
        with self._lock:
            return sorted(n for n, u in users.items()
                          if not u.get("is_role"))

    def exists(self, name: str) -> bool:
        users = self._load()
        with self._lock:
            return name in users

    # ---- checks --------------------------------------------------------
    def check(self, name: Optional[str], priv: str, db: str,
              tbl: str = "*", roles=()) -> bool:
        """None user = internal session (unchecked); information_schema is
        world-readable (reference: infoschema needs no grants). `roles`
        are the session's ACTIVE roles — their grants (transitively, for
        roles granted to roles) union with the user's own."""
        if name is None:
            return True
        if priv == "SELECT" and db.lower() == "information_schema":
            return True
        users = self._load()
        with self._lock:
            u = users.get(name)
            # snapshot under the lock: grant/revoke mutate the set from
            # other connection threads (reference caches are swapped
            # atomically, privileges/cache.go)
            grants = list(u["grants"]) if u is not None else None
            col_grants = list(u.get("col_grants", ())) if u is not None \
                else []
            if grants is not None and roles:
                for r in self._expand_roles(users, roles):
                    grants.extend(users[r]["grants"])
                    col_grants.extend(users[r].get("col_grants", ()))
        if grants is None:
            return False
        db = db.lower()
        tbl = tbl.lower()
        if self._match(grants, priv, db, tbl):
            return True
        # MySQL: holding the privilege on ANY column of the table passes
        # the table-level gate; exact columns check at resolution
        # (check_columns)
        return any(gp in (priv, "ALL") and gdb == db and gtbl == tbl
                   for gp, gdb, gtbl, _ in col_grants)

    @staticmethod
    def _match(grants, priv: str, db: str, tbl: str) -> bool:
        for gp, gdb, gtbl in grants:
            if gp not in (priv, "ALL"):
                continue
            if gdb not in (db, "*"):
                continue
            if gtbl in (tbl, "*"):
                return True
        return False

    def has_col_grants(self, name: Optional[str], roles=()) -> bool:
        """O(1)-ish probe: does this principal hold ANY column-scoped
        grant? The hot read path skips all column enforcement when not
        (full-table access is already gated statement-level)."""
        if name is None:
            return False
        users = self._load()
        with self._lock:
            u = users.get(name)
            if u is None:
                return False
            if u.get("col_grants"):
                return True
            if roles:
                return any(users[r].get("col_grants")
                           for r in self._expand_roles(users, roles))
        return False

    def check_columns(self, name: Optional[str], priv: str, db: str,
                      tbl: str, cols, roles=()) -> Optional[str]:
        """First column of `cols` the user may NOT touch, or None when
        all are allowed. Enforcement applies only to principals whose
        access to THIS table comes through column grants; users with a
        full table/db/global grant — or with no grants on the base table
        at all (e.g. access mediated by a view they hold SELECT on,
        already gated statement-level) — pass."""
        if name is None:
            return None
        db = db.lower()
        tbl = tbl.lower()
        if priv == "SELECT" and db == "information_schema":
            return None
        users = self._load()
        with self._lock:
            u = users.get(name)
            if u is None:
                return None
            grants = list(u["grants"])
            col_grants = set(u.get("col_grants", ()))
            if roles:
                for r in self._expand_roles(users, roles):
                    grants.extend(users[r]["grants"])
                    col_grants.update(users[r].get("col_grants", ()))
        if self._match(grants, priv, db, tbl):
            return None
        if not any(gdb == db and gtbl == tbl
                   for _, gdb, gtbl, _c in col_grants):
            return None  # no column route to this table: defer to gates
        for c in cols:
            c = c.lower()
            if (priv, db, tbl, c) not in col_grants and \
                    ("ALL", db, tbl, c) not in col_grants:
                return c
        return None

    # ---- wire auth -----------------------------------------------------
    def verify_native(self, name: str, salt: bytes,
                      response: bytes) -> bool:
        """mysql_native_password check against the stored double-SHA1."""
        users = self._load()
        with self._lock:
            u = users.get(name)
            stored = u["auth"] if u is not None else None
            if u is not None and u.get("is_role"):
                stored = None  # roles are locked accounts: no login
        if stored is None:
            return False
        if stored == b"":
            # empty-password account: MySQL accepts only an EMPTY auth
            # response (a client that sent a scramble used a password)
            return response == b""
        if len(response) != 20:
            return False
        mask = hashlib.sha1(salt + stored).digest()
        candidate = bytes(a ^ b for a, b in zip(response, mask))
        import secrets
        return secrets.compare_digest(hashlib.sha1(candidate).digest(),
                                      stored)
