"""Session: statement lifecycle over the storage + planner + executors.

Counterpart of the reference's session package (reference:
session/session.go — ExecuteStmt :1328, runStmt :1438, CommitTxn :573) plus
the DDL executor for the synchronous single-node DDL path (reference's async
owner-based DDL, ddl/ddl.go:522, arrives with the multi-node tier).

Txn model: autocommit by default; BEGIN opens an explicit optimistic txn;
statement-level staging gives per-statement rollback inside a txn
(reference: session/txn.go:52-87 staging).
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass
import threading
from typing import Any, Optional

import numpy as np

from ..catalog.schema import Catalog, ColumnInfo, IndexInfo, TableInfo
from ..chunk.chunk import Chunk
from ..copr.client import CopClient
from ..copr.npeval import NumpyEval, _truthy
from ..executor.engine import ExecContext, run_physical
from ..plan.builder import PlanBuilder, PlanError, _literal_const
from ..plan.physical import explain_plan, optimize
from ..sql import ast
from ..sql.parser import ParseError, parse_sql
from ..store.storage import (Storage, Transaction,
                             TxnTooLargeError, WriteConflictError)
from ..store.table_store import TableStore
from ..types.field_type import FieldType, TypeKind
from ..types.value import Decimal


from ..errno import wrap as err_wrap
from ..errno import (
    ER_BAD_FIELD,
    ER_BAD_NULL,
    ER_CANT_CREATE_FILE,
    ER_DATA_INCONSISTENT,
    ER_DUP_ENTRY,
    ER_FILE_EXISTS,
    ER_FILE_NOT_FOUND,
    ER_KILL_DENIED,
    ER_NO_SUCH_TABLE,
    ER_NOT_SUPPORTED_YET,
    ER_OPTION_PREVENTS_STATEMENT,
    ER_PARSE_ERROR,
    ER_QUERY_INTERRUPTED,
    ER_QUERY_MEM_EXCEEDED,
    ER_SPECIFIC_ACCESS_DENIED,
    ER_TABLE_EXISTS,
    ER_TABLEACCESS_DENIED,
    ER_TEXTFILE_NOT_READABLE,
    ER_TIKV_SERVER_BUSY,
    ER_TRUNCATED_WRONG_VALUE,
    ER_UNKNOWN_SYSTEM_VARIABLE,
    ER_VAR_READONLY,
    ER_WRONG_VALUE_COUNT_ON_ROW,
    CodedError,
)


class SQLError(CodedError):
    """Session-layer error; raise sites attach specific errnos
    (reference terror pattern, util/dbterror/terror.go)."""


@dataclass
class ResultSet:
    column_names: list[str]
    rows: list[tuple[Any, ...]]
    affected: int = 0
    # column field types when known (SELECT paths); the wire server uses
    # these for protocol column definitions (reference: server/conn.go
    # writeResultset column metadata)
    column_types: Optional[list[FieldType]] = None

    def __repr__(self) -> str:
        return f"ResultSet({self.column_names}, {len(self.rows)} rows)"


class Session:
    def __init__(self, storage: Optional[Storage] = None, db: str = "test",
                 cop: Optional[CopClient] = None) -> None:
        self.storage = storage if storage is not None else Storage()
        self.catalog: Catalog = self.storage.catalog
        self.current_db = db
        # default coprocessor resolves LAZILY at first access (the first
        # statement that builds an ExecContext): the mesh plane's active
        # check counts devices, which initializes the JAX backend — a
        # session doing metadata-only work must not grab the TPU at
        # construction time
        self._cop: Optional[CopClient] = cop
        self._prepared: dict[int, tuple] = {}
        self._next_stmt_id = 0
        self.txn: Optional[Transaction] = None
        self.in_explicit_txn = False
        # authenticated account for privilege checks; None = internal
        # session, unchecked (reference: planner/optimize.go:246 hook)
        self.user: Optional[str] = None
        # session-scope system variable overrides + user variables
        # (reference: sessionctx/variable/session.go SessionVars)
        self.vars: dict[str, Any] = {}
        self.user_vars: dict[str, Any] = {}
        self._stmt_seq = 0
        self.last_mem_peak = 0  # bytes; per-statement tracker peak
        self.last_spill_count = 0
        # last statement's attribution (stage totals, per-operator
        # exclusive wall / stage split / transfer bytes) — the embedded
        # read side of the Top SQL plane (bench.py persists these)
        self.last_stages: dict[str, float] = {}
        self.last_op_wall: dict[str, float] = {}
        self.last_op_stages: dict[str, dict[str, float]] = {}
        self.last_op_bytes: dict[str, int] = {}
        # per-operator mesh balance ([max shard share, max skew]) from
        # the flight recorder — empty on single-device statements
        self.last_op_mesh: dict[str, list] = {}
        # engine tag per coprocessor read ("device[fat]@mesh8",
        # "host(fragment:key-span)", ...) — the device/host path
        # decision + gate reason, persisted by bench.py per timed query
        self.last_engines: list[str] = []
        self._pending_parse_s = 0.0
        # SQL-text plan cache: key -> (invalidation gen, plan) — a true
        # LRU (move-to-back on hit, evict-oldest at capacity) holding
        # BOTH physical plans and point FastPlans under the same keys,
        # including the prepared-statement #stmt{id} keys
        # (reference: prepared-plan cache, planner/core/common_plans.go +
        # kvcache LRU; text-keyed here because identical statement replay
        # dominates the workloads the cache exists for)
        from collections import OrderedDict
        self._plan_cache: "OrderedDict" = OrderedDict()
        self._plan_cache_key: Optional[str] = None
        # did the last statement's plan come from the cache? (surfaced
        # by EXPLAIN ANALYZE's point row and the fast-path lint)
        self.last_plan_from_cache = False
        # SESSION-scope plan bindings (bindinfo/session_handle.go analog)
        self.session_bindings: dict[str, dict] = {}
        self._binding_gen = 0
        self._binding_match_sql: Optional[str] = None
        self._raw_sql: Optional[str] = None
        # single top-level SELECT text, the only shape the replica-read
        # router may forward (rpc/replica.py)
        self._route_sql: Optional[str] = None
        # ACTIVE roles (SET ROLE); wire login activates default roles
        self.active_roles: set[str] = set()
        # processlist state (Info/Time columns)
        self.in_flight_sql: Optional[str] = None
        self.in_flight_since: Optional[float] = None
        self._stmt_auto_id: Optional[int] = None
        self._found_rows = 0
        self._row_count = -1
        self._is_guard = None  # held infoschema viewer lock, if any
        self.plan_cache_hits = 0
        # KILL plane: QUERY kill interrupts the running statement;
        # CONNECTION kill is handled by the server (socket teardown).
        # Global connection id (embeds the server/node id in shared mode)
        self.conn_id: Optional[int] = None
        self.killed = threading.Event()
        # @@profiling ring: per-statement sampling profiles served by
        # SHOW PROFILES / SHOW PROFILE / information_schema.profiling
        self._profiles: list[dict] = []
        self._profile_seq = 0
        # per-statement warnings (SHOW WARNINGS): degraded cluster_*
        # fan-outs report unreachable peers here instead of failing
        self.warnings: list[tuple[str, int, str]] = []
        # server-wide overload protection (util/governor.py): the LIVE
        # per-statement tracker root while one is registered with the
        # memory governor (processlist MEM reads it), the governor-kill
        # latch distinguishing 8175 from a plain KILL's 1317, and the
        # admission re-entrancy depth (INSERT..SELECT must not buy a
        # second execution token and self-deadlock at token-limit 1)
        self._live_mem = None
        self._governor_killed = False
        self._admission_depth = 0
        # serializes the governor's kill callback against statement
        # tracker install/uninstall: the guard-then-set in
        # _governor_kill must be atomic or a late callback could flag
        # the session's NEXT statement
        self._gov_lock = threading.Lock()

    @property
    def cop(self) -> CopClient:
        """Coprocessor client, resolved on first use: the storage's
        SHARED mesh client when the process mesh plane is active (>1
        device + enabled) so sharded epochs stay device-resident across
        sessions, else a plain per-session CopClient (exact pre-mesh
        behavior). Lazy because the plane's active check initializes
        the JAX backend."""
        if self._cop is None:
            from ..copr import mesh as _mesh
            self._cop = _mesh.client_for(self.storage)
        return self._cop

    @cop.setter
    def cop(self, client: Optional[CopClient]) -> None:
        self._cop = client

    def add_warning(self, message: str, code: int = 1105,
                    level: str = "Warning") -> None:
        self.warnings.append((level, code, message))

    # ==================== public API ====================
    def execute(self, sql: str) -> ResultSet:
        """Execute one or more ;-separated statements; returns the last
        statement's result."""
        if self.storage.shared:
            # multi-process deployments: catch up with sibling servers'
            # commits + schema changes before planning (the per-statement
            # domain-reload; store/storage.py refresh)
            self.storage.refresh()
        import time as _time
        t_parse = _time.perf_counter()
        try:
            stmts = parse_sql(sql)
        except ParseError as e:
            self.storage.obs.query_errors.inc()
            raise SQLError(f"parse error: {e}",
                           errno=getattr(e, 'errno', ER_PARSE_ERROR)) from None
        # parse happens before the per-statement recorder exists; stash
        # it so the first statement's recorder books it as a 'parse'
        # stage — without this the attribution plane undercounts short
        # statements by exactly the lexer/parser time
        self._pending_parse_s = _time.perf_counter() - t_parse
        result = ResultSet([], [])
        single = len(stmts) == 1
        for i, stmt in enumerate(stmts):
            label = sql if single else \
                f"[stmt {i + 1}/{len(stmts)}] {sql}"
            # single-statement SELECT text is the plan-cache key; DML
            # text keys too, for the point fast path's FastPlan cache
            # (plan/fastpath.py — the slow DML paths never consult it)
            is_select = single and isinstance(
                stmt, (ast.SelectStmt, ast.SetOpStmt))
            self._plan_cache_key = sql if (
                is_select or (single and isinstance(
                    stmt, (ast.InsertStmt, ast.UpdateStmt,
                           ast.DeleteStmt)))) else None
            self._binding_match_sql = sql if is_select else None
            self._raw_sql = sql if single else None
            # the replica-read router forwards SQL TEXT, so it only
            # ever routes a statement that IS its own text: a single
            # top-level SELECT (INSERT..SELECT re-enters _exec_select
            # with this unset; prepared statements carry bound ASTs,
            # not reproducible text)
            self._route_sql = sql if is_select else None
            try:
                # batch members skip digest recording: per-statement text
                # isn't recoverable from the batch label, and raw batch
                # text would flood the digest table with unnormalizable
                # entries
                result = self._execute_observed(
                    stmt, label, digest_sql=sql if single else None)
            finally:
                self._plan_cache_key = None
                self._binding_match_sql = None
                self._raw_sql = None
                self._route_sql = None
        # delta-driven auto-analyze at statement boundaries (the reference
        # runs this in the stats owner's background loop,
        # statistics/handle/update.go:860; single-process checks inline)
        self._stmt_seq += 1
        if self._stmt_seq % 64 == 0 and self.txn is None:
            self.storage.stats.auto_analyze(self.storage, self.catalog)
        return result

    def _execute_observed(self, stmt: ast.Stmt, sql: str,
                          digest_sql: Optional[str] = None) -> ResultSet:
        """Run one statement with metrics + slow-log + statement-digest
        accounting — shared by the text protocol and COM_STMT_EXECUTE
        (reference: both paths pass through ExecStmt in
        executor/adapter.go; digests feed util/stmtsummary)."""
        import time as _time

        from .. import obs
        from ..obs import DEFAULT_SLOW_THRESHOLD_MS

        from ..util import interrupt

        o = self.storage.obs
        t0 = _time.perf_counter()
        o.queries.inc(type=type(stmt).__name__.removesuffix("Stmt"))
        failed = False
        shed = False
        rows_out = 0
        # arm the per-statement kill flag (KILL QUERY clears with the
        # statement; KILL CONNECTION leaves it set and the server drops
        # the socket)
        self.killed.clear()
        self._governor_killed = False
        self.last_plan_from_cache = False
        # per-statement working-set accounting: reset so a DML or a
        # failed statement never inherits the previous SELECT's peak in
        # the digest table / slow log (the select path refreshes these
        # in its finally, so governor kills still report their weight)
        self.last_mem_peak = 0
        self.last_spill_count = 0
        interrupt.install(self.killed)
        # @@max_execution_time: a per-statement deadline for SELECTs
        # (MySQL scopes the variable to read-only statements) riding
        # the SAME interrupt plane as KILL QUERY — the engine already
        # polls the flag between plan nodes and device tiles, so an
        # expired statement dies at the next checkpoint with 3024
        # instead of 1317 (reference: executor/adapter.go handleNoDelay
        # + the tidb_mem/max_execution_time kill path)
        deadline_timer = None
        self._deadline_expired = False
        if isinstance(stmt, (ast.SelectStmt, ast.SetOpStmt)):
            try:
                max_ms = int(self._sysvar_value("max_execution_time")
                             or 0)
            except (TypeError, ValueError, SQLError):
                max_ms = 0
            if max_ms > 0:
                def _expire():
                    self._deadline_expired = True
                    self.killed.set()
                deadline_timer = threading.Timer(max_ms / 1000.0,
                                                 _expire)
                deadline_timer.daemon = True
                deadline_timer.start()
        # warnings reset per statement — except SHOW WARNINGS and
        # table-less SELECTs (SELECT @@warning_count, SELECT 1), which
        # MySQL defines as reading the PREVIOUS statement's list
        preserves_warnings = (
            (isinstance(stmt, ast.ShowStmt) and stmt.kind == "WARNINGS")
            or (isinstance(stmt, ast.SelectStmt) and stmt.from_ is None
                and not self._collect_table_names(stmt)))
        if not preserves_warnings:
            self.warnings = []
        # processlist state (SHOW PROCESSLIST reads these from siblings)
        self.in_flight_sql = sql[:256]
        self.in_flight_since = _time.time()
        self._stmt_auto_id = None
        # per-statement dispatch-stage recorder (always on: two clock
        # reads + a dict update per stage) feeding the slow log and
        # EXPLAIN ANALYZE (reference: execdetails on every statement)
        prev_rec = obs.active_stage_recorder()
        rec = obs.StageRecorder()
        # typed wait-state ledger (tso/lease/backoff/2PC/fsync waits):
        # allocated ONLY while performance.wait-profile-enabled is on —
        # disabled, the statement path provably never builds or touches
        # one (the poison/zero-alloc contract test_trace pins)
        prev_led = obs.active_wait_ledger()
        led = obs.WaitLedger() if o.waitprofile.enabled else None
        pp = getattr(self, "_pending_parse_s", 0.0)
        if pp:
            # the batch's parse time books against its first statement
            rec.add("parse", pp)
            rec.add_op_stage("(session)", "parse", pp)
            self._pending_parse_s = 0.0
        # route @@time_zone to the scalar-function layer for the
        # statement's duration: FROM_UNIXTIME formats in the session
        # time zone like MySQL (the round-5 ADVICE finding; the %-
        # strftime portability half was fixed in PR 1)
        from ..copr import funcs as _funcs
        try:
            tz = str(self._sysvar_value("time_zone") or "SYSTEM")
        except (TypeError, ValueError, SQLError):
            tz = "SYSTEM"
        # the TLS frames (stage recorder, session time zone) install
        # INSIDE the protected region: anything raising between an
        # install and the statement body — the profiler start, DML
        # admission — must still restore them in the finally, or the
        # frame leaks onto this worker thread for its next statement
        # (tls-frame-hygiene analysis rule). Restoring a never-
        # installed time zone writes None, which reads as SYSTEM.
        prev_tz = None
        prof = None
        try:
            obs.install_stage_recorder(rec)
            obs.install_wait_ledger(led)
            prev_tz = _funcs.install_session_time_zone(tz)
            # @@profiling: sample THIS thread's stacks for the
            # statement (reference: util/profile; MySQL SHOW PROFILE
            # semantics)
            prof = self._maybe_start_profiler(stmt)
            if isinstance(stmt, (ast.InsertStmt, ast.UpdateStmt,
                                 ast.DeleteStmt, ast.LoadDataStmt)):
                # DML admits at the TOP priority class: point writes
                # must not starve behind queued analytical scans
                # (SELECTs admit inside _exec_select, where the
                # planner's cost estimate is in hand)
                from ..util.governor import PRI_DML
                with self._admission(PRI_DML):
                    rs = self._execute_stmt(stmt)
            else:
                rs = self._execute_stmt(stmt)
            rows_out = len(rs.rows)
            if self._stmt_auto_id is not None:
                self.vars["last_insert_id"] = self._stmt_auto_id
            # ROW_COUNT(): affected rows of the last DML, -1 otherwise
            self._row_count = rs.affected if isinstance(
                stmt, (ast.InsertStmt, ast.UpdateStmt, ast.DeleteStmt,
                       ast.LoadDataStmt)) else -1
            return rs
        except interrupt.QueryInterrupted:
            failed = True
            o.query_errors.inc()
            if self._governor_killed:
                # the server memory governor picked this statement as
                # the heaviest cancellable one: 8175-family, server-
                # scoped message (the per-query quota path raises its
                # own QueryMemExceeded with the [conn] form)
                raise SQLError(
                    "Out Of Memory Quota! [server] statement cancelled "
                    "by the memory governor: tidb-server memory usage "
                    "crossed server-memory-limit and this was the "
                    "heaviest cancellable statement",
                    errno=ER_QUERY_MEM_EXCEEDED) from None
            if self._deadline_expired:
                from ..errno import ER_QUERY_TIMEOUT
                raise SQLError(
                    "Query execution was interrupted, maximum statement "
                    "execution time exceeded",
                    errno=ER_QUERY_TIMEOUT) from None
            raise SQLError("Query execution was interrupted",
                           errno=ER_QUERY_INTERRUPTED) from None
        except Exception as e:
            failed = True
            from ..util.governor import AdmissionTimeout
            shed = isinstance(e, AdmissionTimeout)
            o.query_errors.inc()
            raise
        finally:
            if deadline_timer is not None:
                deadline_timer.cancel()
            self._deadline_expired = False
            interrupt.install(None)
            obs.install_stage_recorder(prev_rec)
            obs.install_wait_ledger(prev_led)
            _funcs.install_session_time_zone(prev_tz)
            self.in_flight_sql = None
            if self._is_guard is not None:
                self._is_guard.release()
                self._is_guard = None
            dt = _time.perf_counter() - t0
            if prof is not None:
                self._finish_profile(prof, sql, dt)
            o.query_seconds.observe(dt)
            # the statement's attribution, readable by embedded callers
            # (bench.py persists these per timed query)
            self.last_stages = rec.totals
            self.last_op_wall = rec.op_wall
            self.last_op_stages = rec.ops
            self.last_op_bytes = rec.op_bytes
            self.last_op_mesh = rec.op_mesh
            self.last_engines = rec.engines
            self.last_waits = led.totals if led is not None else {}
            # worst shard skew of the statement's sharded dispatches
            # (0 = none); surfaces in the slow log + Top SQL
            mesh_skew = 0.0
            if rec.op_mesh:
                mesh_skew = max(v[1] for v in rec.op_mesh.values())
            # mesh skew warnings raised by the flight recorder during
            # this statement become SHOW WARNINGS entries (self._cop,
            # not self.cop: the property would lazily build a mesh
            # plane on statements that never dispatched)
            c = self._cop
            if c is not None:
                if failed:
                    # an interrupted/failed statement leaves queued
                    # per-shard stats uncollected; drop them so they
                    # are not folded into the next statement's mesh
                    # accounting
                    c.discard_mesh_pending()
                for w in c.drain_mesh_warnings():
                    self.add_warning(w)
            if digest_sql is not None:
                o.statements.record(digest_sql, self.current_db, dt,
                                    rows_out, failed,
                                    mem_peak=self.last_mem_peak,
                                    spill_count=self.last_spill_count)
            try:
                thresh = float(
                    self._sysvar_value("tidb_slow_log_threshold"))
            except (TypeError, ValueError, SQLError):
                thresh = DEFAULT_SLOW_THRESHOLD_MS
            slow = dt * 1e3 >= thresh
            # the Top SQL aggregator feed: gated on `enabled` HERE so a
            # disabled plane costs zero work and zero allocations on
            # the statement path (the digest/normalize hash is the
            # expensive part)
            topsql = o.topsql
            # workload-history feed: gated on `enabled` HERE like the
            # Top SQL plane, so a disabled history plane costs zero
            # work and zero allocations on the statement path
            history = self.storage.history
            hist_on = history.enabled and digest_sql is not None
            # wait-profile feed: the ledger only exists while the plane
            # is enabled, so this adds zero work when it is off
            wp_on = led is not None and led.totals \
                and digest_sql is not None
            if slow or hist_on or wp_on or \
                    (topsql.enabled and digest_sql is not None):
                import hashlib
                # same digest the statements_summary uses, so slow-log
                # and top-sql entries join against the digest table
                norm = o.statements.normalize(digest_sql or sql)
                digest = hashlib.sha256(norm.encode()).hexdigest()[:32]
                if hist_on:
                    history.observe(
                        digest, norm[:512], self.current_db, dt,
                        engines=rec.engines, stages=rec.totals,
                        rows=rows_out, failed=failed,
                        op_mesh=rec.op_mesh)
                if wp_on:
                    o.waitprofile.record(digest, norm[:512],
                                         self.current_db, dt,
                                         led.totals)
                if topsql.enabled and digest_sql is not None:
                    topsql.record(
                        digest, norm[:512], self.current_db, dt,
                        stages=rec.totals, op_wall=rec.op_wall,
                        op_stages=rec.ops, op_bytes=rec.op_bytes,
                        rows=rows_out, failed=failed, shed=shed,
                        killed=self._governor_killed,
                        op_mesh={k: v[0] for k, v in
                                 rec.op_mesh.items()} or None,
                        waits=led.totals if led is not None else None)
                if slow:
                    o.record_slow(sql, self.current_db, dt,
                                  plan_digest=digest,
                                  stages=rec.snapshot(),
                                  mem_peak=self.last_mem_peak,
                                  spill_count=self.last_spill_count,
                                  op_wall=rec.op_wall,
                                  mesh_skew=mesh_skew,
                                  waits=dict(led.totals)
                                  if led is not None else None)

    def query(self, sql: str) -> list[tuple[Any, ...]]:
        return self.execute(sql).rows

    # ==================== statement profiling ====================
    def _maybe_start_profiler(self, stmt: ast.Stmt):
        """Start a per-statement stack sampler when @@profiling is on.
        SET and SHOW PROFILE[S] are exempt (MySQL behaves the same —
        toggling/inspecting profiles must not clobber the ring)."""
        if isinstance(stmt, ast.SetStmt):
            return None
        if isinstance(stmt, ast.ShowStmt) and \
                stmt.kind in ("PROFILE", "PROFILES"):
            return None
        try:
            v = self._sysvar_value("profiling")
        except SQLError:
            return None
        if str(v).upper() not in ("1", "ON", "TRUE", "YES"):
            return None
        from .. import obs
        try:
            hz = float(self._sysvar_value("tidb_profiler_sample_hz") or 97)
        except (TypeError, ValueError, SQLError):
            hz = 97.0
        try:
            return obs.SamplingProfiler(
                hz=hz, thread_ids={threading.get_ident()}).start()
        except Exception:
            # runs before the statement's try/finally: a sampler that
            # cannot start must not fail (or leak into) the statement
            return None

    def _finish_profile(self, prof, sql: str, duration_s: float) -> None:
        try:
            profile = prof.stop()
        except Exception:
            return
        self._profile_seq += 1
        self._profiles.append({
            "query_id": self._profile_seq,
            "sql": sql[:512],
            "duration": duration_s,
            "profile": profile,
        })
        try:
            raw = self._sysvar_value("profiling_history_size")
            cap = 15 if raw is None or raw == "" else int(raw)
        except (TypeError, ValueError, SQLError):
            cap = 15
        if cap <= 0:  # MySQL: history size 0 retains nothing
            self._profiles.clear()
        else:
            del self._profiles[:max(len(self._profiles) - cap, 0)]

    # ==================== prepared statements ====================
    def prepare(self, sql: str) -> tuple[int, int]:
        """Server-side prepare (reference: server/conn_stmt.go
        handleStmtPrepare + planner PrepareExec): parse once, count '?'
        markers; returns (stmt_id, n_params)."""
        from ..sql.parser import Parser

        try:
            parser = Parser(sql)
            stmts = parser.parse()
        except ParseError as e:
            raise SQLError(f"parse error: {e}",
                           errno=getattr(e, 'errno', ER_PARSE_ERROR)) from None
        if len(stmts) != 1:
            raise SQLError("prepared statement must be a single statement")
        self._next_stmt_id += 1
        sid = self._next_stmt_id
        self._prepared[sid] = (stmts[0], parser.param_count, sql)
        return sid, parser.param_count

    def execute_prepared(self, stmt_id: int, params: list) -> ResultSet:
        """Bind parameters and run (reference: server/conn_stmt.go
        handleStmtExecute). Binding substitutes literals into a copy of
        the AST; the statement replans per execution (plan cache later)."""
        import copy

        entry = self._prepared.get(stmt_id)
        if entry is None:
            raise SQLError(f"unknown prepared statement {stmt_id}")
        stmt, n_params, raw_sql = entry
        if len(params) != n_params:
            raise SQLError(
                f"expected {n_params} parameters, got {len(params)}")
        bound = copy.deepcopy(stmt)
        if n_params:
            bound = _bind_params(bound, params)
        # prepared plans cache per (stmt, bound params): repeated
        # identical executions reuse the physical plan — or the point
        # FastPlan on the COM_STMT_EXECUTE fast path (reference:
        # prepared-plan cache, common_plans.go getPhysicalPlan)
        if isinstance(bound, (ast.SelectStmt, ast.SetOpStmt,
                              ast.InsertStmt, ast.UpdateStmt,
                              ast.DeleteStmt)):
            self._plan_cache_key = f"#stmt{stmt_id}:{params!r}"
        if isinstance(bound, (ast.SelectStmt, ast.SetOpStmt)):
            # bindings match on the PREPARE text: its '?' markers line up
            # with the literal-normalized binding key
            self._binding_match_sql = raw_sql
        try:
            return self._execute_observed(bound, f"EXECUTE stmt#{stmt_id}")
        finally:
            self._plan_cache_key = None
            self._binding_match_sql = None

    def close_prepared(self, stmt_id: int) -> None:
        self._prepared.pop(stmt_id, None)

    # ==================== statement dispatch ====================
    def _execute_stmt(self, stmt: ast.Stmt) -> ResultSet:
        if self.user is not None:
            self._check_privileges(stmt)
        # OLTP fast path: autocommit point SELECT/UPDATE/DELETE and
        # literal INSERT VALUES bypass the whole plan/dispatch pipeline
        # (plan/fastpath.py — the reference's TryFastPlan point plans,
        # planner/core/point_get_plan.go:413). Anything the recognizer
        # rejects falls through to the unchanged paths below.
        rs = self._try_fast_path(stmt)
        if rs is not None:
            return rs
        if isinstance(stmt, ast.KillStmt):
            self._exec_kill(stmt)
            return ResultSet([], [])
        if isinstance(stmt, ast.CreateViewStmt):
            with self.storage.ddl_section():
                return self._exec_create_view(stmt)
        if isinstance(stmt, ast.DropViewStmt):
            with self.storage.ddl_section():
                return self._exec_drop_view(stmt)
        if isinstance(stmt, ast.AlterUserStmt):
            from .privileges import PrivilegeError
            target = stmt.name or self.user or "root"
            if target != (self.user or "root"):
                self._require_super()  # changing OWN password needs none
            try:
                self.storage.privileges.set_password(target,
                                                     stmt.password)
            except PrivilegeError as e:
                if stmt.if_exists:
                    return ResultSet([], [])
                raise err_wrap(SQLError, e) from None
            return ResultSet([], [])
        if isinstance(stmt, ast.RenameUserStmt):
            self._require_super()
            from .privileges import PrivilegeError
            try:
                self.storage.privileges.rename_users(stmt.pairs)
            except PrivilegeError as e:
                raise err_wrap(SQLError, e) from None
            return ResultSet([], [])
        if isinstance(stmt, ast.CreateUserStmt):
            self._require_super()
            from .privileges import PrivilegeError
            try:
                self.storage.privileges.create_user(
                    stmt.name, stmt.password, stmt.if_not_exists)
            except PrivilegeError as e:
                raise err_wrap(SQLError, e) from None
            return ResultSet([], [])
        if isinstance(stmt, ast.DropUserStmt):
            self._require_super()
            from .privileges import PrivilegeError
            try:
                self.storage.privileges.drop_user(stmt.name, stmt.if_exists)
            except PrivilegeError as e:
                raise err_wrap(SQLError, e) from None
            return ResultSet([], [])
        if isinstance(stmt, ast.GrantStmt):
            self._require_super()
            from .privileges import PrivilegeError
            db = stmt.db if stmt.db else self.current_db
            try:
                if stmt.revoke:
                    self.storage.privileges.revoke(
                        stmt.privs, db, stmt.table, stmt.user,
                        stmt.priv_cols or None)
                else:
                    self.storage.privileges.grant(
                        stmt.privs, db, stmt.table, stmt.user,
                        stmt.priv_cols or None)
            except PrivilegeError as e:
                raise err_wrap(SQLError, e) from None
            return ResultSet([], [])
        if isinstance(stmt, (ast.SelectStmt, ast.SetOpStmt)):
            rs = self._run_in_txn(lambda: self._exec_select(stmt))
            outfile = getattr(stmt, "into_outfile", None)
            if outfile is not None:
                return self._write_outfile(rs, outfile)
            return rs
        if isinstance(stmt, (ast.InsertStmt, ast.UpdateStmt,
                             ast.DeleteStmt)):
            stmt = self._maybe_bind_vars(stmt)
        if isinstance(stmt, ast.InsertStmt):
            return self._run_in_txn(lambda: self._exec_insert(stmt))
        if isinstance(stmt, ast.LoadDataStmt):
            return self._run_in_txn(lambda: self._exec_load_data(stmt))
        if isinstance(stmt, ast.UpdateStmt):
            return self._run_in_txn(lambda: self._exec_update(stmt))
        if isinstance(stmt, ast.DeleteStmt):
            return self._run_in_txn(lambda: self._exec_delete(stmt))
        if isinstance(stmt, ast.CreateTableStmt):
            with self.storage.ddl_section():
                return self._exec_create_table(stmt)
        if isinstance(stmt, ast.DropTableStmt):
            with self.storage.ddl_section():
                return self._exec_drop_table(stmt)
        if isinstance(stmt, ast.CreateDatabaseStmt):
            with self.storage.ddl_section():
                self.catalog.create_schema(stmt.name, stmt.if_not_exists)
                return ResultSet([], [], affected=0)
        if isinstance(stmt, ast.DropDatabaseStmt):
            with self.storage.ddl_section():
                for info in self.catalog.drop_schema(stmt.name,
                                                     stmt.if_exists):
                    self.storage.unregister_table(info.id)
                    self.storage.destroy_table_data(info.id)
                return ResultSet([], [])
        if isinstance(stmt, ast.TruncateTableStmt):
            with self.storage.ddl_section():
                return self._exec_truncate(stmt)
        if isinstance(stmt, ast.CreateSequenceStmt):
            with self.storage.ddl_section():
                return self._exec_create_sequence(stmt)
        if isinstance(stmt, ast.DropSequenceStmt):
            with self.storage.ddl_section():
                return self._exec_drop_sequence(stmt)
        if isinstance(stmt, ast.UseStmt):
            from ..catalog import infoschema as I
            from ..catalog import metrics_schema as MS
            if stmt.db.lower() == I.DB_NAME:
                I.ensure_schema(self.storage)
            elif stmt.db.lower() == MS.DB_NAME:
                MS.ensure_schema(self.storage)
            self.catalog.schema(stmt.db)  # raises if unknown
            self.current_db = stmt.db
            return ResultSet([], [])
        if isinstance(stmt, ast.BeginStmt):
            self._commit_implicit()
            mode = stmt.mode or str(
                self._sysvar_value("tidb_txn_mode") or "")
            self.txn = self.storage.begin(
                pessimistic=mode.upper() == "PESSIMISTIC")
            self.in_explicit_txn = True
            return ResultSet([], [])
        if isinstance(stmt, ast.CommitStmt):
            self._finish_txn(commit=True)
            return ResultSet([], [])
        if isinstance(stmt, ast.RollbackStmt):
            self._finish_txn(commit=False)
            return ResultSet([], [])
        if isinstance(stmt, ast.ExplainStmt):
            return self._exec_explain(stmt)
        if isinstance(stmt, ast.TraceStmt):
            return self._exec_trace(stmt)
        if isinstance(stmt, ast.ShowStmt):
            return self._exec_show(stmt)
        if isinstance(stmt, ast.SetStmt):
            return self._exec_set(stmt)
        if isinstance(stmt, ast.AnalyzeTableStmt):
            return self._exec_analyze(stmt)
        if isinstance(stmt, ast.AlterTableStmt):
            return self._exec_alter(stmt)
        if isinstance(stmt, ast.CreateIndexStmt):
            return self._exec_ddl_job("add_index", stmt.table, {
                "name": stmt.name, "columns": stmt.columns,
                "unique": stmt.unique})
        if isinstance(stmt, ast.DropIndexStmt):
            return self._exec_ddl_job("drop_index", stmt.table,
                                      {"name": stmt.name})
        if isinstance(stmt, ast.RenameTableStmt):
            for old, new in stmt.renames:
                self._exec_ddl_job("rename_table", old, {
                    "new_name": new.name,
                    "new_db": new.db or old.db or self.current_db})
            return ResultSet([], [])
        if isinstance(stmt, ast.CreateBindingStmt):
            return self._exec_create_binding(stmt)
        if isinstance(stmt, ast.DropBindingStmt):
            return self._exec_drop_binding(stmt)
        if isinstance(stmt, (ast.CreateRoleStmt, ast.DropRoleStmt,
                             ast.GrantRoleStmt, ast.SetRoleStmt,
                             ast.SetDefaultRoleStmt)):
            return self._exec_role_stmt(stmt)
        if isinstance(stmt, ast.ChecksumTableStmt):
            return self._run_in_txn(lambda: self._exec_checksum(stmt))
        if isinstance(stmt, ast.AdminStmt):
            if stmt.kind == "SHOW_DDL_JOBS":
                jobs = (list(self.storage.ddl_jobs)
                        + list(reversed(self.storage.ddl_history)))
                return ResultSet(
                    ["JOB_ID", "DB_NAME", "TABLE_NAME", "JOB_TYPE",
                     "SCHEMA_STATE", "STATE", "ERROR"],
                    [j.row() for j in jobs[:32]])
            if stmt.kind == "CHECK_TABLE":
                return self._run_in_txn(
                    lambda: self._exec_admin_check(stmt))
            raise SQLError(f"unsupported ADMIN {stmt.kind}")
        raise SQLError(f"unsupported statement {type(stmt).__name__}")

    # ==================== system / user variables ====================
    def _exec_set(self, stmt: ast.SetStmt) -> ResultSet:
        """SET handling over the sysvar registry (reference:
        executor/set.go; registry in sessionctx/variable/sysvar.go)."""
        from .sysvars import SCOPE_GLOBAL, SCOPE_SESSION, SYSVARS

        for scope, name, expr in stmt.items:
            value = self._set_value(expr)
            if scope == "USERVAR":
                self.user_vars[name] = value
                continue
            if scope == "NAMES":
                for v in ("character_set_client", "character_set_connection",
                          "character_set_results"):
                    self.vars[v] = value
                continue
            sv = SYSVARS.get(name)
            if sv is None:
                # tolerate unknown tidb_/engine-prefixed knobs (forward
                # compat); reject arbitrary unknowns like MySQL does.
                # GLOBAL keeps its semantics: SUPER-gated + stored globally
                if name.startswith(("tidb_", "innodb_", "sql_")):
                    if scope == "GLOBAL":
                        self._require_super()
                        self.storage.sysvars.set_global(name, value)
                    else:
                        self.vars[name] = value
                    continue
                raise SQLError(f"Unknown system variable '{name}'",
                           errno=ER_UNKNOWN_SYSTEM_VARIABLE)
            if sv.read_only:
                raise SQLError(
                    f"Variable '{name}' is a read only variable",
                    errno=ER_VAR_READONLY)
            if isinstance(expr, ast.Literal) and expr.tag == "default":
                value = sv.default
            if scope == "GLOBAL":
                if not sv.scope & SCOPE_GLOBAL:
                    raise SQLError(
                        f"Variable '{name}' is a SESSION variable and "
                        "can't be used with SET GLOBAL")
                # cluster-wide durable state: superuser only (reference:
                # SUPER/SYSTEM_VARIABLES_ADMIN requirement)
                self._require_super()
                self.storage.sysvars.set_global(name, value)
            else:
                if not sv.scope & SCOPE_SESSION:
                    raise SQLError(
                        f"Variable '{name}' is a GLOBAL variable and "
                        "should be set with SET GLOBAL")
                self.vars[name] = value
        return ResultSet([], [])

    def _set_value(self, expr: ast.Expr) -> Any:
        if isinstance(expr, ast.Literal):
            if expr.tag == "decimal":
                return Decimal(expr.value.unscaled, expr.value.scale) \
                    if hasattr(expr.value, "unscaled") else expr.value
            return expr.value
        if isinstance(expr, ast.ColumnRef):
            return expr.name  # bare ident value (utf8mb4, ON, ...)
        if isinstance(expr, ast.SysVarExpr):
            return self._sysvar_value(expr.name, expr.scope)
        if isinstance(expr, ast.UserVarExpr):
            return self.user_vars.get(expr.name)
        if isinstance(expr, ast.UnaryOp) and isinstance(
                expr.operand, ast.Literal):
            v = expr.operand.value
            return -v if expr.op == "-" else v
        raise SQLError("unsupported SET value expression")

    def _sysvar_value(self, name: str, scope: str = "SESSION") -> Any:
        from .sysvars import SYSVARS

        if name == "warning_count" and scope != "GLOBAL":
            # computed per statement (MySQL: clients gate their SHOW
            # WARNINGS fetch on it), like error_count/found_rows
            return len(self.warnings)
        if scope != "GLOBAL" and name in self.vars:
            return self.vars[name]
        v = self.storage.sysvars.get_global(name)
        if v is None and name not in SYSVARS:
            raise SQLError(f"Unknown system variable '{name}'",
                           errno=ER_UNKNOWN_SYSTEM_VARIABLE)
        return v

    def _bind_vars(self, node):
        """Substitute @@sysvar / @user_var reads with typed literals before
        planning (the planner sees plain constants)."""

        def lit(v):
            if v is None:
                return ast.Literal(None, "null")
            if isinstance(v, bool):
                return ast.Literal(int(v), "int")
            if isinstance(v, int):
                return ast.Literal(v, "int")
            if isinstance(v, float):
                return ast.Literal(v, "float")
            return ast.Literal(str(v), "string")

        def fn(n):
            if isinstance(n, ast.SysVarExpr):
                return lit(self._sysvar_value(n.name, n.scope))
            if isinstance(n, ast.UserVarExpr):
                return lit(self.user_vars.get(n.name))
            if isinstance(n, ast.FuncCall) and n.name in _SESSION_FUNCS:
                return lit(self._session_func_value(n))
            if isinstance(n, ast.ColumnRef) and n.table is None and \
                    n.name.upper() in _NILADIC_FUNCS:
                # bare CURRENT_DATE etc. — reserved niladic functions
                return lit(self._session_func_value(
                    ast.FuncCall(n.name.upper(), [])))
            return n

        return ast.transform(node, fn)

    def _session_func_value(self, n: ast.FuncCall) -> Any:
        """Session-dependent function -> value at statement-bind time
        (reference: these evaluate against the session context,
        expression/builtin_info.go + builtin_time.go nondeterministic
        set; binding keeps them out of the plan cache)."""
        import time as _time

        name = n.name
        if name in ("NOW", "CURRENT_TIMESTAMP", "SYSDATE",
                    "LOCALTIME", "LOCALTIMESTAMP"):
            return _time.strftime("%Y-%m-%d %H:%M:%S")
        if name in ("CURDATE", "CURRENT_DATE"):
            return _time.strftime("%Y-%m-%d")
        if name in ("CURTIME", "CURRENT_TIME"):
            return _time.strftime("%H:%M:%S")
        if name == "UNIX_TIMESTAMP" and not n.args:
            return int(_time.time())
        if name == "VERSION":
            return str(self._sysvar_value("version"))
        if name in ("DATABASE", "SCHEMA"):
            return self.current_db
        if name in ("USER", "CURRENT_USER", "SESSION_USER"):
            return f"{self.user or 'root'}@%"
        if name == "CONNECTION_ID":
            return self.conn_id or 0
        if name == "NEXTVAL":
            if len(n.args) != 1:
                raise SQLError("NEXTVAL takes a sequence name")
            seq = self._sequence_for(n.args[0])
            try:
                v = self.storage.sequence_next(seq)
            except ValueError as e:
                raise err_wrap(SQLError, e) from None
            self._seq_lastval = v
            return v
        if name == "LASTVAL":
            return getattr(self, "_seq_lastval", None)
        if name == "SETVAL":
            if len(n.args) != 2 or not isinstance(n.args[1], ast.Literal):
                raise SQLError("SETVAL takes (sequence, constant)")
            seq = self._sequence_for(n.args[0])
            v = int(n.args[1].value)
            self.storage.sequence_set(seq, v)
            return v
        if name == "SYSTEM_USER":
            return f"{self.user or 'root'}@%"
        if name == "LAST_INSERT_ID":
            return int(self.vars.get("last_insert_id", 0) or 0)
        if name == "FOUND_ROWS":
            return int(getattr(self, "_found_rows", 0))
        if name == "ROW_COUNT":
            return int(getattr(self, "_row_count", -1))
        if name == "CURRENT_ROLE":
            return ", ".join(f"`{r}`@`%`"
                             for r in sorted(self.active_roles)) or "NONE"
        if name == "TIDB_IS_DDL_OWNER":
            owner = getattr(self.storage, "ddl_owner", None)
            if owner is None:
                return 1
            return int(bool(getattr(owner, "is_owner", lambda: True)()))
        if name in ("GET_LOCK", "RELEASE_LOCK", "IS_FREE_LOCK",
                    "IS_USED_LOCK", "RELEASE_ALL_LOCKS"):
            return self._user_lock_func(n)
        raise SQLError(f"unsupported function {name}")

    def _user_lock_func(self, n: ast.FuncCall) -> Any:
        """User-level named locks (reference: builtin_miscellaneous.go
        GET_LOCK family; lock table lives on the Storage so siblings in
        one process contend correctly)."""
        me = self.conn_id or id(self)
        if n.name == "RELEASE_ALL_LOCKS":
            return self.storage.user_locks.release_all(me)
        if not n.args:
            raise SQLError(f"{n.name} takes a lock name")
        name = str(self._eval_value(n.args[0]))
        if n.name == "GET_LOCK":
            timeout = 0.0
            if len(n.args) > 1:
                # constant expression (covers unary minus: -1 = forever)
                timeout = float(self._eval_value(n.args[1]))
            return int(self.storage.user_locks.acquire(name, me, timeout))
        if n.name == "RELEASE_LOCK":
            return self.storage.user_locks.release(name, me)
        if n.name == "IS_FREE_LOCK":
            return int(self.storage.user_locks.holder(name) is None)
        holder = self.storage.user_locks.holder(name)
        return holder  # IS_USED_LOCK: holder conn id or NULL

    @staticmethod
    def _has_var_reads(node) -> bool:
        found = False

        def visit(n):
            nonlocal found
            if isinstance(n, (ast.SysVarExpr, ast.UserVarExpr)):
                found = True
                return False
            if isinstance(n, ast.FuncCall) and \
                    n.name in _SESSION_FUNCS:
                # session-dependent/nondeterministic functions bind to
                # literals before planning (and keep the statement out
                # of the plan cache)
                found = True
                return False
            if isinstance(n, ast.ColumnRef) and n.table is None and \
                    n.name.upper() in _NILADIC_FUNCS:
                found = True
                return False
            return None

        ast.walk(node, visit)
        return found

    def _maybe_bind_vars(self, stmt, has_vars: Optional[bool] = None):
        """@var / @@var reads bind in every expression-bearing statement
        (SELECT and DML alike — the SET-then-DML pattern is standard).
        `has_vars` skips re-walking the AST when the caller already
        checked."""
        if has_vars is None:
            has_vars = self._has_var_reads(stmt)
        if has_vars:
            self._guard_per_row_sequences(stmt)
            import copy as _copy
            return self._bind_vars(_copy.deepcopy(stmt))
        return stmt

    def _guard_per_row_sequences(self, stmt) -> None:
        """NEXTVAL binds once per statement, so any per-row context
        would hand every row the same value — reject loudly instead of
        silently duplicating ids (reference evaluates sequences per row
        through expression/builtin_other.go; VALUES lists are fine here
        because each row's FuncCall node binds separately)."""
        def contains_seq(node) -> bool:
            hit = False

            def v(n):
                nonlocal hit
                if isinstance(n, ast.FuncCall) and \
                        n.name in ("NEXTVAL", "SETVAL"):
                    hit = True
                    return False
                return None

            ast.walk(node, v)
            return hit

        def visit(n):
            if isinstance(n, ast.SelectStmt) and n.from_ is not None \
                    and contains_seq(n):
                raise SQLError(
                    "NEXTVAL/SETVAL in per-row contexts (SELECT with "
                    "FROM, INSERT ... SELECT) is unsupported")
            if isinstance(n, ast.UpdateStmt) and (
                    any(contains_seq(a.value) for a in n.assignments)
                    or (n.where is not None and contains_seq(n.where))):
                raise SQLError(
                    "NEXTVAL/SETVAL in UPDATE statements is "
                    "unsupported")
            if isinstance(n, ast.DeleteStmt) and n.where is not None \
                    and contains_seq(n.where):
                raise SQLError(
                    "NEXTVAL/SETVAL in DELETE is unsupported")
            if isinstance(n, ast.InsertStmt) and any(
                    contains_seq(a.value)
                    for a in getattr(n, "on_dup", []) or []):
                raise SQLError(
                    "NEXTVAL/SETVAL in ON DUPLICATE KEY UPDATE is "
                    "unsupported")
            return None

        ast.walk(stmt, visit)

    # ==================== privileges ====================
    def _require_super(self) -> None:
        if self.user is not None and not self.storage.privileges.check(
                self.user, "ALL", "*", "*", roles=self.active_roles):
            raise SQLError(
                f"Access denied; you need SUPER privilege(s) "
                f"for this operation (user '{self.user}')",
                errno=ER_SPECIFIC_ACCESS_DENIED)

    @staticmethod
    def _collect_table_names(stmt) -> list[ast.TableName]:
        out: list[ast.TableName] = []

        def visit(n):
            if isinstance(n, ast.TableName):
                out.append(n)
                return False
            return None

        ast.walk(stmt, visit)
        return out

    _STMT_PRIV = {
        ast.InsertStmt: "INSERT", ast.UpdateStmt: "UPDATE",
        ast.DeleteStmt: "DELETE", ast.CreateTableStmt: "CREATE",
        ast.DropTableStmt: "DROP", ast.TruncateTableStmt: "DROP",
        ast.AlterTableStmt: "ALTER", ast.CreateIndexStmt: "INDEX",
        ast.DropIndexStmt: "INDEX", ast.RenameTableStmt: "ALTER",
        ast.CreateDatabaseStmt: "CREATE", ast.DropDatabaseStmt: "DROP",
        ast.CreateViewStmt: "CREATE", ast.DropViewStmt: "DROP",
        ast.LoadDataStmt: "INSERT",
    }

    def _check_column_privs(self, plan) -> None:
        """Column-scope SELECT enforcement (mysql.columns_priv analog):
        the physical plan's scan leaves carry the PRUNED column sets,
        i.e. exactly what the query touches per table (reference:
        privilege columns checked at resolution, planner visitInfo +
        privileges/cache.go columnsPriv)."""
        if self.user is None:
            return
        pm = self.storage.privileges
        if not pm.has_col_grants(self.user, self.active_roles):
            return  # hot path: no column-scoped grants anywhere
        from ..plan.fragment import PhysFragmentRead
        from ..plan.physical import (PhysIndexMerge, PhysPointGet,
                                     PhysTableRead)

        def leaf_tables(p):
            if isinstance(p, PhysTableRead) and p.table is not None:
                yield p.table, p.dag.scan.col_offsets
            elif isinstance(p, (PhysPointGet, PhysIndexMerge)):
                yield p.table, p.col_offsets
            elif isinstance(p, PhysFragmentRead):
                for t in p.frag.tables:
                    yield t.table, t.col_offsets
            for c in getattr(p, "children", ()) or ():
                yield from leaf_tables(c)

        def db_of(info) -> str:
            for s in self.catalog.schemas.values():
                t = s.tables.get(info.name.lower())
                if t is not None and t.id == info.id:
                    return s.name
            return self.current_db

        for info, offsets in leaf_tables(plan):
            names = [info.columns[o].name for o in offsets
                     if o < len(info.columns)]
            denied = pm.check_columns(self.user, "SELECT", db_of(info),
                                      info.name, names,
                                      roles=self.active_roles)
            if denied is not None:
                raise SQLError(
                    f"SELECT command denied to user '{self.user}' for "
                    f"column '{denied}' in table '{info.name}'",
                    errno=ER_TABLEACCESS_DENIED)

    def _check_dml_columns(self, tn: ast.TableName, info, priv: str,
                           names: list[str]) -> None:
        if self.user is None:
            return
        db = tn.db or self.current_db
        denied = self.storage.privileges.check_columns(
            self.user, priv, db, info.name, names,
            roles=self.active_roles)
        if denied is not None:
            raise SQLError(
                f"{priv} command denied to user '{self.user}' for "
                f"column '{denied}' in table '{info.name}'",
                errno=ER_TABLEACCESS_DENIED)

    def _check_privileges(self, stmt: ast.Stmt) -> None:
        """Statement-level grant checks before planning (reference:
        visitInfo checks at planner/optimize.go:246)."""
        pm = self.storage.privileges

        def deny(priv: str, obj: str):
            raise SQLError(
                f"{priv} command denied to user '{self.user}' "
                f"for table '{obj}'", errno=ER_TABLEACCESS_DENIED)

        if isinstance(stmt, ast.TraceStmt):
            # TRACE runs the target for real: same checks as running it
            self._check_privileges(stmt.target)
            return
        if isinstance(stmt, (ast.SelectStmt, ast.SetOpStmt,
                             ast.ExplainStmt, ast.AnalyzeTableStmt,
                             ast.ChecksumTableStmt)):
            # CHECKSUM fingerprints content: same SELECT requirement
            for tn in self._collect_table_names(stmt):
                db = tn.db or self.current_db
                if not pm.check(self.user, "SELECT", db, tn.name,
                                roles=self.active_roles):
                    deny("SELECT", f"{db}.{tn.name}")
            return
        priv = self._STMT_PRIV.get(type(stmt))
        if priv is None:
            return  # txn control, SET, SHOW, USE, admin: unchecked
        if isinstance(stmt, (ast.CreateDatabaseStmt, ast.DropDatabaseStmt)):
            if not pm.check(self.user, priv, stmt.name, "*",
                            roles=self.active_roles):
                deny(priv, stmt.name)
            return
        # the DML privilege applies to the statement's TARGET table;
        # every other referenced table (subqueries, INSERT..SELECT
        # sources) needs SELECT
        target = getattr(stmt, "table", None)
        for tn in self._collect_table_names(stmt):
            db = tn.db or self.current_db
            need = priv if (tn is target or target is None) else "SELECT"
            if not pm.check(self.user, need, db, tn.name,
                            roles=self.active_roles):
                deny(need, f"{db}.{tn.name}")

    # ==================== information_schema ====================
    _VIEWER_SENSITIVE_IS = frozenset({"processlist", "user_privileges",
                                      "profiling", "cluster_processlist"})

    def _refresh_infoschema(self, stmt) -> None:
        """Rebuild any information_schema tables this statement touches
        from the live catalog (reference: infoschema memtables are served
        from the InfoSchema snapshot, executor/infoschema_reader.go).

        Viewer-sensitive tables (PROCESSLIST visibility, USER_PRIVILEGES
        scope) materialize per-viewer content into the SHARED store, so
        refresh+scan must be exclusive: another session's refresh
        between ours and our scan would serve us its view (or ours to
        it). The statement holds storage.infoschema_lock until it
        finishes (_execute_observed releases)."""
        from ..catalog import infoschema as I
        from ..catalog import metrics_schema as MS

        names: set[str] = set()
        ms_names: set[str] = set()
        for tn in self._collect_table_names(stmt):
            db = (tn.db or self.current_db).lower()
            if db == I.DB_NAME:
                names.add(tn.name.lower())
            elif db == MS.DB_NAME:
                ms_names.add(tn.name.lower())
        if ms_names:
            # the metric-family memtables (one per registered family;
            # not viewer-sensitive, so no infoschema lock needed)
            MS.refresh(self.storage, ms_names)
        if not names:
            return
        if names & self._VIEWER_SENSITIVE_IS and self._is_guard is None:
            # bounded: a statement stuck on row locks while holding this
            # would otherwise stall every sibling's PROCESSLIST read for
            # its whole duration
            lock = self.storage.infoschema_lock
            if not lock.acquire(timeout=10.0):
                raise SQLError(
                    "information_schema busy; try again",
                    errno=ER_TIKV_SERVER_BUSY)
            self._is_guard = lock
        I.refresh(self.storage, names, viewer=self)

    # ==================== online DDL ====================
    def _ddl(self):
        from ..ddl import DDL

        return DDL(self.storage, self.catalog)

    def _exec_create_view(self, stmt: ast.CreateViewStmt) -> ResultSet:
        from ..catalog.schema import ViewInfo
        db = stmt.db or self.current_db
        schema = self.catalog.schema(db)
        key = stmt.name.lower()
        if not hasattr(schema, "views"):
            schema.views = {}
        if key in schema.tables:
            raise SQLError(f"Table '{stmt.name}' already exists")
        if key in schema.views and not stmt.or_replace:
            raise SQLError(f"Table '{stmt.name}' already exists")
        # validate the stored SELECT against the current catalog
        self._plan_view_select(db, stmt.select_sql, stmt.columns)
        schema.views[key] = ViewInfo(
            stmt.name, stmt.select_sql, tuple(stmt.columns),
            definer=f"{self.user or 'root'}@%")
        self.catalog.bump_version()
        return ResultSet([], [])

    def _exec_drop_view(self, stmt: ast.DropViewStmt) -> ResultSet:
        db = stmt.db or self.current_db
        schema = self.catalog.schema(db)
        views = getattr(schema, "views", {})
        if stmt.name.lower() not in views:
            if stmt.if_exists:
                return ResultSet([], [])
            raise SQLError(f"Unknown view '{stmt.name}'")
        del views[stmt.name.lower()]
        self.catalog.bump_version()
        return ResultSet([], [])

    def _exec_ddl_job(self, kind: str, tn: ast.TableName,
                      args: dict) -> ResultSet:
        from ..ddl import DDLError

        self._commit_implicit()  # DDL implicitly commits (MySQL semantics)
        # no ddl_section here: run_job takes the owner lock itself and
        # folds sibling schema changes inside it
        info, _ = self._table_for(tn)
        ddl = self._ddl()
        job = ddl.submit(kind, tn.db or self.current_db, info, args)
        try:
            ddl.run_job(job)
        except DDLError as e:
            raise err_wrap(SQLError, e) from None
        return ResultSet([], [])

    def _exec_alter(self, stmt: ast.AlterTableStmt) -> ResultSet:
        for spec in stmt.specs:
            if spec.op in ("drop_partition", "truncate_partition"):
                self._exec_alter_partition(stmt.table, spec)
                continue
            info = self.catalog.try_table(
                stmt.table.db or self.current_db, stmt.table.name)
            if info is not None and getattr(info, "partition",
                                            None) is not None:
                raise SQLError(
                    f"ALTER {spec.op} on partitioned tables is "
                    "unsupported")
            if spec.op == "add_index":
                idef = spec.index
                if idef.primary:
                    raise SQLError("ADD PRIMARY KEY after create is "
                                   "unsupported")
                name = idef.name or f"idx_{'_'.join(idef.columns)}"
                self._exec_ddl_job("add_index", stmt.table, {
                    "name": name, "columns": idef.columns,
                    "unique": idef.unique})
            elif spec.op == "drop_index":
                self._exec_ddl_job("drop_index", stmt.table,
                                   {"name": spec.name})
            elif spec.op == "add_column":
                cd = spec.column
                ft = _coldef_ftype(cd)
                default = None
                if cd.default is not None:
                    c = _literal_const(cd.default)
                    default = self._decode_default(c, ft)
                self._exec_ddl_job("add_column", stmt.table, {
                    "name": cd.name, "ftype": ft, "default": default,
                    "phys_default": self._phys_value(default, ft)})
            elif spec.op == "drop_column":
                self._exec_ddl_job("drop_column", stmt.table,
                                   {"name": spec.name})
            elif spec.op == "modify_column":
                cd = spec.column
                self._exec_ddl_job("modify_column", stmt.table,
                                   {"name": cd.name,
                                    "ftype": _coldef_ftype(cd)})
            elif spec.op == "rename":
                self._exec_ddl_job("rename_table", stmt.table, {
                    "new_name": spec.name,
                    "new_db": stmt.table.db or self.current_db})
                stmt = ast.AlterTableStmt(
                    ast.TableName(spec.name, stmt.table.db), [])
            else:
                raise SQLError(f"unsupported ALTER action {spec.op}")
        return ResultSet([], [])

    def _exec_alter_partition(self, tn: ast.TableName,
                              spec: ast.AlterSpec) -> None:
        """DROP/TRUNCATE PARTITION (reference: ddl/partition.go
        onDropTablePartition + truncate — partition data reclaim via
        delete-range, here unsafe_destroy_range on the child id)."""
        info, _ = self._table_for(tn)
        part = getattr(info, "partition", None)
        if part is None:
            raise SQLError(f"table {info.name} is not partitioned")
        d = part.by_name(spec.name)
        if d is None:
            raise SQLError(f"unknown partition {spec.name}")
        self._commit_implicit()
        # the first partition's store is the table's shared handle
        # allocator (_table_for): its counter must survive this DDL or
        # re-issued handles would overwrite live rows elsewhere
        alloc = self.storage.table_store(part.defs[0].id)._next_handle
        if spec.op == "drop_partition":
            if part.kind != "range":
                raise SQLError(
                    "DROP PARTITION is only supported for RANGE "
                    "partitioning (use a smaller PARTITIONS count "
                    "for HASH)")
            if len(part.defs) == 1:
                raise SQLError("cannot drop the last partition")
            part.defs.remove(d)
            self.storage.unregister_table(d.id)
            self.storage.stats.drop_table(d.id)
            self.storage.destroy_table_data(d.id)
            new_first = self.storage.table_store(part.defs[0].id)
            new_first._next_handle = max(new_first._next_handle, alloc)
            self.catalog.bump_version()
        else:  # truncate_partition: fresh store, same identity
            self.storage.destroy_table_data(d.id)
            self.storage.stats.drop_table(d.id)
            store = TableStore(Storage.child_table_info(info, d))
            # keep the shared dictionaries (other partitions still
            # reference their codes)
            other = next((p for p in part.defs if p.id != d.id), None)
            if other is not None:
                store.dictionaries = \
                    self.storage.table_store(other.id).dictionaries
            self.storage.tables[d.id] = store
            self.storage.adopt_table_store(store)
            if d.id == part.defs[0].id:
                store._next_handle = alloc
            self.catalog.bump_version()

    def _phys_value(self, v, ft: FieldType):
        """Host default -> physical encoding (scaled decimal, day number)."""
        if v is None:
            return None
        from ..chunk.column import _encode_scalar

        d = None
        if ft.is_string:
            return str(v)
        return _encode_scalar(ft, v, d)

    def _exec_analyze(self, stmt: ast.AnalyzeTableStmt) -> ResultSet:
        """ANALYZE TABLE: build histograms/sketches from a fresh snapshot
        (reference: executor/analyze.go over pushdown collectors)."""
        self._commit_implicit()
        for tn in stmt.tables:
            info, _ = self._table_for(tn)
            for child, store in self._partition_children(info):
                self.storage.stats.analyze_one(child, store, self.storage,
                                               cop=self.cop)
        return ResultSet([], [])

    # ==================== txn plumbing ====================
    def _ensure_txn(self) -> Transaction:
        if self.txn is None:
            self.txn = self.storage.begin()
        return self.txn

    def _run_in_txn(self, fn):
        """One statement in the session txn; autocommit statements that
        lose an optimistic write conflict re-execute at a fresh start_ts
        up to tidb_retry_limit times (reference: session.go:690
        retryable auto-commit retry — explicit txns never auto-retry)."""
        retries = 0
        if not self.in_explicit_txn and self.txn is None:
            try:
                retries = int(self._sysvar_value("tidb_retry_limit") or 0)
            except (TypeError, ValueError):
                retries = 0
        for attempt in range(retries + 1):
            txn = self._ensure_txn()
            stage = txn.memdb.staging()
            guards_before = set(txn.guard_keys)
            try:
                result = fn()
            except Exception:
                txn.memdb.cleanup(stage)
                # unwind unique-guard claims with the staged rows: a
                # failed statement must not leave LOCK markers on values
                # it never wrote
                txn.guard_keys = guards_before
                if not self.in_explicit_txn:
                    self._finish_txn(commit=False)
                raise
            txn.memdb.release(stage)
            if self.in_explicit_txn:
                return result
            try:
                self._finish_txn(commit=True)
            except SQLError as e:
                if attempt < retries and "write conflict" in str(e):
                    continue  # fresh ts, statement re-executes
                raise
            return result

    def _plan_view_select(self, db: str, sql: str, columns) -> None:
        """Validate a view definition by building its plan now (the
        reference re-parses/validates at CreateView, ddl/ddl_api.go)."""
        from ..plan.builder import PlanBuilder, PlanError
        from ..sql.parser import parse_sql as _parse
        try:
            stmts = _parse(sql)
            if len(stmts) != 1 or not isinstance(
                    stmts[0], (ast.SelectStmt, ast.SetOpStmt)):
                raise SQLError("view definition must be one SELECT")
            plan = PlanBuilder(self.catalog, db).build_select(stmts[0])
        except PlanError as e:
            raise err_wrap(SQLError, e) from None
        if columns and len(columns) != len(plan.schema.fields):
            raise SQLError("view column list length mismatch")

    def _exec_kill(self, stmt) -> None:
        """Route KILL to the owning server: local registry when the id
        belongs to this node, the shared-dir kill mailbox otherwise
        (reference: server/server.go:548 Kill; tests/globalkilltest
        cross-server kill with server-id-carrying conn ids)."""
        storage = self.storage
        # ownership check (reference: server.go Kill — SuperPriv OR the
        # target belongs to the same user; MySQL types the refusal as
        # ER_KILL_DENIED 1095, not a generic privilege error)
        if self.user is not None and stmt.conn_id != self.conn_id \
                and not storage.privileges.check(
                    self.user, "ALL", "*", "*", roles=self.active_roles):
            owner_of = getattr(storage, "conn_owner", None)
            owner = owner_of(stmt.conn_id) if owner_of is not None \
                else None
            if owner != self.user:
                raise SQLError(
                    f"You are not owner of thread {stmt.conn_id}",
                    errno=ER_KILL_DENIED)
        coord = getattr(storage, "coord", None)
        if coord is not None:
            nid, _local = coord.split_conn_id(stmt.conn_id)
            if nid != coord.node_id:
                coord.post_kill(stmt.conn_id, stmt.query_only)
                return
        router = getattr(storage, "kill_router", None)
        if router is None or not router(stmt.conn_id, stmt.query_only):
            raise SQLError(f"Unknown thread id: {stmt.conn_id}")

    def rollback_if_active(self) -> None:
        """Abandon any open transaction (connection teardown path —
        reference: server/conn.go Close rolls back the session txn).
        Also releases the session's GET_LOCK user locks (MySQL frees
        them on connection exit)."""
        if self.txn is not None:
            self._finish_txn(commit=False)
        self.storage.user_locks.release_all(self.conn_id or id(self))

    def _commit_implicit(self) -> None:
        if self.txn is not None and not self.in_explicit_txn:
            self._finish_txn(commit=True)

    def _finish_txn(self, commit: bool) -> None:
        if self.txn is None:
            self.in_explicit_txn = False
            return
        txn, self.txn = self.txn, None
        self.in_explicit_txn = False
        if commit:
            try:
                txn.commit()
            except WriteConflictError as e:
                raise err_wrap(SQLError, e) from None
            except TxnTooLargeError as e:
                # performance.txn-total-size-limit crossed: surface as
                # the session-layer SQLError (errno 8004) like the
                # wire layer would, keeping embedded callers' contract
                raise err_wrap(SQLError, e) from None
        else:
            txn.rollback()

    def _exec_ctx(self, stats=None) -> ExecContext:
        """ExecContext with the session's memory quota attached
        (reference: sessionVars.MemQuotaQuery feeding the per-query
        tracker, executor/adapter.go + util/memory/tracker.go:42).
        The root tracker also registers with the server-wide memory
        governor for the statement's lifetime, so a server crossing
        server-memory-limit can pick (and kill) the heaviest
        statement; ExecContext.close() unregisters."""
        from ..util.memory import MemTracker

        quota = int(self._sysvar_value("tidb_mem_quota_query") or 0)
        action = str(self._sysvar_value("tidb_mem_oom_action") or "SPILL")
        mem = MemTracker("query", quota, action=action.upper())
        ctx = ExecContext(self._ensure_txn(), self.cop, stats=stats,
                          mem=mem)
        gov = getattr(self.storage, "governor", None)
        if gov is not None:
            # install the tracker BEFORE registering: register() runs a
            # synchronous pressure check, and a kill issued by it calls
            # back into _governor_kill, whose tracker-identity guard
            # would no-op against a not-yet-installed _live_mem — a
            # statement admitted into an already-over-limit server must
            # be killable at that admission-time check
            with self._gov_lock:
                self._live_mem = mem
            token = gov.register(
                mem, kill=lambda: self._governor_kill(mem),
                label=(self.in_flight_sql or "")[:256],
                conn_id=self.conn_id or 0)

            def _release() -> None:
                gov.unregister(token)
                with self._gov_lock:
                    if self._live_mem is mem:
                        self._live_mem = None

            ctx.on_close = _release
        return ctx

    def _governor_kill(self, mem) -> None:
        """Kill callback the memory governor invokes (from the thread
        that tripped the limit): flip the latch that types the error as
        8175 and set the statement's interrupt flag — the engine polls
        it between plan nodes / device tiles, exactly like KILL QUERY.
        Guarded by tracker identity UNDER the session's governor lock
        (install/uninstall hold the same lock): the governor picks its
        victim outside this session's statement lifecycle, so a
        callback that arrives after the picked statement finished (and
        a new one installed a fresh tracker) must be a no-op, not a
        spurious 8175 against whatever runs next. A flag set while the
        victim is in its final (checkpoint-free) stretch is cleared by
        the next statement's preamble before it can misfire."""
        with self._gov_lock:
            if self._live_mem is not mem:
                return  # the picked statement already completed
            self._governor_killed = True
            self.killed.set()

    @contextmanager
    def _admission(self, priority: int):
        """Hold an execution token for the duration (no-op when the gate
        is unlimited or this statement already holds one — INSERT ..
        SELECT re-enters through _exec_select and must not buy a second
        token). AdmissionTimeout (errno 9003) propagates to the client
        as the typed "server busy" shed."""
        gate = getattr(self.storage, "admission", None)
        if gate is None or self._admission_depth > 0:
            yield
            return
        self._admission_depth += 1
        try:
            with gate.admit(priority,
                            info={"conn_id": self.conn_id or 0,
                                  "sql": self.in_flight_sql or ""}):
                yield
        finally:
            self._admission_depth -= 1

    # ==================== SELECT ====================
    def _exec_select(self, stmt: ast.SelectStmt) -> ResultSet:
        # var reads must be detected BEFORE binding substitutes them with
        # literals, or the cache would freeze the first-seen values
        has_vars = self._has_var_reads(stmt)
        stmt = self._maybe_bind_vars(stmt, has_vars)
        stmt = self._apply_binding(stmt)
        self._refresh_infoschema(stmt)
        ctx = None
        try:
            from contextlib import nullcontext

            from ..util.governor import PRI_DML, plan_priority
            # a locking read must admit BEFORE taking row locks: locks-
            # then-queue inverts against DML (admit-then-lock) and two
            # idle statements would stall each other until the
            # admission timeout. FOR UPDATE is DML-class anyway.
            outer = self._admission(PRI_DML) \
                if getattr(stmt, "for_update", False) else nullcontext()
            with outer:
                if getattr(stmt, "for_update", False):
                    self._lock_for_update(stmt)
                from .. import obs
                with obs.stage("plan_build", span_name="planner.optimize"):
                    plan = self._plan_cached(stmt, uncacheable=has_vars)
                self._check_column_privs(plan)
                # follower read tier: an eligible snapshot read may be
                # served by a replica whose closed ts covers our
                # read_ts (rpc/replica.py). Routed BEFORE admission —
                # the gate bounds LOCAL execution, and an offloaded
                # read must not consume a leader token. Privileges were
                # checked above; on any staleness/term/transport
                # trouble try_route returns None and the unchanged
                # local path below answers.
                from ..rpc import replica as _replica
                routed = _replica.try_route(
                    self, stmt, getattr(self, "_route_sql", None),
                    has_vars, expect_cols=len(plan.schema.fields))
                if routed is not None:
                    names = [f.name for f in plan.schema.fields]
                    ftypes = [f.ftype for f in plan.schema.fields]
                    self._found_rows = len(routed.rows)
                    self.vars["last_plan_from_binding"] = getattr(
                        self, "_lpfb_next", 0)
                    return ResultSet(names, routed.rows,
                                     column_types=ftypes)
                # execution admission: the gate bounds concurrently
                # RUNNING statements, priority from the planner's cost
                # estimate (point gets and small scans outrank
                # analytical sweeps); no-op when already admitted above
                with self._admission(plan_priority(plan)):
                    ctx = self._exec_ctx()
                    try:
                        chunk = run_physical(plan, ctx)
                    finally:
                        ctx.close()
        finally:
            # always clear the per-statement read-ts override — a plan
            # error after FOR UPDATE locking must not leak for_update_ts
            # into later statements' snapshots
            if self.txn is not None:
                self.txn.stmt_read_ts = None
            # record the working-set peak even when the statement died
            # (that is precisely when a governor kill needs explaining)
            if ctx is not None:
                self.last_mem_peak = ctx.mem.peak_footprint()
                self.last_spill_count = ctx.mem.spill_count
        self.vars["last_plan_from_binding"] = getattr(
            self, "_lpfb_next", 0)
        self._found_rows = chunk.num_rows  # FOUND_ROWS()
        names = [f.name for f in plan.schema.fields]
        ftypes = [f.ftype for f in plan.schema.fields]
        if not chunk.columns:
            return ResultSet(names, [], column_types=ftypes)
        return ResultSet(names, chunk.to_pylist(), column_types=ftypes)

    def _lock_for_update(self, stmt: ast.SelectStmt) -> None:
        """SELECT ... FOR UPDATE row locks (reference: point-get/scan
        executors lock keys under pessimistic txns). Only pessimistic
        transactions take locks; optimistic ones keep commit-time
        conflict detection (the reference behaves the same)."""
        txn = self._ensure_txn()
        if not txn.pessimistic or stmt.from_ is None:
            return
        if not isinstance(stmt.from_, ast.TableName):
            raise SQLError(
                "FOR UPDATE supports single-table queries only")
        info, _ = self._table_for(stmt.from_)
        for child, _store in self._partition_children(info):
            self._pessimistic_scan(child, stmt.from_, stmt.where, txn)

    # ==================== OLTP point fast path ====================
    def _fast_path_eligible(self, stmt: ast.Stmt) -> bool:
        """Session-state half of the TryFastPlan gate — ONE definition
        shared by statement execution and EXPLAIN ANALYZE, so the plan
        EXPLAIN shows is the plan that runs."""
        if self.in_explicit_txn or self.txn is not None:
            return False  # explicit txns keep the planned read/lock paths
        if self.user is not None:
            return False  # column-privilege checks live on the slow path
        if not isinstance(stmt, (ast.SelectStmt, ast.InsertStmt,
                                 ast.UpdateStmt, ast.DeleteStmt)):
            return False
        if isinstance(stmt, ast.SelectStmt):
            if self.session_bindings or self.storage.bindings.has_any():
                return False  # a binding could redirect this exact text
            try:
                if str(self._sysvar_value("tidb_replica_read")
                       or "leader").lower() != "leader":
                    # the operator asked reads to offload to followers;
                    # routing preference beats the local bypass
                    return False
            except SQLError:
                pass
        try:
            return bool(int(
                self._sysvar_value("tidb_enable_fast_path") or 0))
        except (TypeError, ValueError):
            return False

    def _try_fast_path(self, stmt: ast.Stmt) -> Optional[ResultSet]:
        """TryFastPlan gate: plan-cache-keyed point statements execute
        straight against the KV/MVCC layer — no planner, no ExecContext,
        no coprocessor (and so no JAX backend). Returns None whenever
        the statement (or session state) is not point-shaped; the
        caller's slow path is authoritative for everything else."""
        if not self._fast_path_eligible(stmt):
            return None
        from .. import obs
        from ..plan import fastpath
        with obs.stage("fast_plan"):
            fp = self._fast_plan_cached(stmt)
        if fp is None:
            return None
        obs.note_engine("point")
        return fastpath.execute(self, fp)

    def _fast_plan_cached(self, stmt: ast.Stmt):
        """Recognize (or fetch the cached) FastPlan for this statement.
        Shares the session plan-cache LRU and its hit/miss/eviction
        counters with the physical-plan cache — the keys embed the
        literals, so a cached FastPlan replays exactly."""
        from ..plan import fastpath
        key = self._plan_cache_key
        use_cache = key is not None and self._plan_cache_enabled()
        o = self.storage.obs
        gen = None
        if use_cache:
            gen = self._plan_cache_gen()
            entry = self._plan_cache.get(key)
            if entry is not None and entry[0] == gen and \
                    isinstance(entry[1], fastpath.FastPlan):
                self._plan_cache.move_to_end(key)
                self.plan_cache_hits += 1
                self.last_plan_from_cache = True
                o.plan_cache_hits.inc()
                return entry[1]
            # a cached PHYSICAL plan falls through: recognition is a
            # cheap AST walk, and the entry may predate a fast-path
            # re-enable (the common non-point statement bails out of
            # recognition within a few isinstance checks anyway)
        fp = fastpath.try_plan(self, stmt)
        if fp is not None and use_cache:
            # every cache-enabled lookup that had to (re)recognize is a
            # miss — symmetric with _plan_cached, so the hit ratio
            # stays honest even for entries deliberately not stored
            o.plan_cache_misses.inc()
            # text-keyed DML embeds its literals, so ad-hoc point
            # writes would fill the LRU with never-reused entries and
            # evict the session's recurring SELECT plans; recognition
            # is a cheap AST walk, so only keys built for replay
            # (prepared #stmt keys) and SELECT texts are worth a slot
            if key.startswith("#stmt") or \
                    isinstance(stmt, ast.SelectStmt):
                self._plan_cache_put(key, gen, fp)
        return fp

    def _plan_cache_gen(self) -> tuple:
        """Invalidation generation every cache entry is stamped with
        (reference: planCacheKey carries schema version + stats,
        planner/core/cache.go)."""
        return (self.catalog.version, self.storage.stats.generation,
                self.current_db, self._binding_gen,
                self.storage.bindings.fingerprint())

    def _plan_cache_enabled(self) -> bool:
        try:
            return bool(int(self._sysvar_value("tidb_enable_plan_cache")
                            or 0))
        except (TypeError, ValueError):
            return False

    def _plan_cache_put(self, key: str, gen: tuple, plan) -> None:
        """Insert as most-recent; evict least-recently-used past
        capacity (performance.plan-cache-size / tidb_plan_cache_size)."""
        cache = self._plan_cache
        if key in cache:
            cache.move_to_end(key)
        cache[key] = (gen, plan)
        try:
            cap = int(self._sysvar_value("tidb_plan_cache_size") or 128)
        except (TypeError, ValueError):
            cap = 128
        evict = self.storage.obs.plan_cache_evictions
        while len(cache) > max(cap, 1):
            cache.popitem(last=False)
            evict.inc()

    def _plan_cached(self, stmt: ast.SelectStmt, uncacheable: bool = False):
        """Plan, going through the SQL-text plan cache when the statement
        is cache-safe (no @@var reads, no FOR UPDATE locking) and the
        cache is enabled. Entries invalidate on schema version or stats
        generation change; the cache is a true LRU — a hit moves the
        entry to the back, capacity evicts from the front."""
        key = self._plan_cache_key
        if (key is None or uncacheable or not self._plan_cache_enabled()
                or getattr(stmt, "for_update", False)):
            return self._plan(stmt)
        from ..plan.fastpath import FastPlan
        o = self.storage.obs
        gen = self._plan_cache_gen()
        entry = self._plan_cache.get(key)
        if entry is not None and entry[0] == gen \
                and not isinstance(entry[1], FastPlan):
            # (a FastPlan under this key means the point path cached it
            # while enabled; replan physically rather than mis-execute)
            self._plan_cache.move_to_end(key)
            self.plan_cache_hits += 1
            self.last_plan_from_cache = True
            o.plan_cache_hits.inc()
            return entry[1]
        o.plan_cache_misses.inc()
        plan = self._plan(stmt)
        self._plan_cache_put(key, gen, plan)
        return plan

    def _plan(self, stmt: ast.SelectStmt):
        try:
            logical = PlanBuilder(self.catalog, self.current_db).build_select(
                stmt)
            return optimize(logical, self.storage.stats)
        except PlanError as e:
            raise err_wrap(SQLError, e) from None

    # ==================== DML ====================
    def _exec_insert(self, stmt: ast.InsertStmt,
                     rows_override: Optional[list[list[Any]]] = None,
                     load_ignore: bool = False) -> ResultSet:
        info, store = self._table_for(stmt.table)
        col_order = self._insert_columns(info, stmt.columns)
        self._check_dml_columns(
            stmt.table, info, "INSERT",
            [info.columns[o].name for o in col_order])
        txn = self._ensure_txn()

        rows: list[list[Any]] = []
        if rows_override is not None:
            rows = rows_override
        elif stmt.select is not None:
            sub = self._exec_select(stmt.select)
            rows = [list(r) for r in sub.rows]
        else:
            for value_row in stmt.rows:
                if len(value_row) != len(col_order):
                    raise SQLError("column count doesn't match value count",
                                   errno=ER_WRONG_VALUE_COUNT_ON_ROW)
                rows.append([self._eval_value(e) for e in value_row])

        # pessimistic txns lock + duplicate-check at the latest committed
        # view (a concurrent INSERT of the same key surfaces as a
        # duplicate here instead of a conflict at commit)
        from ..kv import tablecodec

        if txn.pessimistic:
            txn.stmt_read_ts = txn.refresh_for_update_ts()
        timeout = float(
            self._sysvar_value("innodb_lock_wait_timeout") or 50)
        part = getattr(info, "partition", None)
        children = {c.id: (c, s) for c, s in
                    self._partition_children(info)}
        checkers: dict[int, _UniqueChecker] = {}

        def checker_for(tid: int, fresh: bool = False) -> _UniqueChecker:
            if fresh or tid not in checkers:
                cinfo, cstore = children[tid]
                checkers[tid] = _UniqueChecker(cinfo, cstore, txn)
            return checkers[tid]

        try:
            count = 0
            for rv in rows:
                if len(rv) != len(col_order):
                    raise SQLError("column count doesn't match value count",
                                   errno=ER_WRONG_VALUE_COUNT_ON_ROW)
                full = self._complete_row(info, col_order, rv, store)
                handle = self._row_handle(info, full, store)
                enc = store.encode_row(full)
                if part is not None:
                    # route by partition column (reference:
                    # table/tables/partition.go locatePartition); unique
                    # keys include the partition column, so duplicate
                    # checks stay within the target partition
                    try:
                        tid = part.route(enc[part.col_offset]).id
                    except ValueError as e:
                        raise err_wrap(SQLError, e) from None
                else:
                    tid = info.id
                tinfo = children[tid][0]
                if txn.pessimistic:
                    # lock the new record key AND every unique-index key
                    # this row claims (lock-only keys need no data record)
                    # so a concurrent insert of the same UNIQUE value —
                    # under ANY handle — serializes behind us; after any
                    # wait, re-check duplicates at a fresh view, since the
                    # holder may have committed the very value we carry
                    # (reference: pessimistic lock-then-recheck;
                    # tables/index.go unique key constraint via KV)
                    from ..kv.backoff import (BO_TXN_CONFLICT, BO_TXN_LOCK,
                                              Backoffer, BackoffExhausted)
                    from ..kv.mvcc import WriteConflictError as KVConflict
                    lock_keys = [tablecodec.record_key(tid, handle)]
                    lock_keys += self._unique_lock_keys(tinfo, enc)
                    # the Backoffer budget is the SOLE terminator: like
                    # _lock_for_update, exhaustion surfaces the typed
                    # retry history instead of a bare count cap
                    import time as _time
                    bo = Backoffer(budget_ms=int(timeout * 1000))
                    while True:
                        t0_lock = _time.monotonic()
                        try:
                            waited = self.storage.pessimistic_lock_keys(
                                txn, lock_keys, timeout)
                        except KVConflict:
                            # a commit landed past our for_update_ts:
                            # EVERY cached checker's snapshot is stale
                            txn.stmt_read_ts = txn.refresh_for_update_ts()
                            checkers.clear()
                            try:
                                blocked = _time.monotonic() - t0_lock
                                if blocked > 0.001:
                                    bo.charge(BO_TXN_LOCK, blocked)
                                bo.sleep(BO_TXN_CONFLICT)
                            except BackoffExhausted as e:
                                raise err_wrap(SQLError, e) from None
                            continue
                        except (Storage.DeadlockError,
                                Storage.LockWaitTimeout) as e:
                            raise err_wrap(SQLError, e) from None
                        if waited:
                            txn.stmt_read_ts = txn.refresh_for_update_ts()
                            checkers.clear()
                            # time blocked on foreign locks counts against
                            # the SAME typed budget (as _pessimistic_scan
                            # does), or adversarial victim churn could
                            # hold the statement far past
                            # innodb_lock_wait_timeout — each wait is a
                            # free extra timeout otherwise
                            blocked = _time.monotonic() - t0_lock
                            if blocked > 0.001:
                                try:
                                    bo.charge(BO_TXN_LOCK, blocked)
                                except BackoffExhausted as e:
                                    raise err_wrap(SQLError, e) from None
                        checker = checker_for(tid)
                        conflicts = checker.conflicts(handle, enc)
                        # REPLACE deletes its victims and ON DUPLICATE
                        # updates the first one: both write rows they
                        # didn't insert, so those record keys need locks
                        if not (conflicts
                                and (stmt.is_replace or stmt.on_dup)):
                            break
                        victims = [tablecodec.record_key(tid, h)
                                   for h in conflicts
                                   if tablecodec.record_key(tid, h)
                                   not in txn.locked_keys]
                        if not victims:
                            break
                        lock_keys = victims  # lock them, then re-check
                        # adversarial churn (victims changing every
                        # round) burns the same typed budget instead of
                        # spinning unbounded
                        try:
                            bo.sleep(BO_TXN_CONFLICT)
                        except BackoffExhausted as e:
                            raise err_wrap(SQLError, e) from None
                else:
                    checker = checker_for(tid)
                    conflicts = checker.conflicts(handle, enc)
                if conflicts:
                    if load_ignore:
                        continue  # LOAD DATA IGNORE / INSERT IGNORE: skip
                    if stmt.on_dup:
                        count += self._apply_on_dup(
                            stmt, info, tinfo, tid, store, txn, checker,
                            conflicts[0], full)
                        continue  # the new row itself is not inserted
                    if not stmt.is_replace:
                        raise SQLError(
                            checker.dup_message(handle, enc, conflicts),
                            errno=ER_DUP_ENTRY)
                    for h in conflicts:
                        txn.delete_row(tid, h)
                        checker.note_delete(h)
                    count += len(conflicts)  # MySQL: replaced rows count 2x
                if not txn.pessimistic:
                    # claim the unique values as lock-only guard keys so
                    # a CONCURRENT optimistic insert of the same value
                    # collides at 2PC prewrite instead of both committing
                    # (race found by test_race_harness.py). Only for rows
                    # actually staged — an IGNORE/ON DUP skip must not
                    # leave guard records on values it never wrote.
                    txn.guard_keys.update(
                        self._unique_lock_keys(tinfo, enc))
                txn.set_row(tid, handle, enc)
                checker.note_insert(handle, enc)
                count += 1
            return ResultSet([], [], affected=count)
        finally:
            txn.stmt_read_ts = None

    # rows per checksum chunk: large enough to amortize the numpy view
    # construction, small enough that KILL QUERY lands promptly
    CHECKSUM_CHUNK = 1 << 16

    def _exec_checksum(self, stmt: ast.ChecksumTableStmt) -> ResultSet:
        """CHECKSUM TABLE: deterministic crc32 over the visible rows in
        HANDLE order (compaction reorders rows physically; two replicas
        with identical content but different compaction state must
        agree), column-major: handles, then per column the validity
        bitmap followed by the cell payloads — fixed-width cells with
        NULLs zeroed, strings length-prefixed (("ab","c") != ("a","bc"))
        with only valid cells contributing. Vectorized into per-column
        chunked numpy byte views so million-row tables checksum at
        memory speed, with the KILL flag polled between chunks
        (reference: executor/checksum.go; the polynomial differs — the
        value is stable across servers/restarts, which is what
        replication-drift checks need)."""
        import zlib

        from ..util import interrupt

        step = self.CHECKSUM_CHUNK
        txn = self._ensure_txn()
        rows = []
        for tn in stmt.tables:
            info, _ = self._table_for(tn)
            crc = 0
            for cinfo, _store in self._partition_children(info):
                snap = txn.snapshot(cinfo.id)
                n = snap.num_visible_rows
                handles = snap.handles()
                order = np.argsort(handles, kind="stable")
                hs = np.ascontiguousarray(
                    handles[order].astype("<i8", copy=False))
                for lo in range(0, n, step):
                    interrupt.check()
                    crc = zlib.crc32(hs[lo:lo + step].tobytes(), crc)
                for off in range(cinfo.num_columns):
                    col = snap.column(off)
                    data = col.data[order]
                    valid = col.validity[order].astype(bool, copy=False)
                    d = col.dictionary
                    is_str = d is not None and len(d) and \
                        cinfo.columns[off].ftype.is_string
                    if is_str:
                        # one length-prefixed encode per DICTIONARY
                        # entry, not per cell
                        blobs = [len(b).to_bytes(4, "little") + b
                                 for b in (s.encode() for s in d.values)]
                    for lo in range(0, n, step):
                        interrupt.check()
                        dv = data[lo:lo + step]
                        vv = valid[lo:lo + step]
                        crc = zlib.crc32(
                            np.packbits(vv).tobytes(), crc)
                        if is_str:
                            payload = b"".join(
                                map(blobs.__getitem__,
                                    dv[vv].astype(np.int64).tolist()))
                            crc = zlib.crc32(payload, crc)
                        elif dv.dtype.kind in "iub":
                            ints = np.where(
                                vv, dv.astype("<i8", copy=False),
                                np.int64(0))
                            crc = zlib.crc32(
                                np.ascontiguousarray(ints).tobytes(),
                                crc)
                        else:
                            f = np.array(dv, copy=True)
                            f[~vv] = 0
                            crc = zlib.crc32(
                                np.ascontiguousarray(f).tobytes(), crc)
                crc = zlib.crc32(str(n).encode(), crc)
            db = tn.db or self.current_db
            rows.append((f"{db}.{info.name}", crc & 0xFFFFFFFF))
        return ResultSet(["Table", "Checksum"], rows)

    # ==================== roles ===========================================
    def _exec_role_stmt(self, stmt) -> ResultSet:
        """Role management + activation (reference:
        privilege/privileges role graph, executor/set_role;
        tests: privileges_test.go TestRole*)."""
        from .privileges import PrivilegeError
        pm = self.storage.privileges
        try:
            if isinstance(stmt, ast.CreateRoleStmt):
                self._require_super()
                pm.create_role(stmt.names, stmt.if_not_exists)
            elif isinstance(stmt, ast.DropRoleStmt):
                self._require_super()
                pm.drop_role(stmt.names, stmt.if_exists)
            elif isinstance(stmt, ast.GrantRoleStmt):
                self._require_super()
                pm.grant_roles(stmt.roles, stmt.users, stmt.revoke)
            elif isinstance(stmt, ast.SetDefaultRoleStmt):
                # users may set their OWN default roles; SUPER for others
                if any(u != (self.user or "root") for u in stmt.users):
                    self._require_super()
                # validate every user (existence AND grantedness of the
                # listed roles) before mutating any — same atomicity
                # contract as the other role mutations
                for u in stmt.users:
                    if not pm.exists(u):
                        raise SQLError(f"unknown user '{u}'",
                                       errno=ER_SPECIFIC_ACCESS_DENIED)
                    if stmt.mode == "LIST":
                        granted = pm.roles_of(u)
                        for r in stmt.roles:
                            if r not in granted:
                                raise SQLError(
                                    f"role '{r}' is not granted to "
                                    f"'{u}'",
                                    errno=ER_SPECIFIC_ACCESS_DENIED)
                for u in stmt.users:
                    pm.set_default_roles(u, stmt.mode, stmt.roles)
            else:  # SetRoleStmt: activate for THIS session
                me = self.user or "root"
                granted = pm.roles_of(me)
                if stmt.mode == "ALL":
                    self.active_roles = set(granted)
                elif stmt.mode == "NONE":
                    self.active_roles = set()
                elif stmt.mode == "DEFAULT":
                    self.active_roles = pm.default_roles(me)
                else:
                    missing = [r for r in stmt.roles if r not in granted]
                    if missing:
                        raise SQLError(
                            f"Role '{missing[0]}' has not been granted "
                            f"to '{me}'", errno=ER_SPECIFIC_ACCESS_DENIED)
                    self.active_roles = set(stmt.roles)
        except PrivilegeError as e:
            raise err_wrap(SQLError, e) from None
        return ResultSet([], [])

    # ==================== SQL plan management (bindinfo) ==================
    def _exec_create_binding(self, stmt: ast.CreateBindingStmt
                             ) -> ResultSet:
        """CREATE [GLOBAL|SESSION] BINDING (reference: bindinfo
        CreateBindRecord). The FOR and USING statements must normalize
        identically modulo hints."""
        from .bindinfo import (binding_digest, normalize_binding_sql)
        norm_orig = normalize_binding_sql(stmt.orig_sql)
        norm_bind = normalize_binding_sql(stmt.bind_sql)
        if norm_orig != norm_bind:
            raise SQLError(
                "create binding only supports a USING statement that "
                "differs from the original by optimizer hints")
        bs = stmt.bind_stmt
        hints = list(getattr(bs, "hints", []) or (
            bs.selects[0].hints if isinstance(bs, ast.SetOpStmt) else []))
        if stmt.scope == "GLOBAL":
            self._require_super()
            self.storage.bindings.create(
                norm_orig, stmt.bind_sql, self.current_db, hints)
        else:
            from .bindinfo import make_record
            self.session_bindings[
                binding_digest(norm_orig, self.current_db)] = make_record(
                norm_orig, stmt.bind_sql, self.current_db, hints)
        self._binding_gen += 1
        return ResultSet([], [])

    def _exec_drop_binding(self, stmt: ast.DropBindingStmt) -> ResultSet:
        from .bindinfo import binding_digest, normalize_binding_sql
        norm = normalize_binding_sql(stmt.orig_sql)
        if stmt.scope == "GLOBAL":
            self._require_super()
            self.storage.bindings.drop(norm, self.current_db)
        else:
            self.session_bindings.pop(
                binding_digest(norm, self.current_db), None)
        self._binding_gen += 1
        return ResultSet([], [])

    def _apply_binding(self, stmt):
        """Hint injection for a matched binding: SESSION bindings shadow
        GLOBAL ones; the user's literals are kept and only the binding's
        hint set transfers (reference: bindinfo/bind_record.go).

        @@last_plan_from_binding describes the PREVIOUS statement, so the
        new value lands in session vars only when this statement
        finishes (_exec_select) — a probe SELECT reading the variable at
        runtime still sees its predecessor's value."""
        self._lpfb_next = 0
        sql = self._binding_match_sql
        if not sql or (not self.session_bindings
                       and not self.storage.bindings.has_any()):
            return stmt
        if not int(self._sysvar_value("tidb_use_plan_baselines") or 0):
            return stmt
        from .bindinfo import binding_digest, normalize_binding_sql
        norm = normalize_binding_sql(sql)
        rec = self.session_bindings.get(
            binding_digest(norm, self.current_db)) \
            or self.storage.bindings.match(norm, self.current_db)
        if not rec or rec.get("status") != "enabled":
            return stmt
        hints = [(h[0], list(h[1])) for h in rec.get("hints", [])]
        if isinstance(stmt, ast.SetOpStmt):
            stmt.selects[0].hints = hints
        else:
            stmt.hints = hints
        self._lpfb_next = 1
        return stmt

    # ==================== LOAD DATA / INTO OUTFILE / ADMIN CHECK ==========
    def _require_file_priv(self, path: str) -> None:
        """Server-side file access needs the global FILE privilege, and
        secure_file_priv (when set) confines paths to that directory —
        both per MySQL (reference: planner visitInfo FILE checks;
        executor/load_data.go / select_into.go)."""
        if self.user is not None and not self.storage.privileges.check(
                self.user, "FILE", "*", "*", roles=self.active_roles):
            raise SQLError(
                "Access denied; you need (at least one of) the FILE "
                f"privilege(s) for this operation (user '{self.user}')",
                errno=ER_SPECIFIC_ACCESS_DENIED)
        self._confine_secure_path(path)

    def _confine_secure_path(self, path: str) -> None:
        """secure_file_priv confinement (when set) — applied to EVERY
        server-side file read/write, including opted-in LOAD DATA LOCAL
        (whose read is server-side here, unlike MySQL's client-side
        transfer, so the confinement must still hold)."""
        import os
        base = str(self._sysvar_value("secure_file_priv") or "")
        if base and not os.path.realpath(path).startswith(
                os.path.realpath(base) + os.sep):
            raise SQLError(
                "The MySQL server is running with the "
                "--secure-file-priv option so it cannot execute this "
                "statement", errno=ER_OPTION_PREVENTS_STATEMENT)

    def _exec_load_data(self, stmt: ast.LoadDataStmt) -> ResultSet:
        """LOAD DATA INFILE: parse the file host-side, then feed the rows
        through the transactional insert path so duplicate checks,
        partition routing and indexes all apply (reference:
        executor/load_data.go; TiDB too batches through the txn layer)."""
        import os
        if stmt.local and not self._sysvar_value("local_infile"):
            # without the explicit local_infile opt-in (config
            # local-infile / SET GLOBAL local_infile=1) LOCAL keeps the
            # typed rejection: the COM_QUERY LOCAL INFILE wire transfer
            # is not implemented, and silently reading a SERVER-side
            # path would be both surprising and a privilege escalation
            # for FILE-less users
            raise SQLError(
                "LOAD DATA LOCAL INFILE is not supported (enable the "
                "local_infile system variable / local-infile config to "
                "accept it); use server-side LOAD DATA INFILE",
                errno=ER_NOT_SUPPORTED_YET)
        info, store = self._table_for(stmt.table)
        col_order = self._insert_columns(info, stmt.columns)
        path = stmt.fmt.path
        if not stmt.local:
            self._require_file_priv(path)
        else:
            # LOCAL (opted in): MySQL's LOCAL reads the CLIENT's own
            # file, but THIS implementation reads a server-side path —
            # so an authenticated user must bring either the FILE
            # privilege or a configured secure_file_priv confinement
            # (otherwise the LOCAL spelling would hand every FILE-less
            # user the server's filesystem). Embedded sessions
            # (user=None) are unchecked, as everywhere. Duplicate-key
            # errors degrade to IGNORE unless REPLACE was given
            # (reference: executor/load_data.go — LOCAL cannot abort a
            # half-streamed file).
            confined = bool(
                str(self._sysvar_value("secure_file_priv") or ""))
            if not confined and self.user is not None and \
                    not self.storage.privileges.check(
                        self.user, "FILE", "*", "*",
                        roles=self.active_roles):
                raise SQLError(
                    "LOAD DATA LOCAL INFILE reads a server-side path "
                    "on this server; grant FILE or set "
                    "secure_file_priv to confine it",
                    errno=ER_SPECIFIC_ACCESS_DENIED)
            self._confine_secure_path(path)
        if not os.path.isfile(path):
            raise SQLError(f"File '{path}' not found",
                           errno=ER_FILE_NOT_FOUND)
        try:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError as e:
            raise SQLError(f"Can't read file '{path}': {e}",
                           errno=ER_TEXTFILE_NOT_READABLE) from None
        records = _parse_load_file(text, stmt.fmt)
        records = records[stmt.ignore_lines:]
        ftypes = [info.columns[off].ftype for off in col_order]
        rows: list[list[Any]] = []
        for fields in records:
            vals = []
            for i, ft in enumerate(ftypes):
                s = fields[i] if i < len(fields) else None
                vals.append(_load_convert(ft, s))
            rows.append(vals)
        shim = ast.InsertStmt(stmt.table, stmt.columns,
                              is_replace=stmt.dup_mode == "replace")
        ignore = stmt.dup_mode == "ignore" or (
            stmt.local and stmt.dup_mode != "replace")
        return self._exec_insert(shim, rows_override=rows,
                                 load_ignore=ignore)

    def _write_outfile(self, rs: ResultSet, fmt) -> ResultSet:
        """SELECT ... INTO OUTFILE (reference: executor/select_into.go).
        Refuses to overwrite, like MySQL."""
        import os
        self._require_file_priv(fmt.path)
        if os.path.exists(fmt.path):
            raise SQLError(f"File '{fmt.path}' already exists",
                           errno=ER_FILE_EXISTS)
        esc, enc = fmt.escaped, fmt.enclosed
        specials = {esc or "", enc or "",
                    fmt.field_term[:1], fmt.line_term[:1]}
        specials.discard("")

        def render(v) -> str:
            if v is None:
                return esc + "N" if esc else "NULL"
            s = _outfile_text(v)
            if esc:
                s = "".join(esc + c if c in specials else c for c in s)
            return enc + s + enc if enc else s

        lines = [fmt.field_term.join(render(v) for v in row)
                 for row in rs.rows]
        body = fmt.line_term.join(lines)
        if lines:
            body += fmt.line_term
        try:
            with open(fmt.path, "x", encoding="utf-8") as f:
                f.write(body)
        except OSError as e:
            raise SQLError(f"Can't create file '{fmt.path}': {e}",
                           errno=ER_CANT_CREATE_FILE) from None
        return ResultSet([], [], affected=len(rs.rows))

    def _exec_admin_check(self, stmt: ast.AdminStmt) -> ResultSet:
        """ADMIN CHECK TABLE: verify storage/index invariants per table
        (reference: executor/admin.go CheckTable). The TPU index design
        has no per-row index KV to drift, so the checked invariants are
        the ones THIS storage can violate: epoch column/validity shapes,
        handle uniqueness, cached index permutations actually sorting
        their epoch, unique-key duplicates among visible rows, and
        partition routing."""
        for tn in stmt.tables:
            info, _ = self._table_for(tn)
            for cinfo, cstore in self._partition_children(info):
                self._admin_check_store(info, cinfo, cstore)
        return ResultSet([], [])

    def _admin_check_store(self, root: TableInfo, info: TableInfo,
                           store: TableStore) -> None:
        from ..store.index import epoch_index_order

        def fail(what: str) -> None:
            raise SQLError(
                f"admin check table {root.name} failed: {what}",
                errno=ER_DATA_INCONSISTENT)

        txn = self._ensure_txn()
        snap = txn.snapshot(info.id)
        epoch = snap.epoch
        n = epoch.num_rows
        for ci in range(info.num_columns):
            if len(epoch.columns[ci]) != n:
                fail(f"column {info.columns[ci].name} has "
                     f"{len(epoch.columns[ci])} rows, epoch has {n}")
            v = epoch.valids[ci]
            if v is not None and len(v) != n:
                fail(f"validity of {info.columns[ci].name} has {len(v)} "
                     f"rows, epoch has {n}")
        if len(np.unique(epoch.handles)) != n:
            fail("duplicate handles in epoch")
        for idx in info.indices:
            if not idx.visible:
                continue
            order = epoch_index_order(store, epoch, idx)
            if len(order) != n or (
                    n and not np.array_equal(np.sort(order),
                                             np.arange(n))):
                fail(f"index {idx.name}: cached order is not a "
                     "permutation of the epoch")
            # key columns must be lexicographically non-decreasing along
            # the permutation (NULLs-first per level)
            if n:
                prev_eq = np.ones(n - 1, bool)
                for off in idx.col_offsets:
                    data = epoch.columns[off][order]
                    valid = epoch.valids[off]
                    vv = valid[order] if valid is not None else \
                        np.ones(n, bool)
                    lvl = np.stack([vv.astype(np.int64),
                                    np.where(vv, data, 0)], axis=1)
                    cmp_lt = (lvl[:-1, 0] < lvl[1:, 0]) | (
                        (lvl[:-1, 0] == lvl[1:, 0])
                        & (lvl[:-1, 1] < lvl[1:, 1]))
                    cmp_eq = (lvl[:-1] == lvl[1:]).all(axis=1)
                    if not np.all(~prev_eq | cmp_lt | cmp_eq):
                        fail(f"index {idx.name}: epoch not sorted by key")
                    prev_eq &= cmp_eq
            if idx.unique:
                self._admin_check_unique(info, snap, idx, fail)
        part = getattr(root, "partition", None)
        if part is not None and info.id != root.id:
            off = part.col_offset
            vals = epoch.columns[off]
            vv = epoch.valids[off]
            check_vals = vals if vv is None else vals[vv]
            for u in np.unique(check_vals):
                if part.route(int(u)).id != info.id:
                    fail(f"row with partition key {u} stored in wrong "
                         f"partition {info.name}")

    def _admin_check_unique(self, info: TableInfo, snap, idx, fail) -> None:
        """No duplicate fully-non-NULL unique-key tuples among rows
        visible at this snapshot (epoch ∩ base_visible + overlay)."""
        keys = []
        valid_all = None
        vis = snap.base_visible
        for off in idx.col_offsets:
            base = snap.epoch.columns[off][vis]
            ov = snap.overlay_columns[off]
            col = np.concatenate([base, ov])
            if np.issubdtype(col.dtype, np.floating):
                # dedup on bit patterns, not truncation
                from ..copr.analyze import float_bits_key
                col = float_bits_key(col)
            else:
                col = col.astype(np.int64)
            bvl = snap.epoch.valids[off]
            bv = bvl[vis] if bvl is not None else np.ones(len(base), bool)
            ovl = snap.overlay_valids[off]
            o = ovl if ovl is not None else np.ones(len(ov), bool)
            vcat = np.concatenate([bv, o])
            keys.append(col)
            valid_all = vcat if valid_all is None else (valid_all & vcat)
        if not keys or valid_all is None or not valid_all.any():
            return
        stacked = np.stack(keys, axis=1)[valid_all]
        uniq = np.unique(stacked, axis=0)
        if len(uniq) != len(stacked):
            fail(f"unique index {idx.name}: duplicate key values among "
                 "visible rows")

    def _apply_on_dup(self, stmt, info, tinfo, tid: int, store, txn,
                      checker, handle: int, full: list) -> int:
        """ON DUPLICATE KEY UPDATE: update the first conflicting row
        with the assignment list; VALUES(col) refers to the would-be
        inserted row (reference: executor/insert.go
        doDupRowUpdate + expression/builtin_other.go VALUES)."""
        handle = int(handle)
        snap = txn.snapshot(tid)
        gathered = snap.gather(np.array([handle], np.int64),
                               list(range(tinfo.num_columns)))
        existing: list[Any] = []
        for data, valid in gathered:
            existing.append(None if not valid[0]
                            else _np_scalar(data[0]))
        builder = PlanBuilder(self.catalog, self.current_db)
        scan = builder._build_scan(stmt.table)
        # 1-row evaluator over the existing row
        cols = []
        dicts = []
        for off in range(tinfo.num_columns):
            ft = tinfo.columns[off].ftype
            arr = np.zeros(1, ft.np_dtype)
            vl = np.ones(1, bool)
            if existing[off] is None:
                vl[0] = False
            else:
                arr[0] = existing[off]
            cols.append((arr, vl))
            dicts.append(store.dictionaries[off])
        ev = NumpyEval(cols, dicts, 1)
        col_by_name = {c.name.lower(): c for c in tinfo.columns}
        new_phys = list(existing)
        for a in stmt.on_dup:
            target = col_by_name.get(a.column.name.lower())
            if target is None:
                raise SQLError(f"unknown column {a.column.name}",
                               errno=ER_BAD_FIELD)
            ci = target.offset
            col_ft = target.ftype
            # col = VALUES(col2): direct host-value re-encode (keeps
            # temporal/decimal domains exact)
            av = a.value
            if isinstance(av, ast.FuncCall) and av.name == "VALUES":
                src = col_by_name.get(av.args[0].name.lower())
                if src is None:
                    raise SQLError(
                        f"unknown column {av.args[0].name} in VALUES()")
                from ..chunk.column import _encode_scalar
                v = full[src.offset]
                new_phys[ci] = None if v is None else _encode_scalar(
                    col_ft, v, store.dictionaries[ci])
            else:
                expr_ast = self._subst_values_refs(av, col_by_name, full)
                try:
                    pe = builder.resolve(expr_ast, scan.schema)
                except PlanError as e:
                    raise err_wrap(SQLError, e) from None
                if col_ft.is_string:
                    sv, svl = ev.eval_str(pe)
                    d = store.dictionaries[ci]
                    new_phys[ci] = d.encode(sv[0]) if svl[0] else None
                else:
                    vv = ev.eval(pe)
                    if pe.ftype.kind != col_ft.kind or (
                            col_ft.is_decimal
                            and pe.ftype.scale != col_ft.scale):
                        vv = ev._cast(vv, pe.ftype, col_ft)
                    v, vl = vv
                    new_phys[ci] = None if not np.asarray(vl)[0] \
                        else _np_scalar(np.asarray(v)[0])
            if new_phys[ci] is None and not col_ft.nullable:
                raise SQLError(
                    f"column {target.name} cannot be null")
        if info.pk_handle_offset is not None and \
                new_phys[info.pk_handle_offset] != \
                existing[info.pk_handle_offset]:
            raise SQLError(
                "changing the primary key in ON DUPLICATE KEY UPDATE "
                "is unsupported")
        if tuple(new_phys) == tuple(existing):
            return 0  # MySQL: unchanged row counts 0
        conf = checker.conflicts(handle, tuple(new_phys), exclude=handle)
        if conf:
            raise SQLError(
                checker.dup_message(handle, tuple(new_phys), conf))
        txn.set_row(tid, handle, tuple(new_phys))
        checker.note_delete(handle)
        checker.note_insert(handle, tuple(new_phys))
        return 2  # MySQL: an updated duplicate counts 2

    def _subst_values_refs(self, node, col_by_name, full: list):
        """Replace VALUES(col) with the new row's host value as a typed
        literal (non-temporal domains; plain `col = VALUES(col)` takes
        the exact re-encode path above). Transforms a COPY: the on_dup
        AST is shared across conflicting rows, and baking one row's
        values into it would replay them for every later conflict."""
        import copy as _copy
        node = _copy.deepcopy(node)

        def fn(n):
            if isinstance(n, ast.FuncCall) and n.name == "VALUES":
                src = col_by_name.get(n.args[0].name.lower())
                if src is None:
                    raise SQLError(
                        f"unknown column {n.args[0].name} in VALUES()")
                v = full[src.offset]
                if v is None:
                    return ast.Literal(None, "null")
                if isinstance(v, bool):
                    return ast.Literal(int(v), "int")
                if isinstance(v, int):
                    return ast.Literal(v, "int")
                if isinstance(v, float):
                    return ast.Literal(v, "float")
                if isinstance(v, Decimal):
                    return ast.Literal(v, "decimal")
                return ast.Literal(str(v), "string")
            return n

        return ast.transform(node, fn)

    def _exec_update(self, stmt: ast.UpdateStmt) -> ResultSet:
        info, _ = self._table_for(stmt.table)
        self._check_dml_columns(
            stmt.table, info, "UPDATE",
            [a.column.name for a in stmt.assignments])
        # columns READ by the update (WHERE + assignment RHS) need
        # SELECT, or matched-row counts leak unreadable values (MySQL
        # requires the same)
        read_cols: list[str] = []

        def visit(n):
            if isinstance(n, ast.ColumnRef):
                read_cols.append(n.name)
            return None

        if stmt.where is not None:
            ast.walk(stmt.where, visit)
        for a in stmt.assignments:
            ast.walk(a.value, visit)
        if read_cols:
            self._check_dml_columns(stmt.table, info, "SELECT", read_cols)
        txn = self._ensure_txn()
        try:
            total = 0
            # rows moving across partitions are buffered and applied
            # AFTER every partition's snapshot-scan: writing them inline
            # would make them visible to later partitions' scans in the
            # same statement (cross-partition Halloween problem;
            # reference: the update executor collects row changes before
            # applying partition moves)
            moves: list[tuple[int, int, tuple]] = []
            for child, store in self._partition_children(info):
                rs = self._exec_update_inner(stmt, child, store, txn,
                                             parent=info, moves=moves)
                total += rs.affected
            for target_id, new_handle, phys in moves:
                tinfo = next(c for c, _s in self._partition_children(info)
                             if c.id == target_id)
                tstore = self.storage.table_store(target_id)
                checker = _UniqueChecker(tinfo, tstore, txn)
                conf = checker.conflicts(new_handle, phys)
                if conf:
                    raise SQLError(
                        checker.dup_message(new_handle, phys, conf),
                        errno=ER_DUP_ENTRY)
                tstore.note_handle(new_handle)
                # the shared allocator must never re-issue this handle
                _, alloc_store = self._table_for(stmt.table)
                alloc_store.note_handle(new_handle)
                txn.set_row(target_id, new_handle, phys)
            return ResultSet([], [], affected=total)
        finally:
            txn.stmt_read_ts = None

    def _exec_update_inner(self, stmt: ast.UpdateStmt, info, store,
                           txn, parent=None, moves=None) -> ResultSet:
        part = getattr(parent, "partition", None) if parent is not None \
            else None
        if txn.pessimistic:
            snap, mask, ev, handles = self._pessimistic_scan(
                info, stmt.table, stmt.where, txn)
        else:
            snap = txn.snapshot(info.id)
            mask, ev = self._where_mask(info, stmt.table, stmt.where, snap)
            handles = snap.handles()[mask]
        if len(handles) == 0:
            return ResultSet([], [], affected=0)
        # resolve assignments against the scan schema
        builder = PlanBuilder(self.catalog, self.current_db)
        scan = builder._build_scan(stmt.table)
        assigns: dict[int, Any] = {}
        for a in stmt.assignments:
            ci = scan.schema.resolve(a.column.name, a.column.table)
            if ci is None:
                raise SQLError(f"unknown column {a.column}",
                               errno=ER_BAD_FIELD)
            assigns[ci] = builder.resolve(a.value, scan.schema)
        # evaluate each assignment once over the whole snapshot, in the
        # column's own physical domain
        new_vals: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        for ci, e in assigns.items():
            col_ft = info.columns[ci].ftype
            if col_ft.is_string:
                sv, svl = ev.eval_str(e)
                d = store.dictionaries[ci]
                assert d is not None
                data = np.fromiter(
                    (d.encode(s) if ok else 0 for s, ok in zip(sv, svl)),
                    dtype=np.int64, count=len(sv))
                new_vals[ci] = (data, np.asarray(svl))
            else:
                vv = ev.eval(e)
                v, vl = ev._cast(vv, e.ftype, col_ft) if (
                    e.ftype.kind != col_ft.kind or
                    (col_ft.is_decimal and e.ftype.scale != col_ft.scale)
                ) else vv
                new_vals[ci] = (np.asarray(v), np.asarray(vl))
        # constraint checks only when an assigned column is the handle pk
        # or part of a unique index
        pk_changed = info.pk_handle_offset in assigns
        touches_unique = pk_changed or any(
            off in assigns
            for ix in info.indices if ix.unique or ix.primary
            for off in ix.col_offsets
        )
        checker = _UniqueChecker(info, store, txn, snap=snap) \
            if touches_unique else None
        # hoist full-column materialization out of the per-row loop
        cols = [snap.column(c) for c in range(info.num_columns)]
        col_data = [c.data for c in cols]
        col_valid = [c.validity for c in cols]
        rows_idx = np.nonzero(mask)[0]
        count = 0
        for ri, handle in zip(rows_idx, handles):
            ri = int(ri)
            handle = int(handle)
            phys = [
                None if not col_valid[c][ri] else _np_scalar(col_data[c][ri])
                for c in range(info.num_columns)
            ]
            for ci in assigns:
                v, vl = new_vals[ci]
                phys[ci] = None if not vl[ri] else _np_scalar(v[ri])
            new_handle = handle
            if pk_changed:
                pv = phys[info.pk_handle_offset]
                if pv is None:
                    raise SQLError(
                        f"column {info.columns[info.pk_handle_offset].name} "
                        "cannot be null")
                new_handle = int(pv)
                store.note_handle(new_handle)
            if checker is not None:
                conf = checker.conflicts(new_handle, tuple(phys),
                                         exclude=handle)
                if conf:
                    raise SQLError(
                        checker.dup_message(new_handle, tuple(phys), conf))
                if not txn.pessimistic:
                    # optimistic unique-value claim (same guard as the
                    # insert path; see test_race_harness.py)
                    txn.guard_keys.update(
                        self._unique_lock_keys(info, tuple(phys)))
            target_id = info.id
            if part is not None:
                # a partition-column update may move the row
                # (reference: partition.go row movement on update)
                try:
                    target_id = part.route(phys[part.col_offset]).id
                except ValueError as e:
                    raise err_wrap(SQLError, e) from None
            if target_id != info.id:
                # cross-partition move: delete here, apply after every
                # partition scanned (uniqueness checked at apply time)
                txn.delete_row(info.id, handle)
                if checker is not None:
                    checker.note_delete(handle)
                assert moves is not None
                moves.append((target_id, new_handle, tuple(phys)))
                count += 1
                continue
            if new_handle != handle:
                txn.delete_row(info.id, handle)
                if checker is not None:
                    checker.note_delete(handle)
            txn.set_row(info.id, new_handle, tuple(phys))
            if checker is not None:
                checker.note_insert(new_handle, tuple(phys))
            count += 1
        return ResultSet([], [], affected=count)

    def _exec_delete(self, stmt: ast.DeleteStmt) -> ResultSet:
        info, _ = self._table_for(stmt.table)
        txn = self._ensure_txn()
        try:
            total = 0
            for child, _store in self._partition_children(info):
                if txn.pessimistic:
                    snap, mask, _, handles = self._pessimistic_scan(
                        child, stmt.table, stmt.where, txn)
                else:
                    snap = txn.snapshot(child.id)
                    mask, _ = self._where_mask(child, stmt.table,
                                               stmt.where, snap)
                    handles = snap.handles()[mask]
                for h in handles:
                    txn.delete_row(child.id, int(h))
                total += len(handles)
            return ResultSet([], [], affected=total)
        finally:
            txn.stmt_read_ts = None

    def _unique_lock_keys(self, info: TableInfo, enc: tuple) -> list[bytes]:
        """Lock-only keys representing the unique-index entries a new row
        would claim (NULL-bearing keys skipped — MySQL allows repeated
        NULLs in unique indexes). Physical values (dictionary codes) are
        per-store deterministic, so equal SQL values from any session
        encode to equal lock keys."""
        from ..kv import tablecodec

        keys: list[bytes] = []
        for ix in info.indices:
            if not (ix.unique or ix.primary):
                continue
            vals = [enc[off] for off in ix.col_offsets]
            if any(v is None for v in vals):
                continue
            keys.append(tablecodec.index_key(info.id, ix.id, vals))
        return keys

    def _pessimistic_scan(self, info: TableInfo, table: ast.TableName,
                          where: Optional[ast.Expr], txn):
        """Lock the matching rows at a fresh for_update_ts, retrying the
        scan whenever a newer commit invalidates it (reference:
        executor/adapter.go:533 handlePessimisticDML + :623 lock-error
        retry). Leaves txn.stmt_read_ts at the locked for_update_ts so
        every read this statement makes sees the locked versions; the
        caller clears it when the statement ends."""
        from ..kv import tablecodec
        from ..kv.backoff import (BO_TXN_CONFLICT, Backoffer,
                                  BackoffExhausted)
        from ..kv.mvcc import WriteConflictError as KVConflict

        import time as _time

        from ..kv.backoff import BO_TXN_LOCK

        timeout = float(
            self._sysvar_value("innodb_lock_wait_timeout") or 50)
        bo = Backoffer(budget_ms=int(timeout * 1000))
        while True:
            ts = txn.refresh_for_update_ts()
            txn.stmt_read_ts = ts
            snap = txn.snapshot(info.id)
            mask, ev = self._where_mask(info, table, where, snap)
            handles = snap.handles()[mask]
            keys = [tablecodec.record_key(info.id, int(h))
                    for h in handles]
            t0 = _time.monotonic()
            try:
                self.storage.pessimistic_lock_keys(txn, keys, timeout)
                return snap, mask, ev, handles
            except KVConflict:
                try:
                    # time blocked on foreign locks counts against the
                    # SAME budget, or a contended statement could run
                    # far beyond innodb_lock_wait_timeout
                    waited = _time.monotonic() - t0
                    if waited > 0.001:
                        bo.charge(BO_TXN_LOCK, waited)
                    bo.sleep(BO_TXN_CONFLICT)  # then rescan fresh
                except BackoffExhausted as e:
                    raise err_wrap(SQLError, e) from None
            except (Storage.DeadlockError,
                    Storage.LockWaitTimeout) as e:
                raise err_wrap(SQLError, e) from None

    def _where_mask(self, info: TableInfo, table: ast.TableName,
                    where: Optional[ast.Expr], snap):
        n = snap.num_visible_rows
        cols = []
        dicts = []
        for off in range(info.num_columns):
            col = snap.column(off)
            cols.append((col.data, col.validity))
            dicts.append(col.dictionary)
        ev = NumpyEval(cols, dicts, n)
        if where is None:
            return np.ones(n, dtype=bool), ev
        builder = PlanBuilder(self.catalog, self.current_db)
        scan = builder._build_scan(table)
        cond = builder.resolve(where, scan.schema)
        v, vl = ev.eval(cond)
        return _truthy(np.asarray(v)) & vl, ev

    def _eval_value(self, e: ast.Expr) -> Any:
        """Evaluate an INSERT VALUES expression (constants + simple arith)."""
        builder = PlanBuilder(self.catalog, self.current_db)
        from ..plan.schema import PlanSchema
        pe = builder.resolve(e, PlanSchema([]))
        from ..plan.expr import Const
        if not isinstance(pe, Const):
            raise SQLError("non-constant INSERT value")
        if pe.value is None:
            return None
        if pe.ftype.is_decimal:
            return Decimal(pe.value, pe.ftype.scale)
        if pe.ftype.kind == TypeKind.DATE:
            from ..types.value import decode_date
            return decode_date(pe.value)
        if pe.ftype.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
            from ..types.value import decode_datetime
            return decode_datetime(pe.value)
        return pe.value

    def _insert_columns(self, info: TableInfo,
                        names: Optional[list[str]]) -> list[int]:
        if names is None:
            return list(range(info.num_columns))
        out = []
        for n in names:
            c = info.column_by_name(n)
            if c is None:
                raise SQLError(f"unknown column {n}",
                               errno=ER_BAD_FIELD)
            out.append(c.offset)
        return out

    def _complete_row(self, info: TableInfo, col_order: list[int],
                      values: list[Any], store: TableStore) -> list[Any]:
        full: list[Any] = [None] * info.num_columns
        provided = set()
        for off, v in zip(col_order, values):
            full[off] = v
            provided.add(off)
        for c in info.columns:
            if c.offset in provided:
                continue
            if c.default is not None:
                full[c.offset] = c.default
            elif c.auto_increment:
                v = store.alloc_handle()
                full[c.offset] = v
                # LAST_INSERT_ID: first auto-generated value of the
                # statement (reference: builtin_info.go lastInsertID)
                if self._stmt_auto_id is None:
                    self._stmt_auto_id = v
            elif not c.nullable:
                raise SQLError(f"column {c.name} cannot be null",
                               errno=ER_BAD_NULL)
        for c in info.columns:
            if full[c.offset] is None and not c.nullable and \
                    not c.auto_increment:
                raise SQLError(f"column {c.name} cannot be null",
                               errno=ER_BAD_NULL)
        return full

    def _row_handle(self, info: TableInfo, row: list[Any],
                    store: TableStore) -> int:
        if info.pk_handle_offset is not None:
            v = row[info.pk_handle_offset]
            if v is None:
                v = store.alloc_handle()
                row[info.pk_handle_offset] = v
            handle = int(v)
            store.note_handle(handle)
            return handle
        return store.alloc_handle()

    # ==================== DDL ====================
    def _exec_create_table(self, stmt: ast.CreateTableStmt) -> ResultSet:
        db = stmt.table.db or self.current_db
        columns: list[ColumnInfo] = []
        pk_offsets: list[int] = []
        for off, cd in enumerate(stmt.columns):
            ft = cd.ftype
            if cd.not_null or cd.primary_key:
                ft = FieldType(ft.kind, ft.flen, ft.scale, nullable=False)
            default = None
            if cd.default is not None:
                c = _literal_const(cd.default)
                default = self._decode_default(c, ft)
            col = ColumnInfo(
                id=self.catalog.alloc_id(),
                name=cd.name,
                ftype=ft,
                offset=off,
                default=default,
                is_primary=cd.primary_key,
                auto_increment=cd.auto_increment,
            )
            columns.append(col)
            if cd.primary_key:
                pk_offsets.append(off)
        indices: list[IndexInfo] = []
        for off, cd in enumerate(stmt.columns):
            if getattr(cd, "unique", False) and not cd.primary_key:
                indices.append(IndexInfo(self.catalog.alloc_id(),
                                         cd.name, [off], True, False))
        for idef in stmt.indices:
            offs = []
            for name in idef.columns:
                hit = next((c for c in columns
                            if c.name.lower() == name.lower()), None)
                if hit is None:
                    raise SQLError(f"index column {name} not found")
                offs.append(hit.offset)
            if idef.primary:
                pk_offsets.extend(offs)
                for o in offs:
                    columns[o].is_primary = True
                    ftp = columns[o].ftype
                    columns[o].ftype = FieldType(ftp.kind, ftp.flen, ftp.scale,
                                                 nullable=False)
            indices.append(IndexInfo(self.catalog.alloc_id(),
                                     idef.name or f"idx_{len(indices)}",
                                     offs, idef.unique, idef.primary))
        pk_handle = None
        if len(pk_offsets) == 1 and columns[pk_offsets[0]].ftype.is_integer:
            pk_handle = pk_offsets[0]
        elif pk_offsets and not any(ix.primary for ix in indices):
            # non-handle pk (string/composite declared at column level):
            # enforce via a primary unique index
            indices.append(IndexInfo(self.catalog.alloc_id(), "PRIMARY",
                                     list(pk_offsets), True, True))
        partition = None
        if stmt.partition_by is not None:
            partition = self._build_partition_info(
                stmt.partition_by, columns, indices, pk_handle)
        # FK metadata: stored and surfaced, not enforced — exactly the
        # v5.0 reference's behavior (ddl/foreign_key.go builds FKInfo;
        # no runtime checks; foreign_key_checks defaults off)
        from ..catalog.schema import FKInfo
        fk_infos = []
        for i, fk in enumerate(getattr(stmt, "foreign_keys", []) or []):
            offs = []
            for cn in fk.columns:
                hit = next((c for c in columns
                            if c.name.lower() == cn.lower()), None)
                if hit is None:
                    raise SQLError(f"unknown column {cn} in foreign key",
                               errno=ER_BAD_FIELD)
                offs.append(hit.offset)
            if len(offs) != len(fk.ref_columns):
                raise SQLError(
                    "foreign key column count mismatch")
            fk_infos.append(FKInfo(
                fk.name or f"fk_{stmt.table.name}_{i + 1}", offs,
                (fk.ref_table.db or db).lower(), fk.ref_table.name,
                list(fk.ref_columns), fk.on_delete, fk.on_update))
        info = TableInfo(
            id=self.catalog.alloc_id(),
            name=stmt.table.name,
            columns=columns,
            indices=indices,
            pk_handle_offset=pk_handle,
            partition=partition,
            foreign_keys=fk_infos,
        )
        try:
            created = self.catalog.add_table(db, info, stmt.if_not_exists)
        except KeyError as e:
            raise err_wrap(SQLError, e) from None
        if created:
            self.storage.register_table(info)
        return ResultSet([], [])

    def _build_partition_info(self, pb, columns, indices, pk_handle):
        """Validate + build PartitionInfo (reference: ddl/partition.go
        checkPartitionByHash/Range + checkPartitionKeysConstraint — every
        unique key must include the partition column)."""
        from ..catalog.schema import PartitionDef, PartitionInfo

        col = next((c for c in columns
                    if c.name.lower() == pb.column.lower()), None)
        if col is None:
            raise SQLError(f"unknown partition column {pb.column}")
        ft = col.ftype
        if not (ft.is_integer or ft.kind == TypeKind.DATE):
            raise SQLError(
                "partition column must be integer or DATE typed")
        for ix in indices:
            if (ix.unique or ix.primary) and \
                    col.offset not in ix.col_offsets:
                raise SQLError(
                    "A UNIQUE INDEX must include all columns in the "
                    "table's partitioning function")
        if pk_handle is not None and pk_handle != col.offset:
            raise SQLError(
                "A PRIMARY KEY must include all columns in the "
                "table's partitioning function")
        defs: list = []
        if pb.kind == "hash":
            for i in range(pb.count):
                defs.append(PartitionDef(f"p{i}", self.catalog.alloc_id()))
        else:
            prev = None
            for name, less_than in pb.ranges:
                if any(d.name.lower() == name.lower() for d in defs):
                    raise SQLError(f"duplicate partition name {name}")
                if prev is not None and prev[1] is None:
                    raise SQLError("MAXVALUE must be the last partition")
                if less_than is not None and prev is not None and \
                        prev[1] is not None and less_than <= prev[1]:
                    raise SQLError(
                        "VALUES LESS THAN must be strictly increasing")
                defs.append(PartitionDef(name, self.catalog.alloc_id(),
                                         less_than))
                prev = (name, less_than)
        return PartitionInfo(pb.kind, col.offset, defs)

    def _decode_default(self, c, ft: FieldType) -> Any:
        if c.value is None:
            return None
        if ft.is_decimal and c.ftype.is_decimal:
            return Decimal(c.value, c.ftype.scale)
        if ft.is_string or ft.is_temporal:
            return c.value
        return c.value

    def _exec_drop_table(self, stmt: ast.DropTableStmt) -> ResultSet:
        for tn in stmt.tables:
            db = tn.db or self.current_db
            try:
                info = self.catalog.drop_table(db, tn.name, stmt.if_exists)
            except KeyError as e:
                raise err_wrap(SQLError, e) from None
            if info is not None:
                part = getattr(info, "partition", None)
                ids = [d.id for d in part.defs] if part is not None \
                    else [info.id]
                for tid in ids:
                    self.storage.unregister_table(tid)
                    self.storage.stats.drop_table(tid)
                    self.storage.destroy_table_data(tid)
        return ResultSet([], [])

    # ==================== sequences ====================
    def _exec_create_sequence(self, stmt: ast.CreateSequenceStmt
                              ) -> ResultSet:
        from ..catalog.schema import SequenceInfo

        db = stmt.name.db or self.current_db
        schema = self.catalog.schema(db)
        seqs = getattr(schema, "sequences", None)
        if seqs is None:  # catalogs pickled before the field existed
            schema.sequences = seqs = {}
        key = stmt.name.name.lower()
        if key in seqs or self.catalog.try_table(db, stmt.name.name):
            if stmt.if_not_exists:
                return ResultSet([], [])
            raise SQLError(f"table exists: {db}.{stmt.name.name}",
                           errno=ER_TABLE_EXISTS)
        seqs[key] = SequenceInfo(
            id=self.catalog.alloc_id(), name=stmt.name.name,
            start=stmt.start, increment=stmt.increment,
            min_value=stmt.min_value, max_value=stmt.max_value,
            cycle=stmt.cycle, next_value=stmt.start)
        self.catalog.bump_version()
        return ResultSet([], [])

    def _exec_drop_sequence(self, stmt: ast.DropSequenceStmt) -> ResultSet:
        for tn in stmt.names:
            db = tn.db or self.current_db
            schema = self.catalog.schema(db)
            seqs = getattr(schema, "sequences", {}) or {}
            if tn.name.lower() not in seqs:
                if stmt.if_exists:
                    continue
                raise SQLError(f"unknown table: {db}.{tn.name}",
                               errno=ER_NO_SUCH_TABLE)
            del seqs[tn.name.lower()]
        self.catalog.bump_version()
        return ResultSet([], [])

    def _sequence_for(self, node) -> "SequenceInfo":
        if not isinstance(node, ast.ColumnRef):
            raise SQLError("sequence functions take a sequence name")
        db = node.table or self.current_db
        schema = self.catalog.schema(db)
        seq = (getattr(schema, "sequences", {}) or {}).get(
            node.name.lower())
        if seq is None:
            raise SQLError(f"unknown sequence: {db}.{node.name}")
        return seq

    def _exec_truncate(self, stmt: ast.TruncateTableStmt) -> ResultSet:
        info, _ = self._table_for(stmt.table)
        part = getattr(info, "partition", None)
        ids = [d.id for d in part.defs] if part is not None else [info.id]
        for tid in ids:
            self.storage.unregister_table(tid)
            self.storage.stats.drop_table(tid)
            self.storage.destroy_table_data(tid)
        self.storage.register_table(info)
        return ResultSet([], [])

    # ==================== EXPLAIN / SHOW ====================
    def _wait_profile_cell(self) -> str:
        """Statement-level typed wait profile for the EXPLAIN ANALYZE
        header row. EXPLAIN ANALYZE itself runs under the statement's
        wait ledger (installed by `_execute_observed`), so the active
        ledger holds exactly the waits the analyzed execution accrued
        so far. Empty when the wait profile is disabled."""
        from .. import obs
        led = obs.active_wait_ledger()
        if led is None or not led.totals:
            return ""
        return obs.fmt_waits(led.totals)

    def _exec_explain(self, stmt: ast.ExplainStmt) -> ResultSet:
        if not isinstance(stmt.target, (ast.SelectStmt, ast.SetOpStmt)):
            raise SQLError("EXPLAIN supports SELECT only for now")
        # bindings apply to the displayed plan too — EXPLAIN must show
        # what would actually run (reference: bindinfo matched in the
        # common optimize path, planner/optimize.go)
        import re
        m = re.match(r"(?is)\s*explain\s+(?:analyze\s+)?(.*)$",
                     self._raw_sql or "")
        if m and m.group(1):
            prev = self._binding_match_sql
            self._binding_match_sql = m.group(1)
            try:
                stmt.target = self._apply_binding(stmt.target)
            finally:
                self._binding_match_sql = prev
        if stmt.analyze:
            # point statements execute the fast path and show it AS the
            # plan — the bypass decision is the plan, like the routed
            # replica reads below (reference: Point_Get in EXPLAIN)
            rs = self._explain_analyze_point(
                stmt.target, m.group(1) if m else None)
            if rs is not None:
                return rs
        plan = self._plan(stmt.target)
        if not stmt.analyze:
            lines = explain_plan(plan)
            return ResultSet(["plan"], [(line,) for line in lines])
        # EXPLAIN ANALYZE: run the plan with per-node runtime stats
        # (reference: util/execdetails RuntimeStatsColl feeding the
        # explain output, executor/executor.go:262)
        from .. import obs
        from ..plan.physical import explain_nodes

        # follower read tier: when the router would serve this read
        # from a replica, EXPLAIN ANALYZE executes THAT — the routing
        # decision is the plan (engine column `replica@host:port`);
        # per-node device stats belong to the serving replica's own
        # surfaces (its slow log / Top SQL / EXPLAIN ANALYZE)
        from ..rpc import replica as _replica
        routed = _replica.try_route(
            self, stmt.target, m.group(1) if m else None,
            self._has_var_reads(stmt.target),
            expect_cols=len(plan.schema.fields))
        if routed is not None:
            self._commit_implicit()  # release the routing read ts
            rows = []
            for i, line in enumerate(explain_plan(plan)):
                rows.append((
                    line,
                    len(routed.rows) if i == 0 else None,
                    round(routed.wall_ms, 2) if i == 0 else None,
                    f"replica@{routed.addr}" if i == 0 else "",
                    f"replica_read:{routed.wall_ms / 1e3:.3f}"
                    if i == 0 else "", "",
                    self._wait_profile_cell() if i == 0 else ""))
            return ResultSet(["plan", "actRows", "time_ms", "engine",
                              "stages", "mesh", "wait_profile"], rows)

        coll = obs.RuntimeStatsColl()

        def run():
            ctx = self._exec_ctx(stats=coll)
            try:
                return run_physical(plan, ctx)
            finally:
                ctx.close()

        self._run_in_txn(run)
        wp = self._wait_profile_cell()
        rows = []
        for i, (node, line) in enumerate(explain_nodes(plan)):
            st = coll.for_plan(node)
            if st is None:
                rows.append((line, None, None, "", "", "",
                             wp if i == 0 else ""))
            else:
                rows.append((line, st["rows"],
                             round(st["time"] * 1e3, 2),
                             st["engine"] or "",
                             obs.fmt_stages(st.get("stages")),
                             obs.fmt_mesh(st.get("mesh")),
                             wp if i == 0 else ""))
        return ResultSet(["plan", "actRows", "time_ms", "engine",
                          "stages", "mesh", "wait_profile"], rows)

    def _explain_analyze_point(self, target,
                               bare_sql: Optional[str] = None
                               ) -> Optional[ResultSet]:
        """EXPLAIN ANALYZE of a point-eligible SELECT executes the fast
        path and renders one Point_Get row: engine `point`, the
        plan-cache outcome in the stages cell — fast-path coverage is
        observable exactly where operators already look. `bare_sql`
        (the target's own text, stripped of the EXPLAIN prefix) keys
        the SAME cache entry the bare statement uses, so a steady hit
        reports as a hit here too."""
        if not isinstance(target, ast.SelectStmt) or \
                not self._fast_path_eligible(target):
            return None
        import time as _time

        from .. import obs
        from ..plan import fastpath
        prev_key = self._plan_cache_key
        self._plan_cache_key = bare_sql or prev_key
        try:
            with obs.stage("fast_plan"):
                fp = self._fast_plan_cached(target)
        finally:
            self._plan_cache_key = prev_key
        if fp is None:
            return None
        obs.note_engine("point")
        t0 = _time.perf_counter()
        rs = fastpath.execute(self, fp)
        dt = (_time.perf_counter() - t0) * 1e3
        cache = "hit" if self.last_plan_from_cache else "miss"
        key = f"handle:{fp.handle}" if fp.handle is not None \
            else f"key:{fp.index.name}"
        row = (f"Point_Get_1(table:{fp.info.name}, {key})",
               len(rs.rows), round(dt, 3), "point",
               f"plan_cache:{cache}", "", self._wait_profile_cell())
        return ResultSet(["plan", "actRows", "time_ms", "engine",
                          "stages", "mesh", "wait_profile"], [row])

    def _exec_trace(self, stmt: ast.TraceStmt) -> ResultSet:
        """TRACE <select>: execute with span accounting and return the
        span tree (reference: executor/trace.go rendering the collected
        spans; per-operator rows come from the same runtime-stats
        collector EXPLAIN ANALYZE uses)."""
        from .. import obs
        from ..plan.physical import explain_nodes

        target = stmt.target
        if not isinstance(target, (ast.SelectStmt, ast.SetOpStmt,
                                   ast.InsertStmt, ast.UpdateStmt,
                                   ast.DeleteStmt)):
            raise SQLError("TRACE supports SELECT and DML statements")
        is_select = isinstance(target, (ast.SelectStmt, ast.SetOpStmt))
        coll = obs.RuntimeStatsColl()
        plan = None
        try:
            raw = self._sysvar_value("tidb_trace_span_cap")
            cap = obs.TRACE_SPAN_CAP if raw is None or raw == "" \
                else max(int(raw), 1)  # 1 = root only, rest dropped
        except (TypeError, ValueError, SQLError):
            cap = obs.TRACE_SPAN_CAP
        with obs.SpanCollector("session.run", cap=cap) as spans:
            if is_select:
                with obs.span("session.prepare"):
                    target = self._maybe_bind_vars(target)
                    self._refresh_infoschema(target)
                with obs.stage("plan_build", span_name="planner.optimize"):
                    plan = self._plan(target)

                def run():
                    ctx = self._exec_ctx(stats=coll)
                    try:
                        return run_physical(plan, ctx)
                    finally:
                        ctx.close()

                with obs.span("executor.run"):
                    self._run_in_txn(run)
            else:
                with obs.span("executor.dml"):
                    self._execute_stmt(target)
        rows: list[tuple] = spans.rows()
        if plan is not None:
            for node, line in explain_nodes(plan):
                st = coll.for_plan(node)
                dur = round(st["time"] * 1e3, 3) if st else None
                rows.append((f"  {line}", None, dur))
        # keep the tree reachable from the status port
        self.storage.obs.record_trace(self.conn_id or 0, rows)
        return ResultSet(["operation", "start_ms", "duration_ms"], rows)

    def _exec_show(self, stmt: ast.ShowStmt) -> ResultSet:
        if stmt.kind == "TABLES":
            schema = self.catalog.schema(self.current_db)
            names = sorted(t.name for t in schema.tables.values()
                           if _like_match(stmt.pattern, t.name))
            return ResultSet([f"Tables_in_{self.current_db}"],
                             [(n,) for n in names])
        if stmt.kind == "DATABASES":
            return ResultSet(
                ["Database"],
                [(s.name,) for s in sorted(self.catalog.schemas.values(),
                                           key=lambda s: s.name)])
        if stmt.kind == "CREATE_TABLE":
            assert stmt.target is not None
            info, _ = self._table_for(stmt.target)
            lines = [
                f"`{c.name}` {c.ftype!r}"
                f"{'' if c.ftype.nullable else ' NOT NULL'}"
                for c in info.columns
            ]
            for fk in getattr(info, "foreign_keys", []) or []:
                cols_s = ", ".join(f"`{info.columns[o].name}`"
                                   for o in fk.col_offsets)
                refs = ", ".join(f"`{c}`" for c in fk.ref_cols)
                lines.append(
                    f"CONSTRAINT `{fk.name}` FOREIGN KEY ({cols_s}) "
                    f"REFERENCES `{fk.ref_table}` ({refs})"
                    + (f" ON DELETE {fk.on_delete}"
                       if fk.on_delete != "RESTRICT" else "")
                    + (f" ON UPDATE {fk.on_update}"
                       if fk.on_update != "RESTRICT" else ""))
            body = ",\n  ".join(lines)
            ddl = f"CREATE TABLE `{info.name}` (\n  {body}\n)"
            return ResultSet(["Table", "Create Table"], [(info.name, ddl)])
        if stmt.kind == "VARIABLES":
            vals = dict(self.storage.sysvars.all_globals())
            if stmt.scope != "GLOBAL":
                vals.update({k: v for k, v in self.vars.items()})
            rows = [(k, "" if v is None else str(v))
                    for k, v in sorted(vals.items())
                    if _like_match(stmt.pattern, k)]
            return ResultSet(["Variable_name", "Value"], rows)
        if stmt.kind == "STATUS":
            rows = [("Uptime", "0"), ("Threads_connected", "1"),
                    ("Questions", str(self._stmt_seq)),
                    ("Ssl_cipher", "")]
            return ResultSet(["Variable_name", "Value"],
                             [r for r in rows
                              if _like_match(stmt.pattern, r[0])])
        if stmt.kind == "GRANTS":
            target = stmt.pattern or self.user or "root"
            rows = []
            for p, db, tbl in self.storage.privileges.grants_for(target):
                obj = "*.*" if db == "*" and tbl == "*" else f"{db}.{tbl}"
                rows.append((f"GRANT {p} ON {obj} TO '{target}'@'%'",))
            by_scope: dict[tuple, list[str]] = {}
            for p, db, tbl, col in \
                    self.storage.privileges.col_grants_for(target):
                by_scope.setdefault((p, db, tbl), []).append(col)
            for (p, db, tbl), cols in sorted(by_scope.items()):
                rows.append((
                    f"GRANT {p} ({', '.join(cols)}) ON {db}.{tbl} "
                    f"TO '{target}'@'%'",))
            roles = sorted(self.storage.privileges.roles_of(target))
            if roles:
                rs = ", ".join(f"'{r}'@'%'" for r in roles)
                rows.append((f"GRANT {rs} TO '{target}'@'%'",))
            return ResultSet([f"Grants for {target}@%"], rows)
        if stmt.kind == "BINDINGS":
            recs = self.storage.bindings.all() if stmt.scope == "GLOBAL" \
                else list(self.session_bindings.values())
            cols = ["Original_sql", "Bind_sql", "Default_db", "Status",
                    "Create_time", "Update_time", "Charset", "Collation",
                    "Source"]
            return ResultSet(cols, [
                (r["original_sql"], r["bind_sql"], r["default_db"],
                 r["status"], r["create_time"], r["update_time"],
                 "utf8mb4", "utf8mb4_bin", "manual") for r in recs])
        if stmt.kind == "PROCESSLIST":
            provider = getattr(self.storage, "processlist", None)
            if provider is not None:
                # the provider's rows carry (.., mem_max, spill_count)
                # tails for information_schema.processlist; the SHOW
                # surface keeps MySQL's classic eight columns
                rows = [tuple(r[:8]) for r in provider()]
                # MySQL: without the PROCESS privilege, only your own
                # connections' rows are visible
                if self.user is not None and not (
                        self.storage.privileges.check(
                            self.user, "PROCESS", "*", "*",
                            roles=self.active_roles)):
                    rows = [r for r in rows if r[1] == self.user]
            else:
                # embedded session: no wire server; list this session
                import time as _t
                info = self.in_flight_sql
                t = int(_t.time() - self.in_flight_since) \
                    if info and self.in_flight_since else 0
                rows = [(getattr(self, "conn_id", 0),
                         self.user or "root", "localhost",
                         self.current_db, "Query", t, "executing",
                         info)]
            return ResultSet(
                ["Id", "User", "Host", "db", "Command", "Time",
                 "State", "Info"], rows)
        if stmt.kind == "TABLE_STATUS":
            schema = self.catalog.schema(self.current_db)
            rows = []
            for t in sorted(schema.tables.values(), key=lambda t: t.name):
                if not _like_match(stmt.pattern, t.name):
                    continue
                from ..catalog.infoschema import _store_rows
                part = getattr(t, "partition", None)
                ids = [d.id for d in part.defs] if part else [t.id]
                nrows = sum(_store_rows(self.storage, tid)
                            for tid in ids)
                rows.append((t.name, "InnoDB", 10, "Fixed", nrows, 0,
                             0, 0, 0, 0, None, None, None, None,
                             "utf8mb4_bin", None,
                             "partitioned" if part else "", ""))
            for v in sorted(getattr(schema, "views", {}).values(),
                            key=lambda v: v.name):
                if _like_match(stmt.pattern, v.name):
                    rows.append((v.name, None, None, None, None, None,
                                 None, None, None, None, None, None,
                                 None, None, None, None, None, "VIEW"))
            return ResultSet(
                ["Name", "Engine", "Version", "Row_format", "Rows",
                 "Avg_row_length", "Data_length", "Max_data_length",
                 "Index_length", "Data_free", "Auto_increment",
                 "Create_time", "Update_time", "Check_time", "Collation",
                 "Checksum", "Create_options", "Comment"], rows)
        if stmt.kind == "CHARSET":
            rows = [("utf8mb4", "UTF-8 Unicode", "utf8mb4_bin", 4),
                    ("binary", "Binary pseudo charset", "binary", 1),
                    ("utf8", "UTF-8 Unicode", "utf8_bin", 3)]
            rows = [r for r in rows if _like_match(stmt.pattern, r[0])]
            return ResultSet(
                ["Charset", "Description", "Default collation",
                 "Maxlen"], rows)
        if stmt.kind == "PRIVILEGES":
            from .privileges import PRIVS
            return ResultSet(
                ["Privilege", "Context", "Comment"],
                [(p.title(), "Tables,Databases,Global", "")
                 for p in sorted(PRIVS - {"ALL", "USAGE"})])
        if stmt.kind == "PROFILES":
            # the @@profiling ring (reference: MySQL SHOW PROFILES;
            # entries recorded by the per-statement sampling profiler)
            return ResultSet(
                ["Query_ID", "Duration", "Query"],
                [(p["query_id"], round(p["duration"], 6), p["sql"])
                 for p in self._profiles])
        if stmt.kind == "PROFILE":
            # flamegraph-style table for one profiled statement: frame
            # tree rows with estimated seconds + raw sample counts
            if not self._profiles:
                return ResultSet(["Status", "Duration", "Samples"], [])
            if stmt.pattern:
                qid = int(stmt.pattern)
                ent = next((p for p in self._profiles
                            if p["query_id"] == qid), None)
                if ent is None:
                    raise SQLError(f"no profile for query {qid}")
            else:
                ent = self._profiles[-1]
            prof = ent["profile"]
            rows = [(f_, s, n) for f_, s, n in prof.tree_rows()]
            if not rows:
                rows = [("(no samples: statement finished between "
                         f"ticks at {prof.hz:g}Hz)", 0.0, 0)]
            return ResultSet(["Status", "Duration", "Samples"], rows)
        if stmt.kind == "CREATE_DATABASE":
            name = stmt.pattern or ""
            try:
                self.catalog.schema(name)  # raises if unknown
            except KeyError as e:
                raise err_wrap(SQLError, e) from None
            return ResultSet(
                ["Database", "Create Database"],
                [(name, f"CREATE DATABASE `{name}` /*!40100 DEFAULT "
                  f"CHARACTER SET utf8mb4 */")])
        if stmt.kind == "CREATE_VIEW":
            assert stmt.target is not None
            db = stmt.target.db or self.current_db
            schema = self.catalog.schema(db)
            v = getattr(schema, "views", {}).get(stmt.target.name.lower())
            if v is None:
                raise SQLError(f"Unknown view '{stmt.target.name}'",
                               errno=ER_NO_SUCH_TABLE)
            return ResultSet(
                ["View", "Create View", "character_set_client",
                 "collation_connection"],
                [(v.name,
                  f"CREATE VIEW `{v.name}` AS {v.sql}",
                  "utf8mb4", "utf8mb4_bin")])
        if stmt.kind == "WARNINGS":
            return ResultSet(["Level", "Code", "Message"],
                             [tuple(w) for w in self.warnings])
        if stmt.kind == "ENGINES":
            return ResultSet(
                ["Engine", "Support", "Comment", "Transactions", "XA",
                 "Savepoints"],
                [("InnoDB", "DEFAULT",
                  "TiTPU columnar engine (InnoDB-compatible surface)",
                  "YES", "NO", "NO")])
        if stmt.kind == "COLLATION":
            return ResultSet(
                ["Collation", "Charset", "Id", "Default", "Compiled",
                 "Sortlen"],
                [("utf8mb4_bin", "utf8mb4", 46, "Yes", "Yes", 1)])
        if stmt.kind == "COLUMNS":
            assert stmt.target is not None
            info, _ = self._table_for(stmt.target)
            rows = []
            for c in info.columns:
                key = "PRI" if c.is_primary else ""
                rows.append((c.name, repr(c.ftype),
                             "YES" if c.nullable else "NO", key,
                             None if c.default is None else str(c.default),
                             "auto_increment" if c.auto_increment else ""))
            return ResultSet(
                ["Field", "Type", "Null", "Key", "Default", "Extra"],
                [r for r in rows if _like_match(stmt.pattern, r[0])])
        if stmt.kind == "INDEX":
            assert stmt.target is not None
            info, _ = self._table_for(stmt.target)
            rows = []
            for ix in info.indices:
                if not ix.visible:
                    continue
                for seq, off in enumerate(ix.col_offsets):
                    rows.append((
                        info.name, 0 if ix.unique or ix.primary else 1,
                        ix.name, seq + 1, info.columns[off].name, "A",
                        0, None, None, "", "BTREE", "", ""))
            return ResultSet(
                ["Table", "Non_unique", "Key_name", "Seq_in_index",
                 "Column_name", "Collation", "Cardinality", "Sub_part",
                 "Packed", "Null", "Index_type", "Comment",
                 "Index_comment"], rows)
        if stmt.kind == "SLOW":
            from .. import obs as _obs
            rows = [(e["ts"], e["db"], e["duration_ms"], e["sql"],
                     e.get("plan_digest", ""),
                     _obs.fmt_stages_ms(e.get("stages")),
                     e.get("mem_max", 0), e.get("spill_count", 0),
                     _obs.fmt_waits_ms(e.get("waits")))
                    for e in self.storage.obs.slow_queries()]
            return ResultSet(["Time", "DB", "Duration_ms", "Query",
                              "Plan_digest", "Stages", "Mem_max",
                              "Spill_count", "Wait_profile"], rows)
        if stmt.kind == "METRICS":
            from .. import obs
            rows = []
            # this server's registry plus the process-wide one (copr);
            # the two registries hold disjoint metric families
            text = self.storage.obs.render() + obs.PROCESS_METRICS.render()
            for line in text.splitlines():
                if line.startswith("#") or not line.strip():
                    continue
                name, _, val = line.rpartition(" ")
                rows.append((name, val))
            return ResultSet(["Metric", "Value"], rows)
        raise SQLError(f"unsupported SHOW {stmt.kind}")

    # ==================== helpers ====================
    def _table_for(self, tn: ast.TableName) -> tuple[TableInfo, TableStore]:
        db = tn.db or self.current_db
        try:
            info = self.catalog.table(db, tn.name)
        except KeyError as e:
            raise err_wrap(SQLError, e) from None
        part = getattr(info, "partition", None)
        if part is not None:
            # first partition's store: the shared allocator + shared
            # dictionaries (see Storage._register_partitioned)
            return info, self.storage.table_store(part.defs[0].id)
        return info, self.storage.table_store(info.id)

    def _partition_children(self, info: TableInfo):
        """[(child TableInfo, store)] — a single pair for unpartitioned
        tables, so DML loops uniformly over physical tables."""
        part = getattr(info, "partition", None)
        if part is None:
            return [(info, self.storage.table_store(info.id))]
        return [(Storage.child_table_info(info, d),
                 self.storage.table_store(d.id)) for d in part.defs]


# functions whose value depends on the session/clock: bound to literals
# pre-planning and excluded from the plan cache
_SESSION_FUNCS = frozenset({
    "NOW", "CURRENT_TIMESTAMP", "SYSDATE", "LOCALTIME", "LOCALTIMESTAMP",
    "CURDATE", "CURRENT_DATE", "CURTIME", "CURRENT_TIME",
    "VERSION", "DATABASE", "SCHEMA", "USER", "CURRENT_USER",
    "SESSION_USER", "SYSTEM_USER", "CONNECTION_ID", "UNIX_TIMESTAMP",
    "NEXTVAL", "LASTVAL", "SETVAL",
    "LAST_INSERT_ID", "FOUND_ROWS", "ROW_COUNT", "CURRENT_ROLE",
    "GET_LOCK", "RELEASE_LOCK", "RELEASE_ALL_LOCKS", "IS_FREE_LOCK",
    "IS_USED_LOCK", "TIDB_IS_DDL_OWNER",
})

# reserved words usable WITHOUT parentheses (MySQL niladic functions)
_NILADIC_FUNCS = frozenset({
    "CURRENT_DATE", "CURRENT_TIME", "CURRENT_TIMESTAMP", "CURRENT_USER",
    "LOCALTIME", "LOCALTIMESTAMP",
})


def _parse_load_file(text: str, fmt) -> list[list[Optional[str]]]:
    """One-pass LOAD DATA record/field splitter honoring FIELDS TERMINATED/
    ENCLOSED/ESCAPED BY and LINES TERMINATED BY (reference:
    executor/load_data.go field splitting). esc+'N' as a whole field is
    SQL NULL; escapes are processed before terminator matching, so
    escaped terminator characters stay literal."""
    ft, lt = fmt.field_term, fmt.line_term
    if not ft or not lt:
        # parser rejects these; belt-and-braces against an infinite loop
        # (startswith("") is always True)
        raise ValueError("empty field/line terminator")
    enc, esc = fmt.enclosed, fmt.escaped
    esc_map = {"n": "\n", "t": "\t", "r": "\r", "0": "\0", "Z": "\x1a"}
    rows: list[list[Optional[str]]] = []
    fields: list[Optional[str]] = []
    cur: list[str] = []
    null_pending = False
    enclosure_seen = False  # an empty enclosed field ("") still counts
    i, n = 0, len(text)

    def end_field() -> None:
        nonlocal cur, null_pending, enclosure_seen
        if null_pending and not cur:
            fields.append(None)
        else:
            fields.append("".join(cur))
        cur = []
        null_pending = False
        enclosure_seen = False

    def end_line() -> None:
        nonlocal fields
        end_field()
        rows.append(fields)
        fields = []

    while i < n:
        c = text[i]
        if enc and not cur and not null_pending and c == enc:
            # enclosed field: scan to the closing quote (enc+enc = literal)
            enclosure_seen = True
            i += 1
            while i < n:
                c = text[i]
                if esc and c == esc and i + 1 < n:
                    nxt = text[i + 1]
                    cur.append(esc_map.get(nxt, nxt))
                    i += 2
                    continue
                if c == enc:
                    if i + 1 < n and text[i + 1] == enc:
                        cur.append(enc)
                        i += 2
                        continue
                    i += 1
                    break
                cur.append(c)
                i += 1
            # fall through: next chars should be a terminator
            continue
        if esc and c == esc and i + 1 < n:
            nxt = text[i + 1]
            if nxt == "N" and not cur and not null_pending:
                null_pending = True
            else:
                if null_pending:
                    cur.append("N")
                    null_pending = False
                cur.append(esc_map.get(nxt, nxt))
            i += 2
            continue
        if text.startswith(lt, i):
            end_line()
            i += len(lt)
            continue
        if text.startswith(ft, i):
            end_field()
            i += len(ft)
            continue
        if null_pending:
            cur.append("N")
            null_pending = False
        cur.append(c)
        i += 1
    if cur or fields or null_pending or enclosure_seen:
        end_line()
    return rows


def _load_convert(ft: FieldType, s: Optional[str]) -> Any:
    """LOAD DATA text field -> host value for the insert path. Follows
    MySQL coercions: \\N is NULL; empty numeric/decimal fields load as 0;
    empty temporal fields load as NULL (no zero-date type here);
    fractional text into integer columns rounds half away from zero."""
    if s is None:
        return None
    if ft.is_string or ft.kind == TypeKind.JSON:
        return s
    s = s.strip()
    if ft.kind in (TypeKind.DATE, TypeKind.DATETIME, TypeKind.TIMESTAMP):
        return s if s else None
    if ft.is_decimal:
        return s if s else "0"
    if not s:
        return 0
    try:
        if ft.is_float:
            return float(s)
        try:
            return int(s)
        except ValueError:
            f = float(s)
            return int(f + 0.5) if f >= 0 else -int(-f + 0.5)
    except ValueError:
        raise SQLError(
            f"Truncated incorrect {'DOUBLE' if ft.is_float else 'INTEGER'}"
            f" value: '{s}'",
            errno=ER_TRUNCATED_WRONG_VALUE) from None


def _outfile_text(v) -> str:
    """INTO OUTFILE cell rendering (MySQL text form)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def _like_match(pattern: Optional[str], s: str) -> bool:
    """MySQL LIKE over SHOW output (case-insensitive, %, _ and \\-escapes;
    same conversion the coprocessor's LIKE kernel uses)."""
    if pattern is None:
        return True
    import re

    from ..copr.client import _like_to_regex

    return re.fullmatch(_like_to_regex(pattern), s,
                        re.IGNORECASE) is not None


def _coldef_ftype(cd) -> FieldType:
    """Column-definition type with NOT NULL applied."""
    ft = cd.ftype
    if cd.not_null:
        return FieldType(ft.kind, ft.flen, ft.scale, nullable=False)
    return ft


class _UniqueChecker:
    """Duplicate-key detection for DML writes: checks new rows against the
    snapshot (via index lookups) and against rows written earlier in the
    same statement. Counterpart of the reference's unique-index constraint
    path (table/tables/index.go Create; executor/insert.go dup handling,
    REPLACE semantics in executor/replace.go). NULL keys are never
    duplicates (MySQL unique-index NULL rule)."""

    def __init__(self, info: TableInfo, store: TableStore, txn: Transaction,
                 snap=None) -> None:
        from ..store.index import IndexSearcher

        self.info = info
        self.store = store
        self.uniques = [ix for ix in info.indices if ix.unique or ix.primary]
        need = bool(self.uniques) or info.pk_handle_offset is not None
        self.snap = snap if snap is not None else (
            txn.snapshot(info.id) if need else None)
        self._searchers = [
            IndexSearcher(store, self.snap, ix) for ix in self.uniques
        ] if self.snap is not None else []
        self._seen: list[dict] = [dict() for _ in self.uniques]
        self._deleted: set[int] = set()
        self._inserted: set[int] = set()

    def _key(self, ix: IndexInfo, enc: tuple):
        vals = tuple(enc[off] for off in ix.col_offsets)
        return None if any(v is None for v in vals) else vals

    def conflicts(self, handle: int, enc: tuple,
                  exclude: Optional[int] = None) -> list[int]:
        """Visible handles the new row collides with (pk or unique keys).
        Records the first violated constraint for dup_message."""
        out: list[int] = []
        self.last_dup: Optional[tuple[str, tuple]] = None
        if self.snap is None:
            return out
        if self.info.pk_handle_offset is not None:
            live = handle in self._inserted or (
                self.snap.has_handle(handle) and handle not in self._deleted)
            if live and handle != exclude:
                out.append(handle)
                self.last_dup = ("PRIMARY", (handle,))
        for ix, searcher, seen in zip(self.uniques, self._searchers,
                                      self._seen):
            key = self._key(ix, enc)
            if key is None:
                continue
            hits: list[int] = []
            h2 = seen.get(key)
            if h2 is not None and h2 != exclude and h2 not in self._deleted:
                hits.append(h2)
            for h in searcher.eq(key):
                h = int(h)
                # _inserted handles were rewritten this statement: their
                # snapshot index entries are stale (e.g. a multi-row UPDATE
                # vacating a unique value); their live keys are in `seen`
                if h != exclude and h not in self._deleted and \
                        h not in self._inserted:
                    hits.append(h)
            for h in hits:
                if h not in out:
                    out.append(h)
            if hits and self.last_dup is None:
                name = "PRIMARY" if ix.primary else ix.name
                shown = []  # decode dictionary codes back to strings
                for v, off in zip(key, ix.col_offsets):
                    d = self.store.dictionaries[off]
                    shown.append(d.decode(int(v)) if d is not None else v)
                self.last_dup = (name, tuple(shown))
        return out

    def dup_message(self, handle: int, enc: tuple, conflicts: list[int]) -> str:
        if self.last_dup is None:
            return "Duplicate entry"
        name, key = self.last_dup
        return (f"Duplicate entry '{'-'.join(str(v) for v in key)}' "
                f"for key '{name}'")

    def note_insert(self, handle: int, enc: tuple) -> None:
        self._inserted.add(handle)
        self._deleted.discard(handle)
        for ix, seen in zip(self.uniques, self._seen):
            key = self._key(ix, enc)
            if key is not None:
                seen[key] = handle

    def note_delete(self, handle: int) -> None:
        self._deleted.add(handle)
        self._inserted.discard(handle)


def _np_scalar(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


def _bind_params(node, params: list):
    """Replace ParamMarker nodes with typed literals (in a deep copy)."""
    import dataclasses as _dc

    from ..types.value import Decimal as _Dec

    if isinstance(node, ast.ParamMarker):
        v = params[node.idx]
        if v is None:
            return ast.Literal(None, "null")
        if isinstance(v, bool):
            return ast.Literal(v, "bool")
        if isinstance(v, int):
            return ast.Literal(v, "int")
        if isinstance(v, float):
            return ast.Literal(v, "float")
        if isinstance(v, _Dec):
            return ast.Literal(v, "decimal")
        return ast.Literal(str(v), "string")
    if not _dc.is_dataclass(node):
        return node
    for f in _dc.fields(node):
        v = getattr(node, f.name)
        if _dc.is_dataclass(v) and not isinstance(v, type):
            setattr(node, f.name, _bind_params(v, params))
        elif isinstance(v, list):
            setattr(node, f.name, [
                _bind_params(x, params)
                if _dc.is_dataclass(x) and not isinstance(x, type) else
                (tuple(_bind_params(y, params)
                       if _dc.is_dataclass(y) and not isinstance(y, type)
                       else y for y in x) if isinstance(x, tuple) else x)
                for x in v
            ])
    return node
