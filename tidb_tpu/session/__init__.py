from .session import Session, ResultSet, SQLError

__all__ = ["Session", "ResultSet", "SQLError"]
