from .client import CopClient, CopResult

__all__ = ["CopClient", "CopResult"]
