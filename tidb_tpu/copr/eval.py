"""Columnar expression evaluation as JAX array programs.

Counterpart of the reference's vectorized builtin evaluators (reference:
expression/builtin_*_vec.go over util/chunk columns), redesigned for XLA:
every expression lowers to pure jnp ops over (value, validity) array pairs,
so the whole scan->filter->project->aggregate pipeline fuses into one
compiled program — the role unistore's compiled "closure executor" plays
(reference: store/mockstore/unistore/cophandler/closure_exec.go), but on
the TPU's VPU/MXU instead of a Go interpreter.

Null semantics: SQL three-valued logic via Kleene AND/OR; comparisons and
arithmetic propagate NULL; predicates treat NULL as false at the filter.

String columns arrive as int32 dictionary codes; the compiler resolved all
string constants/predicates to codes or per-code lookup tables host-side
(see client.py), so only integer ops reach the device.
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
import numpy as np

from ..plan.expr import Call, Col, Const, PlanExpr
from ..types.field_type import FieldType, TypeKind

# A column on device: (values, validity). validity True = not NULL.
VV = tuple[jnp.ndarray, jnp.ndarray]


class CompileError(Exception):
    """Raised when an expression can't lower to device ops (host fallback)."""


def _np_dtype_for(ft: FieldType):
    """Device dtype: TPUs have no native 64-bit — integers/decimals/codes
    lower to int32 (exactness guaranteed by the planner's interval
    analysis + limb decomposition), floats to float32."""
    import numpy as np
    host = ft.np_dtype
    if host == np.dtype(np.int64):
        return np.dtype(np.int32)
    if host == np.dtype(np.float64):
        return np.dtype(np.float32)
    return host


def _scale_factor(diff: int) -> int:
    return 10 ** diff


def eval_expr(
    e: PlanExpr,
    columns: list[VV],
    prepared: dict[int, Any],
) -> VV:
    """Lower a resolved expression to jnp ops.

    columns: scan output columns as (value, valid) pairs.
    prepared: compiler-resolved payloads by id(expr-node) — string constants
    as codes, LIKE/IN code tables, etc. (built host-side in client.py).
    """
    if isinstance(e, Col):
        return columns[e.idx]
    if isinstance(e, Const):
        n = columns[0][0].shape[0] if columns else 1
        if e.value is None:
            return (jnp.zeros(n, dtype=_np_dtype_for(e.ftype)),
                    jnp.zeros(n, dtype=bool))
        v = prepared.get(id(e), e.value)
        arr = jnp.full(n, v, dtype=_np_dtype_for(e.ftype))
        return arr, jnp.ones(n, dtype=bool)
    assert isinstance(e, Call)
    return _eval_call(e, columns, prepared)


def _eval_call(e: Call, columns: list[VV], prepared: dict[int, Any]) -> VV:
    op = e.op

    def ev(x: PlanExpr) -> VV:
        return eval_expr(x, columns, prepared)

    # ---- logic (Kleene 3VL) ------------------------------------------------
    if op == "and":
        av, aval = _as_bool(ev(e.args[0]))
        bv, bval = _as_bool(ev(e.args[1]))
        value = av & bv
        known_false = (aval & ~av) | (bval & ~bv)
        valid = (aval & bval) | known_false
        return value & valid, valid
    if op == "or":
        av, aval = _as_bool(ev(e.args[0]))
        bv, bval = _as_bool(ev(e.args[1]))
        value = (av & aval) | (bv & bval)
        known_true = (aval & av) | (bval & bv)
        valid = (aval & bval) | known_true
        return value, valid
    if op == "not":
        av, aval = _as_bool(ev(e.args[0]))
        return (~av) & aval, aval
    if op == "isnull":
        _, aval = ev(e.args[0])
        return ~aval, jnp.ones_like(aval)

    # ---- comparisons -------------------------------------------------------
    if op in ("eq", "ne", "lt", "le", "gt", "ge"):
        a, b = e.args
        av, avl = ev(a)
        bv, bvl = ev(b)
        av, bv = _align_numeric(a, av, b, bv)
        fn: Callable = {
            "eq": jnp.equal, "ne": jnp.not_equal, "lt": jnp.less,
            "le": jnp.less_equal, "gt": jnp.greater, "ge": jnp.greater_equal,
        }[op]
        valid = avl & bvl
        return fn(av, bv) & valid, valid

    # ---- membership / pattern ---------------------------------------------
    if op == "in_values":
        av, avl = ev(e.args[0])
        values = prepared.get(id(e), e.extra)
        hit = jnp.zeros_like(avl)
        for v in values:
            hit = hit | (av == v)
        return hit & avl, avl
    if op == "like":
        # prepared: bool code-table over the dictionary
        av, avl = ev(e.args[0])
        table = prepared[id(e)]
        safe = jnp.clip(av, 0, table.shape[0] - 1)
        return table[safe] & avl, avl
    if op == "dict_lookup":
        # generic per-code lookup (string range predicates, collation compares)
        av, avl = ev(e.args[0])
        table = prepared[id(e)]
        safe = jnp.clip(av, 0, table.shape[0] - 1)
        return table[safe] & avl, avl

    # ---- arithmetic --------------------------------------------------------
    if op in ("add", "sub"):
        a, b = e.args
        av, avl = ev(a)
        bv, bvl = ev(b)
        av, bv = _align_decimal_args(a, av, b, bv, e.ftype)
        out = av + bv if op == "add" else av - bv
        return out, avl & bvl
    if op == "mul":
        a, b = e.args
        av, avl = ev(a)
        bv, bvl = ev(b)
        if e.ftype.is_float:
            av = _to_float(av)
            bv = _to_float(bv)
        # decimal mul: scales add up; no rescale needed
        return av * bv, avl & bvl
    if op == "div":
        a, b = e.args
        av, avl = ev(a)
        bv, bvl = ev(b)
        if not e.ftype.is_float:
            raise CompileError("decimal division is host-only")
        av = _to_float(av)
        bv = _to_float(bv)
        nonzero = bv != 0
        out = jnp.where(nonzero, av / jnp.where(nonzero, bv, 1.0), 0.0)
        return out, avl & bvl & nonzero  # MySQL: x/0 -> NULL
    if op == "intdiv":
        a, b = e.args
        av, avl = ev(a)
        bv, bvl = ev(b)
        nonzero = bv != 0
        safe_b = jnp.where(nonzero, bv, 1)
        q = jnp.abs(av) // jnp.abs(safe_b)
        q = jnp.where((av < 0) != (bv < 0), -q, q)  # trunc toward zero
        return q, avl & bvl & nonzero
    if op == "mod":
        a, b = e.args
        av, avl = ev(a)
        bv, bvl = ev(b)
        nonzero = bv != 0
        safe_b = jnp.where(nonzero, bv, 1)
        r = jnp.abs(av) % jnp.abs(safe_b)
        r = jnp.where(av < 0, -r, r)  # MySQL mod takes dividend sign
        return r, avl & bvl & nonzero
    if op == "neg":
        av, avl = ev(e.args[0])
        return -av, avl
    if op == "abs":
        av, avl = ev(e.args[0])
        return jnp.abs(av), avl

    # ---- control flow ------------------------------------------------------
    if op in ("if",):
        cv, cvl = _as_bool(ev(e.args[0]))
        tv, tvl = _cast_to(ev(e.args[1]), e.args[1].ftype, e.ftype)
        fv, fvl = _cast_to(ev(e.args[2]), e.args[2].ftype, e.ftype)
        cond = cv & cvl
        return jnp.where(cond, tv, fv), jnp.where(cond, tvl, fvl)
    if op == "ifnull":
        av, avl = _cast_to(ev(e.args[0]), e.args[0].ftype, e.ftype)
        bv, bvl = _cast_to(ev(e.args[1]), e.args[1].ftype, e.ftype)
        return jnp.where(avl, av, bv), avl | bvl
    if op == "coalesce":
        out_v, out_vl = _cast_to(ev(e.args[0]), e.args[0].ftype, e.ftype)
        for a in e.args[1:]:
            av, avl = _cast_to(ev(a), a.ftype, e.ftype)
            out_v = jnp.where(out_vl, out_v, av)
            out_vl = out_vl | avl
        return out_v, out_vl
    if op == "case":
        args = e.args
        has_else = len(args) % 2 == 1
        pairs = (len(args) - 1) // 2 if has_else else len(args) // 2
        if has_else:
            out_v, out_vl = _cast_to(ev(args[-1]), args[-1].ftype, e.ftype)
        else:
            n = columns[0][0].shape[0] if columns else 1
            out_v = jnp.zeros(n, dtype=_np_dtype_for(e.ftype))
            out_vl = jnp.zeros(n, dtype=bool)
        decided = jnp.zeros_like(out_vl)
        for i in range(pairs):
            cv, cvl = _as_bool(ev(args[2 * i]))
            tv, tvl = _cast_to(ev(args[2 * i + 1]), args[2 * i + 1].ftype,
                               e.ftype)
            take = (cv & cvl) & ~decided
            out_v = jnp.where(take, tv, out_v)
            out_vl = jnp.where(take, tvl, out_vl)
            decided = decided | take
        return out_v, out_vl

    # ---- temporal ----------------------------------------------------------
    if op in ("year", "month", "day"):
        av, avl = ev(e.args[0])
        if e.args[0].ftype.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
            av = av // 86_400_000_000  # micros -> days
        y, m, d = _civil_from_days(av)
        out = {"year": y, "month": m, "day": d}[op]
        return out.astype(jnp.int32), avl
    if op == "date_add_days":
        av, avl = ev(e.args[0])
        return av + int(e.extra), avl

    # ---- limb splits (wide-aggregate term decomposition, bounds.py) --------
    if op == "shr15":
        av, avl = ev(e.args[0])
        return av >> 15, avl
    if op == "and15":
        av, avl = ev(e.args[0])
        return av & 0x7FFF, avl

    # ---- casts -------------------------------------------------------------
    if op == "cast":
        src = e.args[0]
        return _cast_to(ev(src), src.ftype, e.ftype)

    raise CompileError(f"no device lowering for op {op!r}")


# ---- helpers ----------------------------------------------------------------

def _as_bool(vv: VV) -> VV:
    v, vl = vv
    if v.dtype != jnp.bool_:
        v = v != 0
    return v, vl


def _to_float(v: jnp.ndarray) -> jnp.ndarray:
    if not jnp.issubdtype(v.dtype, jnp.floating):
        return v.astype(jnp.float32)
    return v


def _align_numeric(a: PlanExpr, av, b: PlanExpr, bv):
    """Align operands for comparison: decimal scales, float promotion."""
    at, bt = a.ftype, b.ftype
    if at.is_float or bt.is_float:
        fa = _to_float(av)
        fb = _to_float(bv)
        if at.is_decimal:
            fa = fa / _scale_factor(at.scale)
        if bt.is_decimal:
            fb = fb / _scale_factor(bt.scale)
        return fa, fb
    sa = at.scale if at.is_decimal else 0
    sb = bt.scale if bt.is_decimal else 0
    if sa < sb:
        av = av * _scale_factor(sb - sa)
    elif sb < sa:
        bv = bv * _scale_factor(sa - sb)
    return av, bv


def _align_decimal_args(a: PlanExpr, av, b: PlanExpr, bv, out_t: FieldType):
    """Align for add/sub where the result type dictates the common scale."""
    if out_t.is_float:
        fa, fb = _align_numeric(a, av, b, bv)
        return fa, fb
    if out_t.is_decimal:
        sa = a.ftype.scale if a.ftype.is_decimal else 0
        sb = b.ftype.scale if b.ftype.is_decimal else 0
        s = out_t.scale
        if sa < s:
            av = av * _scale_factor(s - sa)
        if sb < s:
            bv = bv * _scale_factor(s - sb)
        return av, bv
    return av, bv


def _cast_to(vv: VV, src: FieldType, dst: FieldType) -> VV:
    v, vl = vv
    if src.kind == dst.kind and src.scale == dst.scale:
        return v, vl
    if dst.is_float:
        f = _to_float(v)
        if src.is_decimal:
            f = f / _scale_factor(src.scale)
        return f, vl
    if dst.is_decimal:
        if src.is_decimal:
            if src.scale < dst.scale:
                return v * _scale_factor(dst.scale - src.scale), vl
            if src.scale > dst.scale:
                # rescale with half-away rounding
                f = _scale_factor(src.scale - dst.scale)
                q = jnp.abs(v) + f // 2
                q = q // f
                return jnp.where(v < 0, -q, q), vl
            return v, vl
        if src.is_integer:
            return v * _scale_factor(dst.scale), vl
        if src.is_float:
            scaled = v * _scale_factor(dst.scale)
            q = jnp.floor(jnp.abs(scaled) + 0.5)
            return jnp.where(scaled < 0, -q, q).astype(jnp.int32), vl
        raise CompileError(f"cast {src!r} -> {dst!r} not on device")
    if dst.is_integer:
        if src.is_decimal:
            f = _scale_factor(src.scale)
            q = jnp.abs(v) + f // 2
            q = q // f
            return jnp.where(v < 0, -q, q), vl
        if src.is_float:
            q = jnp.floor(jnp.abs(v) + 0.5)
            return jnp.where(v < 0, -q, q).astype(jnp.int32), vl
        if src.is_integer or src.kind == TypeKind.BOOLEAN:
            return v.astype(jnp.int32), vl
    raise CompileError(f"cast {src!r} -> {dst!r} not on device")


def _civil_from_days(z: jnp.ndarray):
    """days-since-epoch -> (year, month, day), branch-free integer math
    (Howard Hinnant's civil_from_days; public-domain algorithm)."""
    z = z.astype(jnp.int32) + 719_468
    era = jnp.where(z >= 0, z, z - 146_096) // 146_097
    doe = z - era * 146_097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def selection_mask(
    conditions: list[PlanExpr],
    columns: list[VV],
    prepared: dict[int, Any],
    base: jnp.ndarray,
) -> jnp.ndarray:
    """Conjunctive filter: NULL condition results are false (SQL WHERE)."""
    mask = base
    for c in conditions:
        v, vl = _as_bool(eval_expr(c, columns, prepared))
        mask = mask & v & vl
    return mask
