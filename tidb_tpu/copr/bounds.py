"""Host-side integer interval analysis over plan expressions.

Mirrors the device lowering in eval.py (including its decimal scale
alignment) to compute a conservative [lo, hi] bound for each integer-valued
expression, from per-column min/max epoch statistics. Two uses:

* staging: an int64 column whose values fit int32 uploads as int32 (halves
  HBM footprint and host->device transfer);
* exact MXU aggregation: the one-hot einsum segment-sum (client.py) splits
  values into 12-bit limbs accumulated in float32; the bound picks the
  minimal limb count that keeps every partial sum exactly representable.

Returns None when a bound can't be established (floats, strings, unknown
ops) — callers then assume the full int64 range.

Reference analog: TiDB's planner tracks field length/decimal for overflow
decisions (types/field_type.go flen/decimal); here the same metadata drives
physical kernel layout instead.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..plan.expr import Call, Col, Const, PlanExpr

Bound = Optional[tuple[int, int]]

_I64 = (-(2**63), 2**63 - 1)


def _scale(diff: int) -> int:
    return 10 ** diff


def _mul_bound(a: tuple[int, int], b: tuple[int, int]) -> tuple[int, int]:
    cands = [a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1]]
    return (min(cands), max(cands))


def _union(a: Bound, b: Bound) -> Bound:
    if a is None or b is None:
        return None
    return (min(a[0], b[0]), max(a[1], b[1]))


def expr_bounds(e: PlanExpr, col_bounds: list[Bound]) -> Bound:
    """[lo, hi] of the expression's device value (scaled-int semantics)."""
    if isinstance(e, Col):
        ft = e.ftype
        if ft.is_float:
            return None
        if ft.is_string:
            return col_bounds[e.idx]  # dict codes
        return col_bounds[e.idx]
    if isinstance(e, Const):
        if e.value is None:
            return (0, 0)
        if isinstance(e.value, (bool, np.bool_)):
            return (0, 1)
        if isinstance(e.value, (int, np.integer)):
            v = int(e.value)
            return (v, v)
        return None
    if not isinstance(e, Call):
        return None

    op = e.op

    def sub(i: int) -> Bound:
        return expr_bounds(e.args[i], col_bounds)

    if op in ("and", "or", "not", "isnull", "eq", "ne", "lt", "le", "gt",
              "ge", "in_values", "like", "dict_lookup"):
        return (0, 1)
    if op in ("add", "sub"):
        a, b = sub(0), sub(1)
        if a is None or b is None:
            return None
        at, bt = e.args[0].ftype, e.args[1].ftype
        if e.ftype.is_decimal:
            sa = at.scale if at.is_decimal else 0
            sb = bt.scale if bt.is_decimal else 0
            s = e.ftype.scale
            if sa < s:
                a = (a[0] * _scale(s - sa), a[1] * _scale(s - sa))
            if sb < s:
                b = (b[0] * _scale(s - sb), b[1] * _scale(s - sb))
        if op == "add":
            return (a[0] + b[0], a[1] + b[1])
        return (a[0] - b[1], a[1] - b[0])
    if op == "mul":
        a, b = sub(0), sub(1)
        if a is None or b is None or e.ftype.is_float:
            return None
        return _mul_bound(a, b)
    if op == "neg":
        a = sub(0)
        return None if a is None else (-a[1], -a[0])
    if op == "abs":
        a = sub(0)
        if a is None:
            return None
        m = max(abs(a[0]), abs(a[1]))
        lo = 0 if a[0] <= 0 <= a[1] else min(abs(a[0]), abs(a[1]))
        return (lo, m)
    if op in ("intdiv", "mod"):
        a, b = sub(0), sub(1)
        if a is None:
            return None
        m = max(abs(a[0]), abs(a[1]))
        return (-m, m)
    if op in ("if",):
        return _union(sub(1), sub(2))
    if op == "ifnull":
        return _union(sub(0), sub(1))
    if op == "coalesce":
        out = sub(0)
        for i in range(1, len(e.args)):
            out = _union(out, sub(i))
        return out
    if op == "case":
        has_else = len(e.args) % 2 == 1
        pairs = (len(e.args) - 1) // 2 if has_else else len(e.args) // 2
        out: Bound = sub(len(e.args) - 1) if has_else else (0, 0)
        for i in range(pairs):
            out = _union(out, expr_bounds(e.args[2 * i + 1], col_bounds))
        return out
    if op == "year":
        return (0, 9999)
    if op == "month":
        return (0, 12)
    if op == "day":
        return (0, 31)
    if op == "date_add_days":
        a = sub(0)
        if a is None:
            return None
        d = int(e.extra)
        return (a[0] + min(d, 0), a[1] + max(d, 0))
    if op == "cast":
        src = e.args[0].ftype
        dst = e.ftype
        a = sub(0)
        if a is None:
            return None
        if dst.is_float:
            return None
        if dst.is_decimal:
            ss = src.scale if src.is_decimal else 0
            if ss < dst.scale:
                f = _scale(dst.scale - ss)
                return (a[0] * f, a[1] * f)
            if ss > dst.scale:
                f = _scale(ss - dst.scale)
                return (a[0] // f - 1, a[1] // f + 1)
            return a
        if dst.is_integer:
            if src.is_decimal:
                f = _scale(src.scale)
                return (a[0] // f - 1, a[1] // f + 1)
            return a
        return None
    return None


def fits_int32(b: Bound) -> bool:
    return b is not None and b[0] >= -(2**31) and b[1] < 2**31


def limbs_for(b: Bound, limb_bits: int = 12, max_limbs: int = 6) -> int:
    """Number of signed limb_bits-bit limbs covering [lo, hi] exactly."""
    if b is None:
        return max_limbs
    need = max(int(abs(b[0])), int(abs(b[1])), 1).bit_length() + 1
    n = -(-need // limb_bits)
    return max(1, min(n, max_limbs))
