"""Host-side integer interval analysis over plan expressions.

Mirrors the device lowering in eval.py (including its decimal scale
alignment) to compute a conservative [lo, hi] bound for each integer-valued
expression, from per-column min/max epoch statistics. Two uses:

* staging: an int64 column whose values fit int32 uploads as int32 (halves
  HBM footprint and host->device transfer);
* exact MXU aggregation: the one-hot einsum segment-sum (client.py) splits
  values into 12-bit limbs accumulated in float32; the bound picks the
  minimal limb count that keeps every partial sum exactly representable.

Returns None when a bound can't be established (floats, strings, unknown
ops) — callers then assume the full int64 range.

Reference analog: TiDB's planner tracks field length/decimal for overflow
decisions (types/field_type.go flen/decimal); here the same metadata drives
physical kernel layout instead.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..plan.expr import Call, Col, Const, PlanExpr

Bound = Optional[tuple[int, int]]

_I64 = (-(2**63), 2**63 - 1)


def _scale(diff: int) -> int:
    return 10 ** diff


def _mul_bound(a: tuple[int, int], b: tuple[int, int]) -> tuple[int, int]:
    cands = [a[0] * b[0], a[0] * b[1], a[1] * b[0], a[1] * b[1]]
    return (min(cands), max(cands))


def _union(a: Bound, b: Bound) -> Bound:
    if a is None or b is None:
        return None
    return (min(a[0], b[0]), max(a[1], b[1]))


def expr_bounds(e: PlanExpr, col_bounds: list[Bound]) -> Bound:
    """[lo, hi] of the expression's device value (scaled-int semantics)."""
    if isinstance(e, Col):
        ft = e.ftype
        if ft.is_float:
            return None
        if ft.is_string:
            return col_bounds[e.idx]  # dict codes
        return col_bounds[e.idx]
    if isinstance(e, Const):
        if e.value is None:
            return (0, 0)
        if isinstance(e.value, (bool, np.bool_)):
            return (0, 1)
        if isinstance(e.value, (int, np.integer)):
            v = int(e.value)
            return (v, v)
        return None
    if not isinstance(e, Call):
        return None

    op = e.op

    def sub(i: int) -> Bound:
        return expr_bounds(e.args[i], col_bounds)

    if op in ("and", "or", "not", "isnull", "eq", "ne", "lt", "le", "gt",
              "ge", "in_values", "like", "dict_lookup"):
        return (0, 1)
    if op in ("add", "sub"):
        a, b = sub(0), sub(1)
        if a is None or b is None:
            return None
        at, bt = e.args[0].ftype, e.args[1].ftype
        if e.ftype.is_decimal:
            sa = at.scale if at.is_decimal else 0
            sb = bt.scale if bt.is_decimal else 0
            s = e.ftype.scale
            if sa < s:
                a = (a[0] * _scale(s - sa), a[1] * _scale(s - sa))
            if sb < s:
                b = (b[0] * _scale(s - sb), b[1] * _scale(s - sb))
        if op == "add":
            return (a[0] + b[0], a[1] + b[1])
        return (a[0] - b[1], a[1] - b[0])
    if op == "mul":
        a, b = sub(0), sub(1)
        if a is None or b is None or e.ftype.is_float:
            return None
        return _mul_bound(a, b)
    if op == "neg":
        a = sub(0)
        return None if a is None else (-a[1], -a[0])
    if op == "abs":
        a = sub(0)
        if a is None:
            return None
        m = max(abs(a[0]), abs(a[1]))
        lo = 0 if a[0] <= 0 <= a[1] else min(abs(a[0]), abs(a[1]))
        return (lo, m)
    if op in ("intdiv", "mod"):
        a, b = sub(0), sub(1)
        if a is None:
            return None
        m = max(abs(a[0]), abs(a[1]))
        return (-m, m)
    if op in ("if",):
        return _union(_branch_bound(e.args[1], e.ftype, col_bounds),
                      _branch_bound(e.args[2], e.ftype, col_bounds))
    if op == "ifnull":
        return _union(_branch_bound(e.args[0], e.ftype, col_bounds),
                      _branch_bound(e.args[1], e.ftype, col_bounds))
    if op == "coalesce":
        out = _branch_bound(e.args[0], e.ftype, col_bounds)
        for i in range(1, len(e.args)):
            out = _union(out, _branch_bound(e.args[i], e.ftype, col_bounds))
        return out
    if op == "case":
        has_else = len(e.args) % 2 == 1
        pairs = (len(e.args) - 1) // 2 if has_else else len(e.args) // 2
        out: Bound = _branch_bound(e.args[-1], e.ftype, col_bounds) \
            if has_else else (0, 0)
        for i in range(pairs):
            out = _union(out, _branch_bound(e.args[2 * i + 1], e.ftype,
                                            col_bounds))
        return out
    if op == "year":
        # YEAR over a bounded date/datetime column narrows to the years
        # its values span (monotone in the day number) — the static
        # [0, 9999] span would push an EXTRACT(YEAR ...) group key past
        # the dense-segment gate (TPC-H Q7/Q8 group by l_year/o_year)
        a = sub(0)
        ft = e.args[0].ftype
        from ..types.field_type import TypeKind as _TK
        if a is not None and ft.kind in (_TK.DATE, _TK.DATETIME,
                                         _TK.TIMESTAMP):
            lo, hi = a
            if ft.kind in (_TK.DATETIME, _TK.TIMESTAMP):
                lo //= 86_400_000_000  # micros -> days
                hi //= 86_400_000_000
            if -1_000_000 <= lo <= hi <= 3_000_000:  # civil range guard
                return (_year_of_day(lo), _year_of_day(hi))
        return (0, 9999)
    if op == "month":
        return (0, 12)
    if op == "day":
        return (0, 31)
    if op == "date_add_days":
        a = sub(0)
        if a is None:
            return None
        d = int(e.extra)
        return (a[0] + min(d, 0), a[1] + max(d, 0))
    if op == "shr15":
        a = sub(0)
        if a is None:
            return None
        return (a[0] >> 15, a[1] >> 15)
    if op == "and15":
        return (0, (1 << 15) - 1)
    if op == "cast":
        src = e.args[0].ftype
        dst = e.ftype
        a = sub(0)
        if a is None:
            return None
        if dst.is_float:
            return None
        if dst.is_decimal:
            ss = src.scale if src.is_decimal else 0
            if ss < dst.scale:
                f = _scale(dst.scale - ss)
                return (a[0] * f, a[1] * f)
            if ss > dst.scale:
                f = _scale(ss - dst.scale)
                return (a[0] // f - 1, a[1] // f + 1)
            return a
        if dst.is_integer:
            if src.is_decimal:
                f = _scale(src.scale)
                return (a[0] // f - 1, a[1] // f + 1)
            return a
        return None
    return None


def _year_of_day(z: int) -> int:
    """days-since-epoch -> civil year (host twin of eval._civil_from_days)."""
    z = int(z) + 719468
    era = (z if z >= 0 else z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    return y + (1 if mp >= 10 else 0)


def _branch_bound(arg: PlanExpr, out_t, col_bounds: list[Bound]) -> Bound:
    """Bound of a control-flow branch AFTER eval's cast to the result type
    (eval.py _cast_to rescales decimals to out_t.scale on device)."""
    b = expr_bounds(arg, col_bounds)
    if b is None:
        return None
    st = arg.ftype
    if out_t.is_decimal:
        ss = st.scale if st.is_decimal else 0
        if ss < out_t.scale:
            f = _scale(out_t.scale - ss)
            return (b[0] * f, b[1] * f)
        if ss > out_t.scale:
            f = _scale(ss - out_t.scale)
            return (b[0] // f - 1, b[1] // f + 1)
    return b


def _cmp_aligned_bounds(a: PlanExpr, b: PlanExpr,
                        col_bounds: list[Bound]) -> tuple[Bound, Bound]:
    """Operand bounds AFTER eval's comparison scale alignment
    (eval.py _align_numeric multiplies the lower-scale side by 10^diff
    on device, which itself must fit int32)."""
    ba = expr_bounds(a, col_bounds)
    bb = expr_bounds(b, col_bounds)
    at, bt = a.ftype, b.ftype
    if at.is_float or bt.is_float:
        return ba, bb  # compared in f32; no integer overflow
    sa = at.scale if at.is_decimal else 0
    sb = bt.scale if bt.is_decimal else 0
    if sa < sb and ba is not None:
        f = _scale(sb - sa)
        ba = (ba[0] * f, ba[1] * f)
    elif sb < sa and bb is not None:
        f = _scale(sa - sb)
        bb = (bb[0] * f, bb[1] * f)
    return ba, bb


def fits_int32(b: Bound) -> bool:
    return b is not None and b[0] >= -(2**31) and b[1] < 2**31


_I31 = (-(2**31), 2**31 - 1)


def _safe(b: Bound) -> bool:
    return b is not None and b[0] >= _I31[0] and b[1] <= _I31[1]


def expr_device_safe(e: PlanExpr, col_bounds: list[Bound]) -> bool:
    """True iff every integer-valued node of the tree fits int32 — i.e.
    int32 device arithmetic computes the expression exactly. Floats and
    booleans are always "safe" (they lower to f32/bool); the caller decides
    whether f32 precision is acceptable for the context."""
    if isinstance(e, Col) or isinstance(e, Const):
        ft = e.ftype
        if ft.is_float or ft.is_string:
            return True
        return _safe(expr_bounds(e, col_bounds))
    assert isinstance(e, Call)
    if e.ftype.is_float:
        return all(expr_device_safe(a, col_bounds) for a in e.args)
    if e.op in ("eq", "ne", "lt", "le", "gt", "ge") and len(e.args) == 2:
        # eval aligns decimal scales by multiplying the lower-scale side
        # by 10^diff ON DEVICE — the scaled operand must itself fit int32
        a, b = e.args
        if not (expr_device_safe(a, col_bounds)
                and expr_device_safe(b, col_bounds)):
            return False
        if a.ftype.is_string or b.ftype.is_string:
            return True
        ba, bb = _cmp_aligned_bounds(a, b, col_bounds)
        if a.ftype.is_float or b.ftype.is_float:
            return True
        return _safe(ba) and _safe(bb)
    if e.op in ("and", "or", "not", "isnull", "in_values", "like",
                "dict_lookup"):
        # the predicate itself is boolean; its operands must be safe
        return all(expr_device_safe(a, col_bounds) for a in e.args)
    if not _safe(expr_bounds(e, col_bounds)):
        return False
    return all(expr_device_safe(a, col_bounds) for a in e.args)


def decompose_terms(
    e: PlanExpr, col_bounds: list[Bound], max_terms: int = 8
) -> Optional[list[tuple[PlanExpr, int]]]:
    """Split an integer expression into [(term, shift)] with
    value == sum(term_i << shift_i), every term int32-safe on device.

    Used for aggregate arguments whose per-row value overflows int32
    (e.g. TPC-H Q1's price*(1-disc)*(1+tax), ~37 bits): the wide factor of
    a product is split at bit 15 (hi = a >> 15 arithmetic, lo = a & 0x7fff,
    a == (hi << 15) + lo in two's complement), distributing the multiply.
    Each term is summed exactly on device (sumexact.py) and the host
    recombines sum(e) = sum_i (sum(term_i) << shift_i) in int64.

    Returns None when no safe decomposition exists (caller falls back to
    the host path). Reference analog: the decimal value words of
    types/mydecimal.go — multi-word exact arithmetic, here driven by
    interval analysis instead of a fixed word count.
    """
    if expr_device_safe(e, col_bounds):
        return [(e, 0)]
    if not isinstance(e, Call):
        return None
    if e.op == "neg":
        inner = decompose_terms(e.args[0], col_bounds, max_terms)
        if inner is None:
            return None
        return [(Call("neg", [t], e.ftype), s) for t, s in inner]
    if e.op != "mul":
        return None
    a, b = e.args
    ba = expr_bounds(a, col_bounds)
    bb = expr_bounds(b, col_bounds)
    if ba is None or bb is None:
        return None
    # put the narrow factor on the right; it must fit 15 bits so that
    # (a & 0x7fff) * b and (a >> 15) * b stay int32-safe after splitting
    amax = max(abs(ba[0]), abs(ba[1]))
    bmax = max(abs(bb[0]), abs(bb[1]))
    if amax < bmax:
        a, b, ba, bb, amax, bmax = b, a, bb, ba, bmax, amax
    if not expr_device_safe(b, col_bounds):
        return None
    wide = decompose_terms(a, col_bounds, max_terms)
    if wide is None:
        return None
    out: list[tuple[PlanExpr, int]] = []
    for ta, sa in wide:
        hi = Call("shr15", [ta], ta.ftype)
        lo = Call("and15", [ta], ta.ftype)
        for part, shift in ((Call("mul", [hi, b], e.ftype), sa + 15),
                            (Call("mul", [lo, b], e.ftype), sa)):
            if expr_device_safe(part, col_bounds):
                out.append((part, shift))
            else:
                sub2 = decompose_terms(part, col_bounds, max_terms)
                if sub2 is None:
                    return None
                out.extend((t, s + shift) for t, s in sub2)
            if len(out) > max_terms:
                return None
    return out


def limbs_for(b: Bound, limb_bits: int = 12, max_limbs: int = 6) -> int:
    """Number of signed limb_bits-bit limbs covering [lo, hi] exactly."""
    if b is None:
        return max_limbs
    need = max(int(abs(b[0])), int(abs(b[1])), 1).bit_length() + 1
    n = -(-need // limb_bits)
    return max(1, min(n, max_limbs))
