"""Multi-key TopN on device: lexicographic composites of bounded keys.

The device TopN kernel ranks rows with jax.lax.top_k over ONE int32
score (64-bit-free device; see copr/client.py module docstring). A
multi-key ORDER BY therefore needs the sort items packed into a single
int32 composite that order-embeds the lexicographic (item1, item2, ...)
comparison. This module is that packing:

* every key gets a dense "goodness" code in [0, card): larger code =
  earlier in the result. ASC keys complement against the upper bound
  (hi - v), DESC keys shift by one (v - lo + 1) — per-key [lo, hi]
  bounds come from the host interval analysis (copr/bounds.py), which
  covers epoch AND overlay values, so one packing serves both batches;
* MySQL NULL ordering (first in ASC, last in DESC) is a dedicated code
  at the top (ASC) or bottom (DESC) of each key's range;
* dictionary-encoded string keys are admitted through an
  order-preserving rank table (Dictionary.sort_ranks — the same ranks
  the host sort uses, so device and host agree exactly, including the
  *_ci collation family); codes are ranks, decode happens on the host
  after the TopN cut;
* the composite is a Horner accumulation code_1·card_2·…·card_n + … ;
  it packs iff Π card_i fits int32 — the gate reason names the width.

Ties on every packed key resolve by ROW ORDER on both paths: top_k is
index-stable and the host merge sort above is a stable lexsort, so the
device candidate set is bit-identical to the host's.

The second half of the module serves the fused join+agg+topn cut: exact
per-candidate aggregate values arrive as 12-bit limb PAIR sums
(sumexact.py layout, value = Σ_t 2^shift_t · Σ_l 2^(12l) · (hi·4096+lo))
and must be compared exactly on a 64-bit-free device. `pair_digits`
re-normalizes them into canonical base-4096 digit vectors (signed head)
whose componentwise comparison IS the numeric comparison, so
jax.lax.sort over the digit operands ranks candidates exactly.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from ..plan.expr import Col, PlanExpr

# composite must stay strictly inside int32: top_k's drop sentinel is
# I32_MIN and every packed score is >= 0
PACK_CAP = 2**31 - 2

# max (term, limb) pair count the digit accumulator admits: per-digit
# partial sums are < pairs * 2^27 before carry normalization and must
# not wrap int32
MAX_DIGIT_PAIRS = 8

N_DIGITS = 7  # base-4096 digits cover the planner's 2^62 sum gate
# (a top limb at weight 2^61 with a sub-limb shift spills one digit up)

_LIMB_BITS = 12
_LIMB_MASK = (1 << _LIMB_BITS) - 1


def plan_pack(items, col_bounds, dicts=None):
    """Pack plan for sort items resolved to the evaluation column space.

    items: [(expr, desc)]; col_bounds: per-column host interval bounds;
    dicts: per-column dictionaries for string keys (None rejects them).
    Returns (specs, None) on success or (None, reason)."""
    from .bounds import expr_bounds, expr_device_safe

    specs: list[dict[str, Any]] = []
    prod = 1
    for e, desc in items:
        if e.ftype.is_float:
            return None, "float key in multi-key TopN is host-side"
        if e.ftype.is_string:
            if not isinstance(e, Col) or dicts is None or \
                    e.idx >= len(dicts) or dicts[e.idx] is None:
                return None, "computed string TopN key is host-side"
            d = dicts[e.idx]
            card = len(d) + 1  # ranks 0..len-1 plus the NULL slot
            if card < 2:
                card = 2
            specs.append({"expr": e, "desc": bool(desc), "kind": "rank",
                          "dict": d, "ci": bool(e.ftype.is_ci),
                          "card": card})
        else:
            if not expr_device_safe(e, col_bounds):
                return None, "TopN key too wide for int32 device"
            b = expr_bounds(e, col_bounds)
            if b is None:
                return None, "unbounded multi-key TopN key"
            lo, hi = int(b[0]), int(b[1])
            card = hi - lo + 2  # value span plus the NULL slot
            specs.append({"expr": e, "desc": bool(desc), "kind": "int",
                          "lo": lo, "hi": hi, "card": card})
        prod *= card
        if prod > PACK_CAP:
            return None, (f"multi-key TopN space {prod} too wide to "
                          "pack int32")
    return specs, None


def pack_sig(specs) -> tuple:
    """Deterministic cache-key payload for a pack plan (dictionaries are
    append-only, so their lengths capture rank-table identity)."""
    out = []
    for s in specs:
        if s["kind"] == "rank":
            out.append(("rank", s["desc"], len(s["dict"]), s["ci"]))
        else:
            out.append(("int", s["desc"], s["lo"], s["hi"]))
    return tuple(out)


def stage_rank_table(prepared: dict, key, d, ci: bool) -> None:
    """Host-side: stash one dictionary's order-preserving rank table in
    `prepared` under `key` for a kernel closure (mirrors the LIKE
    code-table staging in client._prepare_expr). Shared by the packed
    TopN keys and the fused hc cut's string group items."""
    ranks = d.sort_ranks(ci=ci)
    prepared[key] = jnp.asarray(ranks) if len(ranks) \
        else jnp.zeros(1, dtype=jnp.int32)


def stage_rank_tables(specs, prepared: dict) -> None:
    """Resolve every string key's rank table for a pack plan."""
    for i, s in enumerate(specs):
        if s["kind"] == "rank":
            stage_rank_table(prepared, ("topn_rank", i), s["dict"],
                             s["ci"])


def composite_score(specs, cols, prepared, eval_fn) -> jnp.ndarray:
    """int32 composite over the evaluated keys: larger = earlier in the
    result. Garbage lanes (invalid/padded) are clipped before packing so
    jnp.where's eager branches cannot overflow; masked-out rows are the
    caller's job (replace with the drop sentinel)."""
    comp: Optional[jnp.ndarray] = None
    for i, s in enumerate(specs):
        v, vl = eval_fn(s["expr"], cols, prepared)
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int32)
        v = v.astype(jnp.int32)
        if s["kind"] == "rank":
            table = prepared[("topn_rank", i)]
            d_len = table.shape[0]
            r = table[jnp.clip(v, 0, d_len - 1)]
            if s["desc"]:
                code = jnp.where(vl, r + 1, 0)
            else:
                code = jnp.where(vl, jnp.int32(d_len - 1) - r,
                                 jnp.int32(d_len))
        else:
            lo, hi = s["lo"], s["hi"]
            vc = jnp.clip(v, lo, hi)
            if s["desc"]:
                code = jnp.where(vl, vc - jnp.int32(lo) + 1, 0)
            else:
                code = jnp.where(vl, jnp.int32(hi) - vc,
                                 jnp.int32(hi - lo + 1))
        comp = code if comp is None else \
            comp * jnp.int32(s["card"]) + code
    assert comp is not None
    return comp


# ==================== exact limb-pair digit comparison ====================

def digits_fit(sched_entry: dict) -> bool:
    """True when every (term, limb) weight of the schedule entry lands
    inside the N_DIGITS digit window (pair_digits would raise)."""
    if sched_entry["kind"] == "count":
        return True
    for _t, shift, L in sched_entry.get("terms", ()):
        for li in range(L):
            q, r = divmod(_LIMB_BITS * li + int(shift), _LIMB_BITS)
            if (q if r == 0 else q + 1) >= N_DIGITS:
                return False
    return True


def count_pairs(sched_entry: dict) -> int:
    """(term, limb) pair count of one agg schedule entry — the digit
    accumulator's overflow budget (MAX_DIGIT_PAIRS)."""
    if sched_entry["kind"] == "count":
        return 1
    return sum(L for _, _, L in sched_entry.get("terms", ()))


def pair_digits(contribs) -> list[jnp.ndarray]:
    """Exact canonical digits of Σ_t 2^shift_t · value(pairs_t).

    contribs: [(shift, pairs)] with pairs int32[L, 2, n] in the
    sumexact layout (limb l value = hi·4096 + lo, hi ≤ n/4096,
    lo < 2^25, top limb signed). Returns N_DIGITS int32[n] arrays
    MOST-significant first: all but the head are canonical [0, 4096)
    digits, the head keeps the sign — componentwise (head signed, rest
    unsigned) lexicographic comparison equals numeric comparison."""
    digits = [None] * N_DIGITS

    def acc(q, arr):
        if digits[q] is None:
            digits[q] = arr
        else:
            digits[q] = digits[q] + arr

    for shift, pairs in contribs:
        L = pairs.shape[0]
        for li in range(L):
            limb_val = pairs[li, 0] * jnp.int32(1 << _LIMB_BITS) + \
                pairs[li, 1]
            q, r = divmod(_LIMB_BITS * li + int(shift), _LIMB_BITS)
            if q >= N_DIGITS:
                raise ValueError("digit span exceeds N_DIGITS")
            if r == 0:
                acc(q, limb_val)
            else:
                # split the shifted limb across two digits without ever
                # materializing the (int32-overflowing) shifted value
                low = (limb_val & ((1 << (_LIMB_BITS - r)) - 1)) << r
                high = limb_val >> (_LIMB_BITS - r)  # arithmetic: sign
                acc(q, low)
                if q + 1 >= N_DIGITS:
                    raise ValueError("digit span exceeds N_DIGITS")
                acc(q + 1, high)

    shape = None
    for d in digits:
        if d is not None:
            shape = d.shape
            break
    assert shape is not None
    zero = jnp.zeros(shape, jnp.int32)
    carry = zero
    out = []
    for i in range(N_DIGITS):
        t = (digits[i] if digits[i] is not None else zero) + carry
        if i < N_DIGITS - 1:
            out.append(t & _LIMB_MASK)
            carry = t >> _LIMB_BITS  # arithmetic shift: floor carry
        else:
            out.append(t)  # signed head absorbs the final carry
    out.reverse()
    return out


# AVG items: every long-division step computes r*4096 + digit with
# r < cnt, so counts must stay under 2^18 for int32 exactness — the
# executor gates the fused cut on the dispatch's total row count
AVG_CNT_CAP = 1 << 18
_AVG_SCALE_UP = 10_000  # div_precincrement=4: out scale = arg scale + 4
_AVG_DIGITS = N_DIGITS + 2  # |sum| * 10^4 < 2^62 * 10^4 fits 9 digits


def avg_sort_keys(digs, cnt, isnull, desc: bool) -> list[jnp.ndarray]:
    """Ascending-sort operands ordering candidates by EXACTLY the value
    the host's AVG produces: round-half-away-from-zero of
    sum * 10^4 / cnt (types/value.Decimal.div with div_precincrement=4;
    the executor gates fused AVG items on out_scale == arg_scale + 4).

    digs: the SUM's signed-head canonical base-4096 digits (pair_digits,
    MSB first); cnt: int32 counts < AVG_CNT_CAP; isnull: cnt == 0.
    Pipeline, all int32-exact: sign-magnitude split (borrow negation of
    the canonical digits), scale by 10^4 with carry renormalization,
    base-4096 long division by cnt (remainders < cnt keep every step
    under 2^31), half-away rounding on the true remainder, then packed
    sign-applied digit operands with MySQL NULL placement folded into
    the leading operand."""
    neg = digs[0] < 0
    # |sum| digits, LSB-first borrow propagation over the canonical form
    mags_lsb = []
    borrow = jnp.zeros_like(digs[0])
    for i in range(N_DIGITS - 1, 0, -1):
        d = digs[i]
        mags_lsb.append(jnp.where(neg, (-d - borrow) & _LIMB_MASK, d))
        nb = ((d + borrow) > 0).astype(jnp.int32)
        borrow = jnp.where(neg, nb, borrow)
    head = jnp.where(neg, -digs[0] - borrow, digs[0])
    # scale magnitude by 10^4 (digit * 10^4 < 2^26, carries renormalize)
    carry = jnp.zeros_like(head)
    scaled_lsb = []
    for m in mags_lsb + [head]:
        cur = m * jnp.int32(_AVG_SCALE_UP) + carry
        scaled_lsb.append(cur & _LIMB_MASK)
        carry = cur >> _LIMB_BITS
    while len(scaled_lsb) < _AVG_DIGITS:
        scaled_lsb.append(carry & _LIMB_MASK)
        carry = carry >> _LIMB_BITS
    # long division MSB-first: quotient digits < 4096, remainder < cnt
    c = jnp.maximum(cnt, 1)  # cnt == 0 candidates fold via isnull below
    r = jnp.zeros_like(head)
    q_msb = []
    for m in reversed(scaled_lsb):
        t = r * jnp.int32(1 << _LIMB_BITS) + m
        q = t // c
        q_msb.append(q)
        r = t - q * c
    # half away from zero on the magnitude (the host rounds |num|/|den|)
    up = (2 * r >= c).astype(jnp.int32)
    k_lsb = []
    carry = up
    for q in reversed(q_msb):
        cur = q + carry
        k_lsb.append(cur & _LIMB_MASK)
        carry = cur >> _LIMB_BITS
    k_msb = list(reversed(k_lsb))
    is_zero = None
    for d in k_msb:
        z = d == 0
        is_zero = z if is_zero is None else (is_zero & z)
    sgn = jnp.where(is_zero, jnp.int32(0),
                    jnp.where(neg, jnp.int32(-1), jnp.int32(1)))
    # pack digit pairs (24 bits per operand) and apply the sign — for
    # equal signs, negated digits reverse the order componentwise
    packed = []
    i = 0
    while i < len(k_msb):
        if i + 1 < len(k_msb):
            packed.append(k_msb[i] * jnp.int32(1 << _LIMB_BITS)
                          + k_msb[i + 1])
            i += 2
        else:
            packed.append(k_msb[i])
            i += 1
    keys = [sgn] + [sgn * p for p in packed]
    if desc:
        keys = [-k for k in keys]
    sent = jnp.int32(2 if desc else -2)  # NULL first-ASC / last-DESC
    return [jnp.where(isnull, sent, keys[0])] + \
        [jnp.where(isnull, 0, k) for k in keys[1:]]


def digit_sort_keys(digs, desc: bool) -> list[jnp.ndarray]:
    """Ascending-sort keys for a digit vector: packed pairs of canonical
    digits (24 bits per int32 operand — halves the variadic-sort operand
    count, whose XLA compile time is the binding constraint), identity
    for ASC (smaller value first), componentwise reversal for DESC. The
    signed head negates; a packed pair p = a·4096+b complements to
    (2^24-1) - p, which IS the componentwise (4095-a, 4095-b) pair."""
    head, rest = digs[0], list(digs[1:])
    packed = [head]
    widths = []
    i = 0
    while i < len(rest):
        if i + 1 < len(rest):
            packed.append(rest[i] * jnp.int32(1 << _LIMB_BITS)
                          + rest[i + 1])
            widths.append(2 * _LIMB_BITS)
            i += 2
        else:
            packed.append(rest[i])
            widths.append(_LIMB_BITS)
            i += 1
    if not desc:
        return packed
    out = [-head]
    for w, p in zip(widths, packed[1:]):
        out.append(jnp.int32((1 << w) - 1) - p)
    return out
