"""Mesh plane: process-wide device mesh + placement-aware coprocessor.

The multi-chip DATA plane (ROADMAP item 2). MULTICHIP_r05 showed 8
devices visible while every fragment executed on one: the sharded
client (parallel/dist.py) existed but nothing *chose* it, and it
re-placed cached epochs onto the mesh on every dispatch. This module
owns both decisions:

* **MeshPlane** — one per process. Owns the 1-D device mesh
  (`jax.sharding.Mesh` over the `shard` axis, SNIPPETS.md [1]-[3]
  idiom), the placement policy, and the per-storage shared clients.
  Configured from the server's `[mesh]` TOML section or the
  `TIDB_TPU_MESH*` env knobs for embedded use.

* **Placement policy** — per TABLE EPOCH, decided once per plan node
  (executor/engine.py opens `placement_scope` around every dispatch):
  - epochs with >= `shard-threshold-rows` rows shard on the row axis
    (`NamedSharding(mesh, P('shard'))`) — the fact-table side;
  - smaller epochs run the unchanged single-device path — sharding a
    4k-row dimension table across 8 chips would pay collective latency
    for no bandwidth;
  - join build sides REPLICATE (broadcast exchange) unless bigger than
    `replicate-threshold-bytes` or the row threshold, in which case
    they shard by key range and probe rows route over the mesh
    (hash-partition exchange, parallel/exchange.py). This mirrors the
    reference's MPP broadcast-vs-hash-partition election
    (planner/core/fragment.go:45).

* **Persistent sharded residency** — staged columns are PLACED at
  creation (client._place_cols) and the placed arrays are what the
  epoch caches hold, so a sharded epoch stays device-resident across
  queries and sessions; `tidb_device_transfer_bytes` stops paying a
  re-shard per dispatch. DML that folds a new epoch invalidates the
  old epoch's device buffers eagerly (Storage.add_epoch_listener).

* **Graceful fallback** — `mesh.enabled = false`, a single visible
  device, or a below-threshold table all take the EXACT single-device
  path: `client_for` hands out a plain CopClient when the plane is
  inactive, and MeshCopClient in `single` mode dispatches every hook
  to the base implementations.

Results are bit-identical to the single-device path by construction:
the sharded kernels produce the same exact limb partials and merge
with native-int32 collectives (parallel/dist.py docstring).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import obs
from ..parallel.dist import AXIS, DistCopClient, _collective_merge, \
    make_mesh, shard_map
from ..util import failpoint
from .client import CopClient, _FirstCallCompile, _dag_key, _obj_nbytes, \
    widen32
from .eval import selection_mask


@dataclass
class MeshConfig:
    """The `[mesh]` knobs (config.py MeshSection mirrors this)."""

    enabled: bool = True
    # devices in the mesh; 0 = every visible device
    axis_size: int = 0
    # epochs with at least this many rows shard on the row axis
    shard_threshold_rows: int = 1 << 20
    # join build sides larger than this stop replicating and shard by
    # key range (probe rows then route over the exchange)
    replicate_threshold_bytes: int = 64 << 20
    # ---- flight recorder (per-shard skew / HBM / compile telemetry) ----
    # warn (session warning + mesh_skew event) when a sharded dispatch's
    # max/mean shard-row ratio reaches this; 0 disables the warning
    skew_warn_ratio: float = 4.0
    # emit a mesh_hbm_watermark event when one device's live buffer
    # bytes cross this fraction of its capacity
    hbm_watermark_fraction: float = 0.85
    # per-device capacity override in bytes; 0 = ask the backend
    # (device.memory_stats()['bytes_limit']; unknown on CPU = disabled)
    hbm_bytes: int = 0
    # per-dispatch shard-accounting ring: digests kept per client
    shard_ring_cap: int = 256


def epoch_nbytes(epoch) -> int:
    """Host bytes of one columnar epoch (columns + validity lanes)."""
    n = 0
    for data, valid in zip(epoch.columns, epoch.valids):
        n += int(data.nbytes)
        if valid is not None:
            n += int(valid.nbytes)
    return n


# ==================== flight recorder ====================

def _plan_digest(kind: str, identity) -> str:
    """Stable per-logical-kernel digest: the plan identity WITHOUT the
    shape bucket or placement mode — the same key the recompile-storm
    detector groups by (bucket/mode churn re-enters compile under ONE
    signature)."""
    import hashlib
    return hashlib.sha256(
        (str(kind) + "|" + str(identity)).encode()).hexdigest()[:16]


def _stat_pair(in_rows, out_rows):
    """int32[1, 2] per-shard (input rows, post-filter survivors); the
    P(AXIS) out_spec concatenates shards into [n_devices, 2]."""
    return jnp.stack([jnp.asarray(in_rows, dtype=jnp.int32),
                      jnp.asarray(out_rows, dtype=jnp.int32)])[None]


def _rows_partial_total(p):
    """Device-side total of a 1-limb 'rows' agg partial
    (int32[1, 2, segments], value = hi*4096 + lo per segment): the
    shard's post-filter survivor count, read off the partials the
    kernel already computes — no second pass over the data."""
    return jnp.sum(p[:, 0, :]) * 4096 + jnp.sum(p[:, 1, :])


def _bits_shard_counts(arr) -> np.ndarray:
    """Per-shard popcount of a P(AXIS)-sharded packed row bitmask: each
    device's local slice of the packed bits IS its survivor set."""
    counts = []
    for sh in sorted(arr.addressable_shards,
                     key=lambda s: s.device.id):
        counts.append(int(np.unpackbits(
            np.asarray(sh.data).view(np.uint8)).sum()))
    return np.asarray(counts, dtype=np.int64)


class MeshFlightRecorder:
    """Per-client mesh dispatch telemetry: a bounded ring of per-shard
    accounting keyed by plan digest, compile counts/durations with a
    recompile-storm detector, and the skew detector feeding EXPLAIN
    ANALYZE / Top SQL / the slow log / tidb_events.

    Hot-path contract: the dispatch side only APPENDS (kind, digest,
    device-array stats, routed bytes, operator) tuples to a thread-
    local list — no lock, no fetch, no sync. collect() (called by the
    engine after each dispatching plan node, i.e. after the
    statement's own device_get) fetches the tiny [n_devices, 2] stats
    arrays, computes skew, and folds everything into the ring. The
    single-device CopClient never touches any of this (zero-work
    contract). No background thread — rings are bounded OrderedDicts
    trimmed at insert."""

    STORM_COMPILES = 3   # same signature compiled this often = a storm
    COMPILE_CAP = 256    # signatures kept in the compile ring
    WARN_INTERVAL_S = 10.0  # per-digest skew-warning throttle

    def __init__(self, plane: "MeshPlane") -> None:
        self.plane = plane
        # the owning storage's Observability (events sink); set by
        # MeshPlane.client_for — None for bare test clients
        self.obs = None
        self._lock = threading.Lock()
        self._ring: "OrderedDict[str, dict]" = OrderedDict()
        self._compiles: "OrderedDict[str, dict]" = OrderedDict()
        self._tls = threading.local()

    # ---- dispatch side (hot path) --------------------------------------
    def note_pending(self, kind: str, digest: str, stats,
                     routed: int = 0, op: Optional[str] = None) -> None:
        pend = getattr(self._tls, "pending", None)
        if pend is None:
            pend = self._tls.pending = []
        if len(pend) < 128:  # bound a pathological dispatch loop
            pend.append((kind, digest, stats, int(routed), op))

    # ---- collection (after the statement's own device_get) -------------
    def collect(self) -> Optional[dict]:
        pend = getattr(self._tls, "pending", None)
        if not pend:
            return None
        self._tls.pending = []
        cap = max(int(self.plane.cfg.shard_ring_cap), 1)
        thr = float(self.plane.cfg.skew_warn_ratio)
        note_in = note_rows = None
        max_skew = 0.0
        routed_total = 0
        shards = 0
        now = time.time()
        for kind, digest, stats, routed, op in pend:
            inp = rows = None
            try:
                if isinstance(stats, dict) and "bits" in stats:
                    rows = _bits_shard_counts(stats["bits"])
                else:
                    a = np.asarray(stats)
                    inp = a[:, 0].astype(np.int64)
                    rows = a[:, 1].astype(np.int64)
                    if (rows < 0).any():
                        rows = None  # survivors unobservable (hc path)
            except Exception:  # noqa: BLE001 — telemetry only
                continue
            basis = rows if rows is not None and rows.sum() > 0 else inp
            skew = 1.0
            share = 0.0
            if basis is not None and len(basis) and basis.sum() > 0:
                total = float(basis.sum())
                skew = float(basis.max()) / (total / len(basis))
                share = float(basis.max()) / total
            fp = failpoint.inject("mesh/skew")
            if fp:
                skew = float(fp) if isinstance(fp, (int, float)) and \
                    not isinstance(fp, bool) else 1000.0
            # shard count from the observed arrays, not `basis`: a
            # dispatch whose filter matches zero rows is still an
            # n-way dispatch (basis is None when every count is 0)
            n = len(rows) if rows is not None else (
                len(inp) if inp is not None else 0)
            shards = max(shards, n)
            max_skew = max(max_skew, skew)
            routed_total += routed
            if rows is not None:
                note_rows = rows if note_rows is None else note_rows + rows
            if inp is not None:
                note_in = inp if note_in is None else note_in + inp
            # ---- ring update (keyed by plan digest) ----
            last_rows = [int(x) for x in (
                rows if rows is not None else
                (inp if inp is not None else []))]
            warn = False
            with self._lock:
                ent = self._ring.get(digest)
                if ent is None:
                    while len(self._ring) >= cap:
                        self._ring.popitem(last=False)
                    ent = self._ring[digest] = {
                        "digest": digest, "kind": kind, "op": op or "",
                        "dispatches": 0, "shards": n, "last_rows": [],
                        "last_skew": 1.0, "max_skew": 1.0,
                        "skew_hits": [],
                        "in_rows": 0, "out_rows": 0, "routed_bytes": 0,
                        "last_seen": 0.0, "last_warn": 0.0}
                else:
                    self._ring.move_to_end(digest)
                ent["dispatches"] += 1
                ent["shards"] = n
                if op:
                    ent["op"] = op
                if last_rows:
                    ent["last_rows"] = last_rows
                if rows is not None:
                    ent["out_rows"] += int(rows.sum())
                if inp is not None:
                    ent["in_rows"] += int(inp.sum())
                ent["last_skew"] = round(skew, 4)
                ent["max_skew"] = max(ent["max_skew"], round(skew, 4))
                if thr > 0 and skew >= thr:
                    # (timestamp, skew) per dispatch that individually
                    # crossed the warn ratio, bounded — the inspection
                    # rule's "sustained AND current" evidence: it
                    # counts and grades ONLY in-window crossings, so
                    # neither the monotonic max_skew nor a lifetime
                    # hit pile can flag a long-fixed hot range
                    hits = ent.setdefault("skew_hits", [])
                    hits.append((now, round(skew, 4)))
                    del hits[:-32]
                ent["routed_bytes"] += routed
                ent["last_seen"] = now
                if thr > 0 and skew >= thr and \
                        now - ent["last_warn"] >= self.WARN_INTERVAL_S:
                    ent["last_warn"] = now
                    warn = True
            obs.MESH_SKEW_RATIO.set(skew)
            srec = obs.active_stage_recorder()
            if srec is not None and n > 1:
                srec.note_mesh(op or kind, share, skew)
            if warn:
                obs.MESH_SKEW_WARNINGS.inc()
                detail = (f"{kind} dispatch {digest}: max/mean shard "
                          f"rows {skew:.2f} >= mesh.skew-warn-ratio "
                          f"{thr:g}; rows={last_rows}")
                o = self.obs
                if o is not None:
                    o.events.record("mesh_skew", detail=detail,
                                    severity="warn")
                w = getattr(self._tls, "warnings", None)
                if w is None:
                    w = self._tls.warnings = []
                if len(w) < 16:
                    w.append("mesh skew: " + detail)
        if shards == 0:
            return None
        return {"shards": shards,
                "in": None if note_in is None
                else [int(x) for x in note_in],
                "rows": None if note_rows is None
                else [int(x) for x in note_rows],
                "skew": max_skew, "routed": routed_total}

    def drain_warnings(self) -> tuple:
        w = getattr(self._tls, "warnings", None)
        if not w:
            return ()
        self._tls.warnings = []
        return tuple(w)

    def discard_pending(self) -> None:
        """Drop this thread's queued per-shard stats without folding
        them — a failed statement's dispatches must not leak into the
        next statement's first collect()."""
        if getattr(self._tls, "pending", None):
            self._tls.pending = []

    # ---- compile observability -----------------------------------------
    def note_compile(self, kind: str, signature: str, seconds: float,
                     full_key=None) -> None:
        obs.MESH_COMPILES.inc(kind=str(kind))
        obs.MESH_COMPILE_SECONDS.inc(float(seconds))
        storm = None
        with self._lock:
            ent = self._compiles.get(signature)
            if ent is None:
                while len(self._compiles) >= self.COMPILE_CAP:
                    self._compiles.popitem(last=False)
                ent = self._compiles[signature] = {
                    "signature": signature, "kind": str(kind),
                    "count": 0, "total_s": 0.0, "last_s": 0.0,
                    "storm": False, "last_key": ""}
            else:
                self._compiles.move_to_end(signature)
            ent["count"] += 1
            ent["total_s"] = round(ent["total_s"] + float(seconds), 6)
            ent["last_s"] = round(float(seconds), 6)
            if full_key is not None:
                ent["last_key"] = str(full_key)[:200]
            if ent["count"] >= self.STORM_COMPILES and not ent["storm"]:
                ent["storm"] = True
                storm = dict(ent)
        if storm is not None:
            obs.MESH_RECOMPILE_STORMS.inc()
            o = self.obs
            if o is not None:
                o.events.record(
                    "mesh_compile_storm",
                    detail=(f"kernel signature {storm['signature']} "
                            f"({storm['kind']}) compiled "
                            f"{storm['count']}x — bucket/placement-mode "
                            f"churn re-enters XLA compile; last key "
                            f"{storm['last_key']}"),
                    severity="warn")

    # ---- read side ------------------------------------------------------
    def table_rows(self) -> list[list]:
        """information_schema.tidb_mesh_shards rows, newest first."""
        with self._lock:
            ents = [dict(e) for e in self._ring.values()]
        rows = []
        for e in reversed(ents):
            rows.append([
                e["digest"], e["kind"], e["op"], e["dispatches"],
                e["shards"],
                ",".join(str(x) for x in e["last_rows"])[:256],
                e["last_skew"], e["max_skew"], e["in_rows"],
                e["out_rows"], e["routed_bytes"],
                time.strftime("%Y-%m-%d %H:%M:%S",
                              time.localtime(e["last_seen"]))])
        return rows

    def snapshot(self) -> dict:
        """The /debug/mesh payload half owned by this recorder."""
        with self._lock:
            return {
                "dispatches": [dict(e) for e in self._ring.values()],
                "compiles": [dict(e) for e in self._compiles.values()],
            }


class MeshPlane:
    """Process-wide mesh owner: device mesh, placement policy, shared
    per-storage clients, and the per-device telemetry the gauges read."""

    AXIS = AXIS

    def __init__(self, cfg: Optional[MeshConfig] = None,
                 devices=None) -> None:
        self.cfg = cfg or MeshConfig()
        self._devices = devices  # explicit device list (tests)
        self._mesh = None
        # RLock: client_for constructs clients (which read .mesh) under
        # the same lock
        self._lock = threading.RLock()
        # storage -> shared MeshCopClient (weak: a collected Storage
        # must release its device buffers with it)
        import weakref
        self._clients: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        # devices currently above the HBM watermark (edge-triggered
        # mesh_hbm_watermark events)
        self._above_watermark: set[str] = set()

    # ---- mesh lifecycle ---------------------------------------------------
    @property
    def mesh_built(self) -> bool:
        return self._mesh is not None

    @property
    def mesh(self):
        """The 1-D device mesh; building it initializes the backend, so
        it stays lazy until the first active client asks."""
        with self._lock:
            if self._mesh is None:
                devs = self._devices
                if devs is None:
                    import jax
                    devs = jax.devices()
                if self.cfg.axis_size > 0:
                    devs = list(devs)[: self.cfg.axis_size]
                self._mesh = make_mesh(devs)
            return self._mesh

    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def active(self) -> bool:
        """Enabled AND more than one device. Checking device count
        builds the mesh; a disabled plane never touches the backend."""
        if not self.cfg.enabled:
            return False
        try:
            return self.n_devices > 1
        except Exception:  # noqa: BLE001 — no backend: single-device
            return False

    # ---- placement policy -------------------------------------------------
    def placement_for(self, snap) -> str:
        """'shard' | 'single' for one table snapshot. Per-EPOCH
        deterministic (row count is fixed per epoch id), so staged-
        array cache keys never see both placements for one epoch."""
        if not self.active:
            return "single"
        if snap.epoch.num_rows >= self.cfg.shard_threshold_rows:
            return "shard"
        return "single"

    # ---- shared clients ---------------------------------------------------
    def client_for(self, storage) -> "MeshCopClient":
        """The storage's shared mesh client: every session of a storage
        uses ONE client, so sharded epochs persist across queries AND
        connections, and a folded epoch can be evicted eagerly."""
        with self._lock:
            c = self._clients.get(storage)
            if c is None:
                c = MeshCopClient(self)
                self._clients[storage] = c
        # the flight recorder's event sink: this storage's event ring
        # receives mesh_skew / mesh_compile_storm / mesh_hbm_watermark
        if c.recorder.obs is None:
            c.recorder.obs = getattr(storage, "obs", None)
        # the keyspace heat recorder: scans account per-range traffic
        if c.heat is None:
            c.heat = getattr(storage, "heat", None)
        # module-level storage->client registry: the diag/infoschema
        # read side (client_of) resolves through it, so recorder rings
        # stay queryable whichever plane instance built the client
        # (tests construct private planes; latest client wins)
        _STORAGE_CLIENTS[storage] = c
        # outside the plane lock: the listener hook takes storage-side
        # structures only
        if hasattr(storage, "add_epoch_listener"):
            storage.add_epoch_listener(c.on_epoch_replaced)
        return c

    def clients(self) -> list:
        with self._lock:
            return list(self._clients.values())

    # ---- telemetry --------------------------------------------------------
    def device_bytes(self) -> dict[str, int]:
        """Live device-resident bytes per device across this plane's
        clients (sharded epochs count their shard; replicated builds
        count a full copy per device — that is what pins HBM). The
        per-client walk is memoized per cache generation
        (MeshCopClient.telemetry), so scrapes between cache changes
        cost dict lookups, not an array walk. Crossing the HBM
        watermark is detected here (edge-triggered events)."""
        per: dict[str, int] = {}
        if self.mesh_built:
            for d in self._mesh.devices.flat:
                per[str(d)] = 0
        for c in self.clients():
            try:
                for dev, b in c.telemetry()["per_device"].items():
                    per[dev] = per.get(dev, 0) + b
            except Exception:  # noqa: BLE001 — telemetry only
                continue
        self._check_watermark(per)
        return per

    def device_capacity_bytes(self) -> int:
        """Per-device HBM capacity for the watermark check:
        mesh.hbm-bytes when set, else the backend's bytes_limit
        (unknown on CPU meshes = 0 = watermark disabled)."""
        if self.cfg.hbm_bytes > 0:
            return int(self.cfg.hbm_bytes)
        if not self.mesh_built:
            return 0
        try:
            ms = next(iter(self._mesh.devices.flat)).memory_stats()
            return int((ms or {}).get("bytes_limit", 0) or 0)
        except Exception:  # noqa: BLE001 — CPU devices have no stats
            return 0

    def _check_watermark(self, per: dict[str, int]) -> None:
        cap = self.device_capacity_bytes()
        if cap <= 0:
            return
        thr = cap * float(self.cfg.hbm_watermark_fraction)
        for dev, b in per.items():
            if b >= thr:
                if dev in self._above_watermark:
                    continue
                self._above_watermark.add(dev)
                obs.MESH_HBM_WATERMARK.inc(device=dev)
                detail = (f"device {dev}: {b} live buffer bytes >= "
                          f"{self.cfg.hbm_watermark_fraction:.0%} of "
                          f"{cap}-byte capacity")
                for c in self.clients():
                    o = getattr(c.recorder, "obs", None)
                    if o is not None:
                        o.events.record("mesh_hbm_watermark",
                                        detail=detail, severity="warn")
            else:
                self._above_watermark.discard(dev)

    def status(self) -> dict:
        """The /status `mesh` section (and the diag fan-out payload)."""
        out = {
            "enabled": self.cfg.enabled,
            "built": self.mesh_built,
            "devices": self.n_devices if self.mesh_built else 0,
            "shard_threshold_rows": self.cfg.shard_threshold_rows,
            "replicate_threshold_bytes":
                self.cfg.replicate_threshold_bytes,
            "skew_warn_ratio": self.cfg.skew_warn_ratio,
            "hbm_watermark_fraction": self.cfg.hbm_watermark_fraction,
        }
        if self.mesh_built:
            out["device_buffer_bytes"] = self.device_bytes()
            out["device_peak_bytes"] = self.device_peak_bytes()
            out["reshard_bytes_total"] = obs.MESH_RESHARD_BYTES.get()
        return out

    def device_peak_bytes(self) -> dict[str, int]:
        """High-water live bytes per device across this plane's
        clients (tracked at every telemetry recompute)."""
        peak: dict[str, int] = {}
        for c in self.clients():
            try:
                for dev, b in c.telemetry()["peak"].items():
                    peak[dev] = max(peak.get(dev, 0), b)
            except Exception:  # noqa: BLE001 — telemetry only
                continue
        return peak


def _walk_arrays(o):
    """Yield jax arrays nested in cache values (tuples/dicts/arrays)."""
    if isinstance(o, (tuple, list)):
        for x in o:
            yield from _walk_arrays(x)
    elif isinstance(o, dict):
        for x in o.values():
            yield from _walk_arrays(x)
    elif hasattr(o, "addressable_shards"):
        yield o


def _cached_arrays(client):
    """UNIQUE device arrays resident in a client's caches. The same
    array can sit under two keys (a replicated build under its base
    staging key AND its 'repc' re-placement key — jax.device_put to an
    identical sharding shares buffers), so byte accounting dedupes by
    identity or it would double-count every broadcast build."""
    with client._lock:
        vals = list(client._col_cache.values()) \
            + list(client._mask_cache.values())
    seen: set = set()
    for arr in _walk_arrays(vals):
        if id(arr) not in seen:
            seen.add(id(arr))
            yield arr


def _add_shard_bytes(arr, per: dict) -> None:
    """Accumulate one array's per-device resident bytes from its
    addressable shards (the one walk device_bytes and
    placement_report share)."""
    for sh in arr.addressable_shards:
        dev = str(sh.device)
        per[dev] = per.get(dev, 0) + int(sh.data.nbytes)


def _classify_key(key) -> tuple:
    """(epoch_id or None, provenance kind) for one staging-cache key —
    the HBM ledger's classification of WHAT pins the bytes: 'epoch'
    (sharded/staged scan columns + masks), 'replica' (broadcast join
    builds), 'perm' (join permutation tables), 'partition'
    (key-partitioned builds), 'aligned' (epoch-aligned join columns),
    'rankaux' (streamseg metadata)."""
    try:
        if key and key[0] == "tile":
            return int(key[1]), "epoch"
        k1 = key[1] if len(key) > 1 else None
        if isinstance(k1, str):
            kind = {"perm": "perm", "perm-rep": "perm",
                    "partb": "partition", "aligned": "aligned",
                    "repc": "replica", "repv": "replica",
                    "repvis": "replica", "rankaux": "rankaux",
                    "semibm": "perm", "semibm-rep": "perm"}.get(k1, k1)
            return int(key[0]), kind
        if key and key[-1] == "rep":
            return int(key[0]), "replica"
        if key and isinstance(key[0], int):
            return int(key[0]), "epoch"
    except Exception:  # noqa: BLE001 — ledger is best-effort
        pass
    return None, "other"


class MeshCopClient(DistCopClient):
    """Placement-aware coprocessor client over a MeshPlane.

    Every dispatch runs under a thread-local placement mode set by
    `placement_scope` (engine.py opens it per plan node from the probe
    snapshot). In `shard` mode the DistCopClient machinery applies —
    row-sharded staging, shard_map kernels, collective merges, the
    broadcast/partition join election. In `single` mode every hook
    dispatches to the base CopClient implementation, so a small table
    behaves EXACTLY as on one device (same kernels, same cache keys
    modulo the mode prefix, same engine tags)."""

    def __init__(self, plane: MeshPlane) -> None:
        super().__init__(plane.mesh)
        self.plane = plane
        self._part_thr_rows = DistCopClient.partition_join_threshold
        # mesh flight recorder: per-shard dispatch accounting, compile
        # observability, skew detection (one per client = per storage)
        self.recorder = MeshFlightRecorder(plane)
        # (col version, mask version) -> telemetry dict; per-device
        # live-byte high-water marks (guarded by self._lock)
        self._telemetry_memo: Optional[tuple] = None
        self._device_peak: dict[str, int] = {}

    # ---- placement state ---------------------------------------------------
    def _mode(self) -> str:
        return getattr(self._tls, "mode", None) or "single"

    def _sharded(self) -> bool:
        return self._mode() == "shard"

    @contextmanager
    def _mode_scope(self, mode: str):
        prev = getattr(self._tls, "mode", None)
        self._tls.mode = mode
        try:
            yield
        finally:
            self._tls.mode = prev

    def placement_scope(self, snap):
        return self._mode_scope(self.plane.placement_for(snap))

    def execute(self, dag, snap):
        # direct callers (no engine scope): decide placement here
        if getattr(self._tls, "mode", None) is None:
            with self.placement_scope(snap):
                return super().execute(dag, snap)
        return super().execute(dag, snap)

    # ---- storage integration ----------------------------------------------
    def on_epoch_replaced(self, store) -> None:
        """Eager invalidation on epoch fold (bulk load / compaction /
        DDL rewrite): free the superseded epoch's device buffers NOW
        instead of on the next dispatch — sharded epochs pin HBM on
        every device."""
        self._evict_stale(store.table.id, store.epoch.epoch_id)

    # ---- engine tags -------------------------------------------------------
    def _device_engine(self) -> str:
        return f"device@mesh{self._n}" if self._sharded() else "device"

    def _frag_engine(self, mode: str) -> str:
        if self._sharded():
            return f"device[{mode}]@mesh{self._n}"
        return f"device[{mode}]"

    # ---- mode-dispatched hooks --------------------------------------------
    # kernels compiled for the two modes differ (shard_map vs plain jit)
    # while their cache keys could coincide; the mode prefix keeps them
    # apart
    def _kernel(self, key, build):
        fn = super()._kernel((self._mode(),) + tuple(key), build)
        if isinstance(fn, _FirstCallCompile) and fn.on_first is None:
            # compile observability: the signature EXCLUDES the shape
            # bucket and placement mode, so bucket/mode churn that
            # re-enters compile lands on one signature — the
            # recompile-storm detector's grouping
            rec = self.recorder
            kind = str(key[0]) if key else "?"
            sig = _plan_digest(kind, key[1] if len(key) > 1 else "")
            full = (self._mode(),) + tuple(key)
            fn.on_first = lambda dt, _r=rec, _k=kind, _s=sig, _f=full: \
                _r.note_compile(_k, _s, dt, _f)
        return fn

    def _bucket_size(self, n: int) -> int:
        if self._sharded():
            return DistCopClient._bucket_size(self, n)
        return CopClient._bucket_size(self, n)

    def _place_cols(self, data, valid):
        if self._sharded():
            return DistCopClient._place_cols(self, data, valid)
        return CopClient._place_cols(self, data, valid)

    def _place_mask(self, mask):
        if self._sharded():
            return DistCopClient._place_mask(self, mask)
        return CopClient._place_mask(self, mask)

    def _with_shard_stats(self, fn, kind: str, digest: str):
        """Split a stats-augmented jitted kernel's (result, stats)
        pair: the result flows back to the unchanged base machinery;
        the tiny [n_devices, 2] per-shard stats arrays queue on the
        recorder's thread-local pending list and are fetched at
        take_mesh_note() time — AFTER the statement's own device_get,
        so no extra sync lands inside the dispatch pipeline."""
        rec = self.recorder

        def kern(*args):
            out, stats = fn(*args)
            rec.note_pending(kind, digest, stats,
                             op=obs.active_operator())
            return out

        return kern

    def _build_agg_kernel(self, dag, prepared, cards, segments):
        if not self._sharded():
            return CopClient._build_agg_kernel(
                self, dag, prepared, cards, segments)
        # the DistCopClient shard_map, plus per-shard flight-recorder
        # stats: input rows from the visibility mask, post-filter
        # survivors read off the 'rows' partial the kernel already
        # computes — both BEFORE the collective merge, so they are the
        # per-shard (not global) numbers
        body = self._agg_kernel_body(dag, prepared, cards, segments)
        sched = prepared["__agg_sched__"]

        def sharded(cols, row_mask):
            out = body(cols, row_mask)
            stats = _stat_pair(jnp.sum(row_mask.astype(jnp.int32)),
                               _rows_partial_total(out["rows"]))
            return _collective_merge(out, sched), stats

        mapped = shard_map(sharded, mesh=self.mesh,
                           in_specs=(P(AXIS), P(AXIS)),
                           out_specs=(P(), P(AXIS)))
        return self._with_shard_stats(
            jax.jit(mapped), "agg",
            _plan_digest("agg", _dag_key(dag, prepared)))

    def _build_topn_kernel(self, dag, prepared, expr, desc, n):
        if not self._sharded():
            return CopClient._build_topn_kernel(
                self, dag, prepared, expr, desc, n)
        raw = self._topn_body(dag, prepared, expr, desc, n)
        sel = dag.selection

        def body(cols, row_mask):
            out = raw(cols, row_mask)
            # survivor count re-derives the selection mask; XLA CSEs it
            # with the identical graph inside raw
            m = row_mask if sel is None else selection_mask(
                sel.conditions, widen32(list(cols)), prepared, row_mask)
            return out, _stat_pair(jnp.sum(row_mask.astype(jnp.int32)),
                                   jnp.sum(m.astype(jnp.int32)))

        mapped = shard_map(body, mesh=self.mesh,
                           in_specs=(P(AXIS), P(AXIS)),
                           out_specs=(P(None, AXIS), P(AXIS)))
        return self._with_shard_stats(
            jax.jit(mapped), "topn",
            _plan_digest("topn", _dag_key(dag, prepared)))

    def _build_rowmask_kernel(self, dag, prepared):
        if not self._sharded():
            return CopClient._build_rowmask_kernel(self, dag, prepared)
        raw = self._rowmask_body(dag, prepared)
        sel = dag.selection

        def body(cols, row_mask):
            packed = raw(cols, row_mask)
            m = row_mask if sel is None else selection_mask(
                sel.conditions, widen32(list(cols)), prepared, row_mask)
            return packed, _stat_pair(
                jnp.sum(row_mask.astype(jnp.int32)),
                jnp.sum(m.astype(jnp.int32)))

        mapped = shard_map(body, mesh=self.mesh,
                           in_specs=(P(AXIS), P(AXIS)),
                           out_specs=(P(AXIS), P(AXIS)))
        return self._with_shard_stats(
            jax.jit(mapped), "rows",
            _plan_digest("rows", _dag_key(dag, prepared)))

    def _frag_jit(self, kernel, mode, prepared):
        if not self._sharded():
            return CopClient._frag_jit(self, kernel, mode, prepared)
        rec = self.recorder
        routed = prepared.get("__part_join__") is not None or mode == "hc"
        kind = "frag-" + mode
        digest = _plan_digest(kind, tuple(prepared.get("__sig__", ())))
        build_specs = self._build_in_specs(prepared)
        if mode == "agg":
            sched = prepared["__agg_sched__"]

            def merged(pcols, pvis, builds):
                out = kernel(pcols, pvis, builds)
                stats = _stat_pair(jnp.sum(pvis.astype(jnp.int32)),
                                   _rows_partial_total(out["rows"]))
                return _collective_merge(out, sched), stats

            fn = jax.jit(shard_map(
                merged, mesh=self.mesh,
                in_specs=(P(AXIS), P(AXIS), build_specs),
                out_specs=(P(), P(AXIS))))
        elif mode == "hc":
            # DistCopClient's hc specs, with the per-shard stats riding
            # along; post-exchange survivors are not observable outside
            # the candidate path, so only input balance is recorded
            # (-1 = unknown survivors)
            specs = DistCopClient._hc_out_specs(prepared)

            def hc_body(pcols, pvis, builds):
                res = kernel(pcols, pvis, builds)
                stats = _stat_pair(jnp.sum(pvis.astype(jnp.int32)),
                                   jnp.int32(-1))
                return res, stats

            fn = jax.jit(shard_map(
                hc_body, mesh=self.mesh,
                in_specs=(P(AXIS), P(AXIS), build_specs),
                out_specs=(specs, P(AXIS))))
        elif mode == "topn":
            # fused join+topn: per-shard top-n candidate rows concatenate
            # along the k axis; survivors are not observable outside the
            # candidate cut, so only input balance is recorded
            def tp_body(pcols, pvis, builds):
                res = kernel(pcols, pvis, builds)
                stats = _stat_pair(jnp.sum(pvis.astype(jnp.int32)),
                                   jnp.int32(-1))
                return res, stats

            fn = jax.jit(shard_map(
                tp_body, mesh=self.mesh,
                in_specs=(P(AXIS), P(AXIS), build_specs),
                out_specs=(P(None, AXIS), P(AXIS))))
        else:
            # rows mode: the packed bitmask is already P(AXIS)-sharded;
            # each device's slice popcounts to its survivors at collect
            # time, so the kernel needs no extra outputs
            # rows fragments never route: the partitioned-join election
            # (fragment.py) is agg/hc-only — routed rows would lose
            # probe-row identity — so there are no exchange bytes to
            # account here, only the per-shard survivor popcounts
            inner = DistCopClient._frag_jit(self, kernel, mode, prepared)

            def row_kern(pcols, pvis, builds, *rest):
                out = inner(pcols, pvis, builds, *rest)
                rec.note_pending(kind, digest, {"bits": out},
                                 op=obs.active_operator())
                return out

            return row_kern

        def kern(pcols, pvis, builds, *rest):
            nbytes = 0
            if routed:
                # rows cross the mesh inside the kernel (all_to_all);
                # the collective itself is untimeable host-side, so
                # account the routed payload bytes at dispatch
                nbytes = _obj_nbytes(pcols) + _obj_nbytes([pvis])
                obs.MESH_RESHARD_BYTES.inc(nbytes)
            out, stats = fn(pcols, pvis, builds, *rest)
            rec.note_pending(kind, digest, stats, routed=nbytes,
                             op=obs.active_operator())
            return out

        return kern

    # ---- flight-recorder surface (engine + session hooks) -----------------
    def take_mesh_note(self):
        return self.recorder.collect()

    def drain_mesh_warnings(self) -> tuple:
        return self.recorder.drain_warnings()

    def discard_mesh_pending(self) -> None:
        self.recorder.discard_pending()

    def telemetry(self) -> dict:
        """Per-device live bytes + the HBM provenance ledger in ONE
        cached-array walk, memoized per cache generation (the
        _VersionedDict mutation counters): scrapes and /debug/mesh
        reads between cache changes are dict lookups, not re-walks of
        every cached array. Also advances the per-device peak marks."""
        with self._lock:
            gen = (self._col_cache.version, self._mask_cache.version)
            memo = self._telemetry_memo
            if memo is not None and memo[0] == gen:
                return memo[1]
            items = list(self._col_cache.items()) + \
                list(self._mask_cache.items())
            epoch_tables = {eid: tid
                            for tid, eid in self._live_epochs.items()}
        per: dict[str, int] = {}
        entries: dict[tuple, list] = {}
        seen: set = set()
        for key, val in items:
            eid, kind = _classify_key(key)
            for arr in _walk_arrays(val):
                if id(arr) in seen:
                    continue  # dedupe rep aliases (see _cached_arrays)
                seen.add(id(arr))
                try:
                    shards = list(arr.addressable_shards)
                except Exception:  # noqa: BLE001 — telemetry only
                    continue
                for sh in shards:
                    try:
                        dev = str(sh.device)
                        b = int(sh.data.nbytes)
                    except Exception:  # noqa: BLE001
                        continue
                    per[dev] = per.get(dev, 0) + b
                    e = entries.setdefault((dev, eid, kind), [0, 0])
                    e[0] += 1
                    e[1] += b
        rows = [{"device": d, "epoch": eid, "kind": k,
                 "arrays": a, "bytes": b}
                for (d, eid, k), (a, b) in sorted(
                    entries.items(),
                    key=lambda kv: (kv[0][0], str(kv[0][1]), kv[0][2]))]
        with self._lock:
            for dev, b in per.items():
                if b > self._device_peak.get(dev, 0):
                    self._device_peak[dev] = b
            result = {"per_device": per, "entries": rows,
                      "peak": dict(self._device_peak),
                      "epoch_tables": epoch_tables}
            self._telemetry_memo = (gen, result)
        return result

    def _stage_build_table(self, facade, snap):
        if self._sharded():
            return DistCopClient._stage_build_table(self, facade, snap)
        return CopClient._stage_build_table(self, facade, snap)

    def _place_build_array(self, arr, key=None):
        if self._sharded():
            return DistCopClient._place_build_array(self, arr, key)
        return CopClient._place_build_array(self, arr, key)

    def _hc_exchange_fn(self, frag, prepared):
        if self._sharded():
            return DistCopClient._hc_exchange_fn(self, frag, prepared)
        return None

    def _join_exchange_fn(self, frag, prepared, spans):
        if self._sharded():
            return DistCopClient._join_exchange_fn(
                self, frag, prepared, spans)
        return None

    def _stage_partitioned_build(self, t, snap, lo, span, j):
        # partitioned builds are only elected in shard mode
        return DistCopClient._stage_partitioned_build(
            self, t, snap, lo, span, j)

    # ---- join build election ----------------------------------------------
    @property
    def partition_join_threshold(self):
        return self._part_thr_rows if self._sharded() else None

    @partition_join_threshold.setter
    def partition_join_threshold(self, v) -> None:
        self._part_thr_rows = v

    def _partition_build(self, snap) -> bool:
        if not self._sharded():
            return False
        if CopClient._partition_build(self, snap):
            return True
        return epoch_nbytes(snap.epoch) > \
            self.plane.cfg.replicate_threshold_bytes

    @property
    def frag_axis(self):
        return AXIS if self._sharded() else None

    @property
    def hc_exchange_blocks(self) -> int:
        return self._n if self._sharded() else 1


# ==================== process-wide plane ====================

_PLANE: Optional[MeshPlane] = None
_PLANE_LOCK = threading.Lock()

# storage -> latest shared mesh client, whichever plane built it (weak:
# dies with the storage); the diag/infoschema read side resolves here
import weakref as _weakref  # noqa: E402

_STORAGE_CLIENTS: "_weakref.WeakKeyDictionary" = \
    _weakref.WeakKeyDictionary()


def _env_config() -> MeshConfig:
    """Embedded-use defaults: the `TIDB_TPU_MESH*` env knobs (server
    processes override via config.seed_mesh from the [mesh] section)."""
    import os

    cfg = MeshConfig()
    v = os.environ.get("TIDB_TPU_MESH")
    if v is not None:
        cfg.enabled = v not in ("0", "false", "off", "")
    for env, attr in (("TIDB_TPU_MESH_DEVICES", "axis_size"),
                      ("TIDB_TPU_MESH_SHARD_ROWS", "shard_threshold_rows"),
                      ("TIDB_TPU_MESH_REPLICATE_BYTES",
                       "replicate_threshold_bytes"),
                      ("TIDB_TPU_MESH_HBM_BYTES", "hbm_bytes"),
                      ("TIDB_TPU_MESH_RING_CAP", "shard_ring_cap")):
        raw = os.environ.get(env)
        if raw:
            try:
                setattr(cfg, attr, int(raw))
            except ValueError:
                pass
    for env, attr in (("TIDB_TPU_MESH_SKEW_RATIO", "skew_warn_ratio"),
                      ("TIDB_TPU_MESH_HBM_FRACTION",
                       "hbm_watermark_fraction")):
        raw = os.environ.get(env)
        if raw:
            try:
                setattr(cfg, attr, float(raw))
            except ValueError:
                pass
    return cfg


def get_plane() -> MeshPlane:
    global _PLANE
    with _PLANE_LOCK:
        if _PLANE is None:
            _PLANE = MeshPlane(_env_config())
        return _PLANE


def configure(enabled: Optional[bool] = None,
              axis_size: Optional[int] = None,
              shard_threshold_rows: Optional[int] = None,
              replicate_threshold_bytes: Optional[int] = None,
              skew_warn_ratio: Optional[float] = None,
              hbm_watermark_fraction: Optional[float] = None,
              hbm_bytes: Optional[int] = None,
              shard_ring_cap: Optional[int] = None) -> MeshPlane:
    """Replace the process plane (server startup / tests). Existing
    sessions keep their clients; NEW sessions see the new policy."""
    global _PLANE
    cfg = _env_config()
    if enabled is not None:
        cfg.enabled = enabled
    if axis_size is not None:
        cfg.axis_size = axis_size
    if shard_threshold_rows is not None:
        cfg.shard_threshold_rows = shard_threshold_rows
    if replicate_threshold_bytes is not None:
        cfg.replicate_threshold_bytes = replicate_threshold_bytes
    if skew_warn_ratio is not None:
        cfg.skew_warn_ratio = skew_warn_ratio
    if hbm_watermark_fraction is not None:
        cfg.hbm_watermark_fraction = hbm_watermark_fraction
    if hbm_bytes is not None:
        cfg.hbm_bytes = hbm_bytes
    if shard_ring_cap is not None:
        cfg.shard_ring_cap = shard_ring_cap
    with _PLANE_LOCK:
        _PLANE = MeshPlane(cfg)
        return _PLANE


def client_for(storage) -> CopClient:
    """Default coprocessor client for a session over `storage`: the
    storage's shared mesh client when the plane is active, else a fresh
    single-device CopClient (exactly the pre-mesh behavior)."""
    plane = get_plane()
    if not plane.active:
        c = CopClient()
        c.heat = getattr(storage, "heat", None)
        return c
    return plane.client_for(storage)


def status() -> dict:
    """The /status `mesh` section; never builds a mesh as a side
    effect (a scrape must not grab the TPU)."""
    with _PLANE_LOCK:
        plane = _PLANE
    if plane is None:
        return {"enabled": _env_config().enabled, "built": False,
                "devices": 0}
    return plane.status()


def client_of(storage) -> Optional["MeshCopClient"]:
    """The storage's EXISTING mesh client, or None — never creates one
    and never builds a mesh (the diag/infoschema read paths must not
    grab a backend as a side effect)."""
    return _STORAGE_CLIENTS.get(storage)


def shard_rows(storage) -> list[list]:
    """information_schema.tidb_mesh_shards rows for one storage (empty
    while the mesh plane is inactive or the storage has no client)."""
    c = client_of(storage)
    return c.recorder.table_rows() if c is not None else []


def storage_rows(storage) -> list[list]:
    """information_schema.tidb_mesh_storage rows: the per-device HBM
    provenance ledger — one row per (device, table/epoch, kind) entry
    plus one '(device)' total row per device carrying live AND peak
    bytes (the live totals equal tidb_device_buffer_bytes{device})."""
    c = client_of(storage)
    if c is None:
        return []
    t = c.telemetry()
    names: dict = {}
    for eid, tid in t["epoch_tables"].items():
        store = getattr(storage, "tables", {}).get(tid)
        if store is not None:
            names[eid] = store.table.name
    rows: list[list] = []
    for e in t["entries"]:
        rows.append([e["device"], names.get(e["epoch"]), e["epoch"],
                     e["kind"], e["arrays"], e["bytes"], None])
    for dev in sorted(t["per_device"]):
        rows.append([dev, "(device)", None, "total", None,
                     t["per_device"][dev], t["peak"].get(dev, 0)])
    return rows


def debug_payload() -> dict:
    """The /debug/mesh JSON: plane status + every client's dispatch
    ring, compile ring, and HBM ledger. Never builds a mesh (a scrape
    must not grab the TPU)."""
    out: dict = {"status": status(), "dispatches": [], "compiles": [],
                 "storage": []}
    with _PLANE_LOCK:
        plane = _PLANE
    if plane is None:
        return out
    for c in plane.clients():
        snap = c.recorder.snapshot()
        out["dispatches"].extend(snap["dispatches"])
        out["compiles"].extend(snap["compiles"])
        if plane.mesh_built:
            try:
                t = c.telemetry()
                out["storage"].append({
                    "per_device": t["per_device"], "peak": t["peak"],
                    "entries": t["entries"]})
            except Exception:  # noqa: BLE001 — scrape survives
                continue
    return out


def placement_report(client: CopClient) -> dict:
    """Per-device placement of a client's device-resident buffers —
    the MULTICHIP board / bench flight payload: bytes per device (from
    `arr.sharding` / `addressable_shards`), array counts by placement,
    and an example shard spec."""
    per: dict[str, int] = {}
    n_sharded = n_replicated = n_single = 0
    shard_spec = None
    for arr in _cached_arrays(client):
        try:
            s = arr.sharding
            devs = s.device_set
            _add_shard_bytes(arr, per)
            if len(devs) <= 1:
                n_single += 1
            elif s.is_fully_replicated:
                n_replicated += 1
            else:
                n_sharded += 1
                if shard_spec is None:
                    shard_spec = str(getattr(s, "spec", s))
        except Exception:  # noqa: BLE001 — report what we can
            continue
    return {"device_bytes": per, "sharded_arrays": n_sharded,
            "replicated_arrays": n_replicated,
            "single_arrays": n_single, "shard_spec": shard_spec}


# ---- per-device gauge probe (run before every /metrics scrape and
# metrics-history sample; passes obs.lint_metrics via the registered
# family help texts in obs.py) ------------------------------------------------

def _mesh_telemetry_probe() -> None:
    with _PLANE_LOCK:
        plane = _PLANE
    if plane is None or not plane.mesh_built:
        return
    obs.MESH_DEVICES.set(plane.n_devices)
    for dev, b in plane.device_bytes().items():
        obs.DEVICE_BUFFER_BYTES.set(b, device=dev)


obs.register_gauge_probe(_mesh_telemetry_probe)


__all__ = ["MeshConfig", "MeshPlane", "MeshCopClient",
           "MeshFlightRecorder", "epoch_nbytes", "get_plane",
           "configure", "client_for", "client_of", "status",
           "placement_report", "shard_rows", "storage_rows",
           "debug_payload"]
