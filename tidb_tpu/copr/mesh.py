"""Mesh plane: process-wide device mesh + placement-aware coprocessor.

The multi-chip DATA plane (ROADMAP item 2). MULTICHIP_r05 showed 8
devices visible while every fragment executed on one: the sharded
client (parallel/dist.py) existed but nothing *chose* it, and it
re-placed cached epochs onto the mesh on every dispatch. This module
owns both decisions:

* **MeshPlane** — one per process. Owns the 1-D device mesh
  (`jax.sharding.Mesh` over the `shard` axis, SNIPPETS.md [1]-[3]
  idiom), the placement policy, and the per-storage shared clients.
  Configured from the server's `[mesh]` TOML section or the
  `TIDB_TPU_MESH*` env knobs for embedded use.

* **Placement policy** — per TABLE EPOCH, decided once per plan node
  (executor/engine.py opens `placement_scope` around every dispatch):
  - epochs with >= `shard-threshold-rows` rows shard on the row axis
    (`NamedSharding(mesh, P('shard'))`) — the fact-table side;
  - smaller epochs run the unchanged single-device path — sharding a
    4k-row dimension table across 8 chips would pay collective latency
    for no bandwidth;
  - join build sides REPLICATE (broadcast exchange) unless bigger than
    `replicate-threshold-bytes` or the row threshold, in which case
    they shard by key range and probe rows route over the mesh
    (hash-partition exchange, parallel/exchange.py). This mirrors the
    reference's MPP broadcast-vs-hash-partition election
    (planner/core/fragment.go:45).

* **Persistent sharded residency** — staged columns are PLACED at
  creation (client._place_cols) and the placed arrays are what the
  epoch caches hold, so a sharded epoch stays device-resident across
  queries and sessions; `tidb_device_transfer_bytes` stops paying a
  re-shard per dispatch. DML that folds a new epoch invalidates the
  old epoch's device buffers eagerly (Storage.add_epoch_listener).

* **Graceful fallback** — `mesh.enabled = false`, a single visible
  device, or a below-threshold table all take the EXACT single-device
  path: `client_for` hands out a plain CopClient when the plane is
  inactive, and MeshCopClient in `single` mode dispatches every hook
  to the base implementations.

Results are bit-identical to the single-device path by construction:
the sharded kernels produce the same exact limb partials and merge
with native-int32 collectives (parallel/dist.py docstring).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Optional

from .. import obs
from ..parallel.dist import AXIS, DistCopClient, make_mesh
from .client import CopClient, _obj_nbytes


@dataclass
class MeshConfig:
    """The `[mesh]` knobs (config.py MeshSection mirrors this)."""

    enabled: bool = True
    # devices in the mesh; 0 = every visible device
    axis_size: int = 0
    # epochs with at least this many rows shard on the row axis
    shard_threshold_rows: int = 1 << 20
    # join build sides larger than this stop replicating and shard by
    # key range (probe rows then route over the exchange)
    replicate_threshold_bytes: int = 64 << 20


def epoch_nbytes(epoch) -> int:
    """Host bytes of one columnar epoch (columns + validity lanes)."""
    n = 0
    for data, valid in zip(epoch.columns, epoch.valids):
        n += int(data.nbytes)
        if valid is not None:
            n += int(valid.nbytes)
    return n


class MeshPlane:
    """Process-wide mesh owner: device mesh, placement policy, shared
    per-storage clients, and the per-device telemetry the gauges read."""

    AXIS = AXIS

    def __init__(self, cfg: Optional[MeshConfig] = None,
                 devices=None) -> None:
        self.cfg = cfg or MeshConfig()
        self._devices = devices  # explicit device list (tests)
        self._mesh = None
        # RLock: client_for constructs clients (which read .mesh) under
        # the same lock
        self._lock = threading.RLock()
        # storage -> shared MeshCopClient (weak: a collected Storage
        # must release its device buffers with it)
        import weakref
        self._clients: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()

    # ---- mesh lifecycle ---------------------------------------------------
    @property
    def mesh_built(self) -> bool:
        return self._mesh is not None

    @property
    def mesh(self):
        """The 1-D device mesh; building it initializes the backend, so
        it stays lazy until the first active client asks."""
        with self._lock:
            if self._mesh is None:
                devs = self._devices
                if devs is None:
                    import jax
                    devs = jax.devices()
                if self.cfg.axis_size > 0:
                    devs = list(devs)[: self.cfg.axis_size]
                self._mesh = make_mesh(devs)
            return self._mesh

    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def active(self) -> bool:
        """Enabled AND more than one device. Checking device count
        builds the mesh; a disabled plane never touches the backend."""
        if not self.cfg.enabled:
            return False
        try:
            return self.n_devices > 1
        except Exception:  # noqa: BLE001 — no backend: single-device
            return False

    # ---- placement policy -------------------------------------------------
    def placement_for(self, snap) -> str:
        """'shard' | 'single' for one table snapshot. Per-EPOCH
        deterministic (row count is fixed per epoch id), so staged-
        array cache keys never see both placements for one epoch."""
        if not self.active:
            return "single"
        if snap.epoch.num_rows >= self.cfg.shard_threshold_rows:
            return "shard"
        return "single"

    # ---- shared clients ---------------------------------------------------
    def client_for(self, storage) -> "MeshCopClient":
        """The storage's shared mesh client: every session of a storage
        uses ONE client, so sharded epochs persist across queries AND
        connections, and a folded epoch can be evicted eagerly."""
        with self._lock:
            c = self._clients.get(storage)
            if c is None:
                c = MeshCopClient(self)
                self._clients[storage] = c
        # outside the plane lock: the listener hook takes storage-side
        # structures only
        if hasattr(storage, "add_epoch_listener"):
            storage.add_epoch_listener(c.on_epoch_replaced)
        return c

    def clients(self) -> list:
        with self._lock:
            return list(self._clients.values())

    # ---- telemetry --------------------------------------------------------
    def device_bytes(self) -> dict[str, int]:
        """Live device-resident bytes per device across this plane's
        clients (sharded epochs count their shard; replicated builds
        count a full copy per device — that is what pins HBM)."""
        per: dict[str, int] = {}
        if self.mesh_built:
            for d in self._mesh.devices.flat:
                per[str(d)] = 0
        for c in self.clients():
            for arr in _cached_arrays(c):
                try:
                    _add_shard_bytes(arr, per)
                except Exception:  # noqa: BLE001 — telemetry only
                    continue
        return per

    def status(self) -> dict:
        """The /status `mesh` section (and the diag fan-out payload)."""
        out = {
            "enabled": self.cfg.enabled,
            "built": self.mesh_built,
            "devices": self.n_devices if self.mesh_built else 0,
            "shard_threshold_rows": self.cfg.shard_threshold_rows,
            "replicate_threshold_bytes":
                self.cfg.replicate_threshold_bytes,
        }
        if self.mesh_built:
            out["device_buffer_bytes"] = self.device_bytes()
            out["reshard_bytes_total"] = obs.MESH_RESHARD_BYTES.get()
        return out


def _walk_arrays(o):
    """Yield jax arrays nested in cache values (tuples/dicts/arrays)."""
    if isinstance(o, (tuple, list)):
        for x in o:
            yield from _walk_arrays(x)
    elif isinstance(o, dict):
        for x in o.values():
            yield from _walk_arrays(x)
    elif hasattr(o, "addressable_shards"):
        yield o


def _cached_arrays(client):
    """UNIQUE device arrays resident in a client's caches. The same
    array can sit under two keys (a replicated build under its base
    staging key AND its 'repc' re-placement key — jax.device_put to an
    identical sharding shares buffers), so byte accounting dedupes by
    identity or it would double-count every broadcast build."""
    with client._lock:
        vals = list(client._col_cache.values()) \
            + list(client._mask_cache.values())
    seen: set = set()
    for arr in _walk_arrays(vals):
        if id(arr) not in seen:
            seen.add(id(arr))
            yield arr


def _add_shard_bytes(arr, per: dict) -> None:
    """Accumulate one array's per-device resident bytes from its
    addressable shards (the one walk device_bytes and
    placement_report share)."""
    for sh in arr.addressable_shards:
        dev = str(sh.device)
        per[dev] = per.get(dev, 0) + int(sh.data.nbytes)


class MeshCopClient(DistCopClient):
    """Placement-aware coprocessor client over a MeshPlane.

    Every dispatch runs under a thread-local placement mode set by
    `placement_scope` (engine.py opens it per plan node from the probe
    snapshot). In `shard` mode the DistCopClient machinery applies —
    row-sharded staging, shard_map kernels, collective merges, the
    broadcast/partition join election. In `single` mode every hook
    dispatches to the base CopClient implementation, so a small table
    behaves EXACTLY as on one device (same kernels, same cache keys
    modulo the mode prefix, same engine tags)."""

    def __init__(self, plane: MeshPlane) -> None:
        super().__init__(plane.mesh)
        self.plane = plane
        self._part_thr_rows = DistCopClient.partition_join_threshold

    # ---- placement state ---------------------------------------------------
    def _mode(self) -> str:
        return getattr(self._tls, "mode", None) or "single"

    def _sharded(self) -> bool:
        return self._mode() == "shard"

    @contextmanager
    def _mode_scope(self, mode: str):
        prev = getattr(self._tls, "mode", None)
        self._tls.mode = mode
        try:
            yield
        finally:
            self._tls.mode = prev

    def placement_scope(self, snap):
        return self._mode_scope(self.plane.placement_for(snap))

    def execute(self, dag, snap):
        # direct callers (no engine scope): decide placement here
        if getattr(self._tls, "mode", None) is None:
            with self.placement_scope(snap):
                return super().execute(dag, snap)
        return super().execute(dag, snap)

    # ---- storage integration ----------------------------------------------
    def on_epoch_replaced(self, store) -> None:
        """Eager invalidation on epoch fold (bulk load / compaction /
        DDL rewrite): free the superseded epoch's device buffers NOW
        instead of on the next dispatch — sharded epochs pin HBM on
        every device."""
        self._evict_stale(store.table.id, store.epoch.epoch_id)

    # ---- engine tags -------------------------------------------------------
    def _device_engine(self) -> str:
        return f"device@mesh{self._n}" if self._sharded() else "device"

    def _frag_engine(self, mode: str) -> str:
        if self._sharded():
            return f"device[{mode}]@mesh{self._n}"
        return f"device[{mode}]"

    # ---- mode-dispatched hooks --------------------------------------------
    # kernels compiled for the two modes differ (shard_map vs plain jit)
    # while their cache keys could coincide; the mode prefix keeps them
    # apart
    def _kernel(self, key, build):
        return super()._kernel((self._mode(),) + tuple(key), build)

    def _bucket_size(self, n: int) -> int:
        if self._sharded():
            return DistCopClient._bucket_size(self, n)
        return CopClient._bucket_size(self, n)

    def _place_cols(self, data, valid):
        if self._sharded():
            return DistCopClient._place_cols(self, data, valid)
        return CopClient._place_cols(self, data, valid)

    def _place_mask(self, mask):
        if self._sharded():
            return DistCopClient._place_mask(self, mask)
        return CopClient._place_mask(self, mask)

    def _build_agg_kernel(self, dag, prepared, cards, segments):
        if self._sharded():
            return DistCopClient._build_agg_kernel(
                self, dag, prepared, cards, segments)
        return CopClient._build_agg_kernel(
            self, dag, prepared, cards, segments)

    def _build_topn_kernel(self, dag, prepared, expr, desc, n):
        if self._sharded():
            return DistCopClient._build_topn_kernel(
                self, dag, prepared, expr, desc, n)
        return CopClient._build_topn_kernel(
            self, dag, prepared, expr, desc, n)

    def _build_rowmask_kernel(self, dag, prepared):
        if self._sharded():
            return DistCopClient._build_rowmask_kernel(self, dag, prepared)
        return CopClient._build_rowmask_kernel(self, dag, prepared)

    def _frag_jit(self, kernel, mode, prepared):
        if not self._sharded():
            return CopClient._frag_jit(self, kernel, mode, prepared)
        fn = DistCopClient._frag_jit(self, kernel, mode, prepared)
        routed = prepared.get("__part_join__") is not None or mode == "hc"
        if not routed:
            return fn

        def counted(pcols, pvis, builds, *rest):
            # rows cross the mesh inside the kernel (all_to_all); the
            # collective itself is untimeable host-side, so account the
            # routed payload bytes at dispatch
            obs.MESH_RESHARD_BYTES.inc(
                _obj_nbytes(pcols) + _obj_nbytes([pvis]))
            return fn(pcols, pvis, builds, *rest)

        return counted

    def _stage_build_table(self, facade, snap):
        if self._sharded():
            return DistCopClient._stage_build_table(self, facade, snap)
        return CopClient._stage_build_table(self, facade, snap)

    def _place_build_array(self, arr, key=None):
        if self._sharded():
            return DistCopClient._place_build_array(self, arr, key)
        return CopClient._place_build_array(self, arr, key)

    def _hc_exchange_fn(self, frag, prepared):
        if self._sharded():
            return DistCopClient._hc_exchange_fn(self, frag, prepared)
        return None

    def _join_exchange_fn(self, frag, prepared, spans):
        if self._sharded():
            return DistCopClient._join_exchange_fn(
                self, frag, prepared, spans)
        return None

    def _stage_partitioned_build(self, t, snap, lo, span, j):
        # partitioned builds are only elected in shard mode
        return DistCopClient._stage_partitioned_build(
            self, t, snap, lo, span, j)

    # ---- join build election ----------------------------------------------
    @property
    def partition_join_threshold(self):
        return self._part_thr_rows if self._sharded() else None

    @partition_join_threshold.setter
    def partition_join_threshold(self, v) -> None:
        self._part_thr_rows = v

    def _partition_build(self, snap) -> bool:
        if not self._sharded():
            return False
        if CopClient._partition_build(self, snap):
            return True
        return epoch_nbytes(snap.epoch) > \
            self.plane.cfg.replicate_threshold_bytes

    @property
    def frag_axis(self):
        return AXIS if self._sharded() else None

    @property
    def hc_exchange_blocks(self) -> int:
        return self._n if self._sharded() else 1


# ==================== process-wide plane ====================

_PLANE: Optional[MeshPlane] = None
_PLANE_LOCK = threading.Lock()


def _env_config() -> MeshConfig:
    """Embedded-use defaults: the `TIDB_TPU_MESH*` env knobs (server
    processes override via config.seed_mesh from the [mesh] section)."""
    import os

    cfg = MeshConfig()
    v = os.environ.get("TIDB_TPU_MESH")
    if v is not None:
        cfg.enabled = v not in ("0", "false", "off", "")
    for env, attr in (("TIDB_TPU_MESH_DEVICES", "axis_size"),
                      ("TIDB_TPU_MESH_SHARD_ROWS", "shard_threshold_rows"),
                      ("TIDB_TPU_MESH_REPLICATE_BYTES",
                       "replicate_threshold_bytes")):
        raw = os.environ.get(env)
        if raw:
            try:
                setattr(cfg, attr, int(raw))
            except ValueError:
                pass
    return cfg


def get_plane() -> MeshPlane:
    global _PLANE
    with _PLANE_LOCK:
        if _PLANE is None:
            _PLANE = MeshPlane(_env_config())
        return _PLANE


def configure(enabled: Optional[bool] = None,
              axis_size: Optional[int] = None,
              shard_threshold_rows: Optional[int] = None,
              replicate_threshold_bytes: Optional[int] = None) -> MeshPlane:
    """Replace the process plane (server startup / tests). Existing
    sessions keep their clients; NEW sessions see the new policy."""
    global _PLANE
    cfg = _env_config()
    if enabled is not None:
        cfg.enabled = enabled
    if axis_size is not None:
        cfg.axis_size = axis_size
    if shard_threshold_rows is not None:
        cfg.shard_threshold_rows = shard_threshold_rows
    if replicate_threshold_bytes is not None:
        cfg.replicate_threshold_bytes = replicate_threshold_bytes
    with _PLANE_LOCK:
        _PLANE = MeshPlane(cfg)
        return _PLANE


def client_for(storage) -> CopClient:
    """Default coprocessor client for a session over `storage`: the
    storage's shared mesh client when the plane is active, else a fresh
    single-device CopClient (exactly the pre-mesh behavior)."""
    plane = get_plane()
    if not plane.active:
        return CopClient()
    return plane.client_for(storage)


def status() -> dict:
    """The /status `mesh` section; never builds a mesh as a side
    effect (a scrape must not grab the TPU)."""
    with _PLANE_LOCK:
        plane = _PLANE
    if plane is None:
        return {"enabled": _env_config().enabled, "built": False,
                "devices": 0}
    return plane.status()


def placement_report(client: CopClient) -> dict:
    """Per-device placement of a client's device-resident buffers —
    the MULTICHIP board / bench flight payload: bytes per device (from
    `arr.sharding` / `addressable_shards`), array counts by placement,
    and an example shard spec."""
    per: dict[str, int] = {}
    n_sharded = n_replicated = n_single = 0
    shard_spec = None
    for arr in _cached_arrays(client):
        try:
            s = arr.sharding
            devs = s.device_set
            _add_shard_bytes(arr, per)
            if len(devs) <= 1:
                n_single += 1
            elif s.is_fully_replicated:
                n_replicated += 1
            else:
                n_sharded += 1
                if shard_spec is None:
                    shard_spec = str(getattr(s, "spec", s))
        except Exception:  # noqa: BLE001 — report what we can
            continue
    return {"device_bytes": per, "sharded_arrays": n_sharded,
            "replicated_arrays": n_replicated,
            "single_arrays": n_single, "shard_spec": shard_spec}


# ---- per-device gauge probe (run before every /metrics scrape and
# metrics-history sample; passes obs.lint_metrics via the registered
# family help texts in obs.py) ------------------------------------------------

def _mesh_telemetry_probe() -> None:
    with _PLANE_LOCK:
        plane = _PLANE
    if plane is None or not plane.mesh_built:
        return
    obs.MESH_DEVICES.set(plane.n_devices)
    for dev, b in plane.device_bytes().items():
        obs.DEVICE_BUFFER_BYTES.set(b, device=dev)


obs.register_gauge_probe(_mesh_telemetry_probe)


__all__ = ["MeshConfig", "MeshPlane", "MeshCopClient", "epoch_nbytes",
           "get_plane", "configure", "client_for", "status",
           "placement_report"]
