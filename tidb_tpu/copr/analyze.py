"""ANALYZE pushdown: full-column statistics as device reduction kernels.

The reference pushes ANALYZE to the store as sample collectors + FM
sketches per region (reference: executor/analyze.go,
statistics/fmsketch.go, distsql/distsql.go:137 Analyze); only histogram
assembly happens centrally. The TPU analog (SURVEY §2.3 P13): one fused
reduction kernel per column batch over the SAME shape-bucketed tiles the
query path stages (cached device columns are reused), producing

  * non-null row count,
  * min / max,
  * 256 HLL-style registers from a 32-bit splitmix hash (the device is
    64-bit-free) — the NDV estimator that replaces a host np.unique over
    the full column.

Histograms and CM sketches still build host-side from a bounded SAMPLE
(statistics/builder.go builds histograms from samples in the reference
too); the device pass removes the full-column host scans that dominate
ANALYZE wall time at SF10+.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

N_REG = 256         # HLL registers (2^8: ~6.5% standard error)
_REG_BITS = 8

# splitmix32-style avalanche (device-side; uint32 lanes)
_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)


def _hash32(x):
    h = x.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(_M1)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(_M2)
    h = h ^ (h >> 16)
    return h


def hash32_host(x: np.ndarray) -> np.ndarray:
    """Host twin of the device hash (sketches built on either side must
    agree)."""
    with np.errstate(over="ignore"):
        h = x.astype(np.uint32)
        h ^= h >> 16
        h *= _M1
        h ^= h >> 13
        h *= _M2
        h ^= h >> 16
    return h


def hll_bucket_rank(v32):
    """Device (bucket, rank) per lane for HLL register updates: bucket =
    low 8 hash bits, rank = 1 + trailing zeros of the remaining bits
    (isolated low bit is a power of two -> exact f32 log2). Shared by
    ANALYZE NDV and the APPROX_COUNT_DISTINCT aggregate so their sketches
    merge."""
    h = _hash32(v32)
    bucket = (h & jnp.uint32(N_REG - 1)).astype(jnp.int32)
    rest = (h >> _REG_BITS) | jnp.uint32(1 << (32 - _REG_BITS))
    low = rest & (~rest + jnp.uint32(1))
    rank = jnp.log2(low.astype(jnp.float32)).astype(jnp.int32) + 1
    return bucket, rank


def hll_bucket_rank_host(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host twin of hll_bucket_rank (bit-identical registers)."""
    h = hash32_host(x)
    bucket = (h & np.uint32(N_REG - 1)).astype(np.int32)
    rest = (h >> np.uint32(_REG_BITS)) | np.uint32(1 << (32 - _REG_BITS))
    low = rest & (~rest + np.uint32(1))
    rank = np.log2(low.astype(np.float64)).astype(np.int32) + 1
    return bucket, rank


def hll_hash_src_int(v: np.ndarray) -> np.ndarray:
    """uint32 hash input for integer values. The choice is PER ELEMENT:
    int32-range values use their low 32 bits (bit-identical to the device
    sketch), wider values fold their high 32 bits in (plain truncation
    would collide every pair differing only above bit 31). A per-batch
    choice would hash the same in-range value differently across partial
    producers (partitions/overlay), double-counting it in the register
    merge."""
    v = np.asarray(v).astype(np.int64)
    u = v.view(np.uint64)
    low = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    in_range = (v >= -(2 ** 31)) & (v < 2 ** 31)
    if in_range.all():
        return low
    folded = ((u ^ (u >> np.uint64(32))) &
              np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return np.where(in_range, low, folded)


def float_bits_key(x: np.ndarray) -> np.ndarray:
    """Canonical int64 bit-key for float64 values: -0.0 normalizes to
    0.0 so the two zero encodings compare equal. Shared by distinct
    aggregation, the host HLL hash, and ADMIN CHECK unique scans — one
    canonicalization, three consumers."""
    norm = np.where(x == 0, 0.0, np.asarray(x, np.float64))
    return norm.view(np.int64)


def hll_group_registers_host(av: np.ndarray, avl: np.ndarray,
                             inv: np.ndarray, n_seg: int) -> np.ndarray:
    """Per-group HLL registers host-side: (n_seg, N_REG) int32 max-rank,
    bit-identical to the device scatter (copr/client.agg_partials hll
    branch) so host-fallback partials merge with device partials."""
    regs = np.zeros((n_seg, N_REG), np.int32)
    rows = np.nonzero(avl)[0]
    if len(rows):
        bucket, rank = hll_bucket_rank_host(av[rows])
        np.maximum.at(regs, (inv[rows], bucket), rank)
    return regs


def hll_pack_words(regs: np.ndarray) -> np.ndarray:
    """(n, N_REG) int32 registers -> (n, N_REG // 8) int64 byte-packed."""
    regs = regs.astype(np.int64)
    words = np.zeros((regs.shape[0], N_REG // 8), np.int64)
    for w in range(N_REG // 8):
        for b in range(8):
            words[:, w] |= regs[:, w * 8 + b] << (8 * b)
    return words


def hll_unpack_words(words: np.ndarray) -> np.ndarray:
    """(n, N_REG // 8) int64 byte-packed -> (n, N_REG) int32 registers."""
    out = np.zeros((words.shape[0], N_REG), np.int32)
    for w in range(words.shape[1]):
        for b in range(8):
            out[:, w * 8 + b] = (words[:, w] >> (8 * b)) & 0xFF
    return out


def _column_partials(data, valid):
    """Reduction body for one staged column (int32/f32 + validity)."""
    v32 = data.astype(jnp.int32) if data.dtype in (
        jnp.int8, jnp.int16, jnp.int32) else data
    cnt = jnp.sum(valid.astype(jnp.int32))
    if v32.dtype == jnp.float32:
        big = jnp.float32(np.inf)
        mn = jnp.min(jnp.where(valid, v32, big))
        mx = jnp.max(jnp.where(valid, v32, -big))
    else:
        big = jnp.int32(2**31 - 1)
        mn = jnp.min(jnp.where(valid, v32, big))
        mx = jnp.max(jnp.where(valid, v32, -big - 1))
    # HLL registers over a 32-bit hash: bucket = low _REG_BITS bits, rank =
    # trailing zeros of the remaining bits + 1 (isolated low bit is a
    # power of two -> exact f32 log2)
    hsrc = jax.lax.bitcast_convert_type(v32, jnp.int32) \
        if v32.dtype == jnp.float32 else v32
    bucket, rank = hll_bucket_rank(hsrc)
    rank = jnp.where(valid, rank, 0)
    regs = jnp.zeros(N_REG, jnp.int32).at[bucket].max(rank)
    return {"cnt": cnt, "mn": mn, "mx": mx, "regs": regs}


def _merge(parts: list[dict]) -> dict:
    out = dict(parts[0])
    for p in parts[1:]:
        out["cnt"] = out["cnt"] + p["cnt"]
        out["mn"] = np.minimum(out["mn"], p["mn"])
        out["mx"] = np.maximum(out["mx"], p["mx"])
        out["regs"] = np.maximum(out["regs"], p["regs"])
    return out


def hll_ndv(regs: np.ndarray, nonnull: float) -> int:
    """Standard HLL estimate with small-range correction."""
    m = float(N_REG)
    alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(
        N_REG, 0.7213 / (1 + 1.079 / m))
    regs = np.asarray(regs, dtype=np.float64)
    est = alpha * m * m / np.sum(np.exp2(-regs))
    zeros = float((regs == 0).sum())
    if est <= 2.5 * m and zeros > 0:
        est = m * np.log(m / zeros)
    return max(1, min(int(round(est)), int(nonnull)))


def device_column_stats(cop, snap, offsets: list[int]):
    """off -> (nonnull_count, min, max, ndv) via one kernel per tile,
    reusing the query path's cached tile staging. Columns whose staged
    width cannot represent the values (host int64 beyond int32) are
    skipped — the caller falls back to host stats for those."""
    from ..plan.dag import CopDAG, DAGScan

    usable = []
    for off in offsets:
        d = snap.epoch.columns[off]
        if d.dtype == np.int64:
            b = cop._col_stats(snap, off)
            if b is None or b[0] < -(2**31) or b[1] >= 2**31:
                continue
        usable.append(off)
    if not usable:
        return {}
    dag = CopDAG(scan=DAGScan(snap.store.table.id, usable))
    # placement must match the query path's: an ANALYZE staging outside
    # the scope would seed the SHARED mesh client's epoch cache with
    # single-device arrays under the keys sharded queries hit, silently
    # defeating the persistent sharded residency
    with cop.placement_scope(snap):
        tiles = cop._stage_tiles(dag, snap)
        bucket = tiles[0][0][0][0].shape[0] if tiles and tiles[0][0] else 0

        def build():
            def kernel(d, v, vis):
                from .client import widen32
                (d, v), = widen32([(d, v)])
                return _column_partials(d, v & vis)
            return jax.jit(kernel)

        # one kernel per (dtype, bucket) — shared across all columns of
        # that width, so the first ANALYZE compiles a handful of tiny
        # programs
        devs = []
        for ci in range(len(usable)):
            dt = str(tiles[0][0][ci][0].dtype)
            kern = cop._kernel(("analyze", dt, bucket), build)
            devs.append([kern(cols[ci][0], cols[ci][1], vis)
                         for cols, vis, _ in tiles])
        outs = jax.device_get(devs)
    result = {}
    for ci, off in enumerate(usable):
        p = _merge(list(outs[ci]))
        nonnull = float(p["cnt"])
        result[off] = (nonnull, p["mn"], p["mx"],
                       hll_ndv(p["regs"], nonnull) if nonnull else 0)
    return result
