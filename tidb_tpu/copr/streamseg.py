"""Run-ordered segmented sums as a Pallas TPU kernel.

The high-cardinality aggregation path needs per-GROUP sums when the group
key has millions of distinct values (GROUP BY l_orderkey). When storage
order already groups the key (fact tables are clustered by PK — the
StreamAgg eligibility, reference: planner/core/exhaust_physical_plans.go
getStreamAggs, executor/aggregate.go StreamAgg), every group is one
contiguous run and the whole aggregation is a *rank-space* reduction:

    rank(row)   = number of key changes up to the row   (host-precomputed)
    out[k, r]   = sum of vals[k, row] over rows with rank(row) == r

XLA offers no fast lowering for this on TPU: sorts are unnecessary,
scatter-adds serialize, and per-row prefix+gather schemes cost 4 random
gathers per value array (~50M elem/s). This kernel streams the rows once:

  * 1-D sequential grid; each step consumes B inner blocks of BLK rows
    (the fori_loop amortizes the ~15us grid-step overhead);
  * per inner block: local ranks = running count + in-block cumsum of the
    host-precomputed change flags (log-doubling rolls — Mosaic has no
    cumsum primitive);
  * per-rank sums via ONE one-hot f32 matmul on the MXU
    ([K, BLK] x [BLK, OHW]) — exact, because every addend is an integer
    limb < 2^12 and every per-rank total is < 2^24 (gated on max rows per
    key). The one-hot target absorbs the sub-128 part of the rank offset,
    so the accumulate into the VMEM window is 128-lane-aligned;
  * the sliding VMEM window flushes fixed-size 128-aligned chunks to the
    HBM output (async copy + static roll) whenever enough ranks are
    final; ranks are written exactly once.

Host metadata (change flags, block stats) is computed once per epoch from
the key column(s) and cached; per query the kernel reads only the masked
value arrays.

On non-TPU backends `rank_sums` lowers to jax.ops.segment_sum — the
semantic spec of the kernel — so the test suite exercises the same path
shape on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BLK = 1024     # rows per inner block (one-hot sublane extent)
B = 16         # inner blocks per grid step
MAX_ROWS_PER_KEY = 4096   # f32 exactness: rows_per_key * (2^12-1) < 2^24
MAX_ARRAYS = 8  # K cap


def _r128(x: int) -> int:
    return (-(-x // 128)) * 128


def rank_meta(key_cols: list[np.ndarray]):
    """Host-side per-epoch metadata from the raw (lexicographically
    run-ordered) key column(s). Pad rows added by staging keep the last
    rank; their values are query-masked to zero.

    Returns None when a gate fails (too many rows in one key)."""
    n0 = len(key_cols[0])
    if n0 == 0:
        return None
    chg = np.zeros(n0, dtype=bool)
    for k in key_cols:
        chg[1:] |= k[1:] != k[:-1]
    r0 = np.flatnonzero(np.concatenate([[True], chg[1:n0]])).astype(
        np.int32)
    nd = len(r0)
    seg_rows = np.diff(np.concatenate([r0, [n0]]))
    if len(seg_rows) and seg_rows.max() > MAX_ROWS_PER_KEY:
        return None
    f = np.zeros(n0, dtype=np.int32)
    f[1:] = chg[1:]
    # widest per-inner-block rank count (drives the one-hot width)
    nblk0 = -(-n0 // BLK)
    fb = np.zeros(nblk0 * BLK, dtype=np.int64)
    fb[:n0] = f
    maxd = int(fb.reshape(nblk0, BLK).sum(axis=1).max()) + 1
    ohw = _r128(maxd + 2) + 128           # +128: absorbs offset % 128
    F = _r128(B * maxd + 2)               # fixed flush chunk
    # window: up to F unflushed ranks at step start + one step's growth
    # (<= B*maxd <= F) + the one-hot extent of the last block
    wstep = 2 * F + ohw + 256
    nd_pad = max(_r128(nd), 128)
    out_pad = nd_pad + wstep + F          # final flush slack
    r0_pad = np.zeros(nd_pad, dtype=np.int32)
    r0_pad[:nd] = r0
    return {
        "n0": n0, "nd": nd,
        "nd_pad": nd_pad, "out_pad": out_pad, "maxd": maxd, "ohw": ohw,
        "flush": F, "wstep": wstep, "f": f, "r0": r0_pad,
        "identity": nd == n0,
    }


def _kernel(vals_ref, f_ref, out_hbm, acc, sem, st, *, K, OHW, F, WS,
            steps):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc[:, :] = jnp.zeros_like(acc)
        st[0] = 0   # rank count so far (global, inclusive of last rank)
        st[1] = 0   # completed flushes (window base = st[1] * F)

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)
    ohl = jax.lax.broadcasted_iota(jnp.int32, (BLK, OHW), 1)

    def inner(j, cur):
        v = vals_ref[:, pl.ds(j * BLK, BLK)]
        fl = f_ref[0, pl.ds(j * BLK, BLK)].reshape(1, BLK)
        blr = fl
        d = 1
        while d < BLK:
            blr = blr + jnp.where(lane >= d, pltpu.roll(blr, d, axis=1),
                                  0)
            d *= 2
        o = cur - st[1] * F               # window-relative rank offset
        o128 = o // 128 * 128
        w = (o - o128) + blr              # per-row one-hot target
        oh = (ohl == w.reshape(BLK, 1)).astype(jnp.float32)
        S = jax.lax.dot_general(
            v, oh, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
        cur_win = acc[:, pl.ds(o128, OHW)]
        acc[:, pl.ds(o128, OHW)] = cur_win + S
        return cur + jnp.sum(fl)

    cur = jax.lax.fori_loop(0, B, inner, st[0])
    st[0] = cur

    # flush a fixed 128-aligned chunk once the window holds F final ranks
    # (the last active rank may still grow — never flush past it)
    @pl.when((cur - 1 - st[1] * F >= F) & (i < steps - 1))
    def _flush():
        cp = pltpu.make_async_copy(
            acc.at[:, 0:F], out_hbm.at[:, pl.ds(st[1] * F, F)], sem)
        cp.start()
        cp.wait()
        rolled = pltpu.roll(acc[:, :], WS - F, axis=1)
        ll = jax.lax.broadcasted_iota(jnp.int32, (1, WS), 1)
        acc[:, :] = jnp.where(ll < WS - F, rolled, 0.0)
        st[1] = st[1] + 1

    @pl.when(i == steps - 1)
    def _final():
        cp = pltpu.make_async_copy(
            acc.at[:, :], out_hbm.at[:, pl.ds(st[1] * F, WS)], sem)
        cp.start()
        cp.wait()


def rank_sums(vals, f_dev, meta):
    """vals: f32[K, n_pad] query-masked integer-valued arrays.
    -> f32[K, nd_pad] per-rank sums (exact integers; entries at ranks
    >= nd are zeroed).

    TPU: the Pallas kernel above; otherwise jax.ops.segment_sum."""
    K = vals.shape[0]
    nd, nd_pad = meta["nd"], meta["nd_pad"]
    if meta["identity"]:
        flat = vals[:, :nd_pad]
        if flat.shape[1] < nd_pad:
            flat = jnp.pad(flat, ((0, 0), (0, nd_pad - flat.shape[1])))
    elif jax.default_backend() != "tpu":
        f = f_dev
        if f.shape[0] < vals.shape[1]:
            f = jnp.pad(f, (0, vals.shape[1] - f.shape[0]))
        rank = jnp.cumsum(f[: vals.shape[1]])
        flat = jax.vmap(
            lambda v: jax.ops.segment_sum(v, rank, num_segments=nd_pad)
        )(vals)
    else:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        steps = -(-vals.shape[1] // (B * BLK))
        npad2 = steps * B * BLK
        K8 = -(-K // 8) * 8   # DMA slices must be sublane(8)-aligned
        pad_rows = ((0, K8 - K), (0, max(0, npad2 - vals.shape[1])))
        if pad_rows != ((0, 0), (0, 0)):
            vals = jnp.pad(vals, pad_rows)
        kern = functools.partial(
            _kernel, K=K8, OHW=meta["ohw"], F=meta["flush"],
            WS=meta["wstep"], steps=steps)
        out = pl.pallas_call(
            kern,
            grid=(steps,),
            in_specs=[
                pl.BlockSpec((K8, B * BLK), lambda i: (0, i)),
                pl.BlockSpec((1, B * BLK), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[
                pltpu.VMEM((K8, meta["wstep"]), jnp.float32),
                pltpu.SemaphoreType.DMA,
                pltpu.SMEM((2,), jnp.int32),
            ],
            out_shape=jax.ShapeDtypeStruct((K8, meta["out_pad"]),
                                           jnp.float32),
        )(vals, jnp.pad(f_dev, (0, npad2 - f_dev.shape[0])
                        ).reshape(1, -1))
        flat = out[:K, :nd_pad]
    # ranks beyond nd carry garbage (unwritten HBM) on the kernel path
    live = jnp.arange(nd_pad, dtype=jnp.int32) < nd
    return jnp.where(live[None, :], flat, 0.0)
