"""NumpyEval: vectorized host-side expression evaluation.

The numpy twin of copr/eval.py (reference keeps the same duality: row-based
eval* alongside vectorized vecEval*, expression/builtin_*.go). Shared by the
host coprocessor fallback (copr/host_exec.py) and the host volcano operators
(executor/) for selections, projections, join/sort keys, and complete
aggregation over operator output chunks.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..chunk.column import Dictionary
from ..plan.expr import Call, Col, Const, PlanExpr
from ..types.field_type import FieldType, TypeKind

VV = tuple[np.ndarray, np.ndarray]


class NumpyEval:
    """Evaluates resolved expressions over (data, valid) numpy column pairs."""

    def __init__(
        self,
        cols: list[VV],
        dicts: list[Optional[Dictionary]],
        n: int,
    ) -> None:
        self.cols = cols
        self.dicts = dicts
        self.n = n

    # ---- string-domain evaluation -------------------------------------------
    def eval_str(self, e: PlanExpr) -> VV:
        """Evaluate a string-typed expression to (object array of str, valid).

        Used when the value crosses dictionary domains (CASE branches,
        IFNULL over different columns, literals) — the caller re-encodes the
        result into a fresh dictionary."""
        if isinstance(e, Col):
            codes, vl = self.cols[e.idx]
            d = self.dicts[e.idx]
            if d is None or len(d) == 0:
                return np.full(self.n, "", dtype=object), \
                    np.zeros(self.n, bool) if d is None else vl
            vals = np.array(d.values, dtype=object)
            return vals[np.clip(codes, 0, len(d) - 1)], vl
        if isinstance(e, Const):
            if e.value is None:
                return (np.full(self.n, "", dtype=object),
                        np.zeros(self.n, bool))
            return (np.full(self.n, str(e.value), dtype=object),
                    np.ones(self.n, bool))
        assert isinstance(e, Call)
        op = e.op
        A = e.args
        if op == "if":
            cv, cvl = _b(self.eval(A[0]))
            tv, tvl = self.eval_str(A[1])
            fv, fvl = self.eval_str(A[2])
            cond = cv & cvl
            return np.where(cond, tv, fv), np.where(cond, tvl, fvl)
        if op == "ifnull":
            av, avl = self.eval_str(A[0])
            bv, bvl = self.eval_str(A[1])
            return np.where(avl, av, bv), avl | bvl
        if op == "coalesce":
            out_v, out_vl = self.eval_str(A[0])
            for a in A[1:]:
                av, avl = self.eval_str(a)
                out_v = np.where(out_vl, out_v, av)
                out_vl = out_vl | avl
            return out_v, out_vl
        if op == "case":
            has_else = len(A) % 2 == 1
            pairs = (len(A) - 1) // 2 if has_else else len(A) // 2
            if has_else:
                out_v, out_vl = self.eval_str(A[-1])
                out_v = np.array(out_v, copy=True)
                out_vl = np.array(out_vl, copy=True)
            else:
                out_v = np.full(self.n, "", dtype=object)
                out_vl = np.zeros(self.n, bool)
            decided = np.zeros(self.n, bool)
            for i in range(pairs):
                cv, cvl = _b(self.eval(A[2 * i]))
                tv, tvl = self.eval_str(A[2 * i + 1])
                take = cv & cvl & ~decided
                out_v = np.where(take, tv, out_v)
                out_vl = np.where(take, tvl, out_vl)
                decided |= take
            return out_v, out_vl
        if op == "substring":
            av, avl = self.eval_str(A[0])
            start, length = e.extra
            out = np.empty(self.n, dtype=object)
            for i, s in enumerate(av):
                out[i] = _substring(s, start, length)
            return out, avl
        if op == "json_extract":
            av, avl = self.eval_str(A[0])
            out = np.full(self.n, "", dtype=object)
            ok = np.zeros(self.n, bool)
            for i, (s, v) in enumerate(zip(av, avl)):
                if not v:
                    continue
                r = _json_extract(s, str(e.extra))
                if r is not None:
                    out[i] = r
                    ok[i] = True
            return out, ok
        if op == "json_unquote":
            av, avl = self.eval_str(A[0])
            out = np.empty(self.n, dtype=object)
            for i, s in enumerate(av):
                out[i] = _json_unquote(s)
            return out, avl
        if op == "json_type":
            import json as _json

            av, avl = self.eval_str(A[0])
            out = np.full(self.n, "", dtype=object)
            ok = np.zeros(self.n, bool)
            for i, (s, v) in enumerate(zip(av, avl)):
                if not v:
                    continue
                try:
                    out[i] = _json_type_name(_json.loads(s))
                    ok[i] = True
                except ValueError:
                    pass
            return out, ok
        raise NotImplementedError(f"string eval: {op}")

    # ---- evaluation ---------------------------------------------------------
    def eval(self, e: PlanExpr) -> VV:
        if isinstance(e, Col):
            return self.cols[e.idx]
        if isinstance(e, Const):
            if e.value is None:
                return (np.zeros(self.n, dtype=e.ftype.np_dtype),
                        np.zeros(self.n, dtype=bool))
            v = e.value
            if e.ftype.is_string:
                # resolved per comparison; free-standing only for eq against
                # another string expr handled below
                return (np.full(self.n, -2, dtype=np.int64),
                        np.ones(self.n, dtype=bool))
            return (np.full(self.n, v, dtype=e.ftype.np_dtype),
                    np.ones(self.n, dtype=bool))
        assert isinstance(e, Call)
        return self._call(e)

    def _call(self, e: Call) -> VV:
        op = e.op
        A = e.args

        if op == "and":
            av, avl = _b(self.eval(A[0]))
            bv, bvl = _b(self.eval(A[1]))
            known_false = (avl & ~av) | (bvl & ~bv)
            valid = (avl & bvl) | known_false
            return av & bv & valid, valid
        if op == "or":
            av, avl = _b(self.eval(A[0]))
            bv, bvl = _b(self.eval(A[1]))
            value = (av & avl) | (bv & bvl)
            valid = (avl & bvl) | value
            return value, valid
        if op == "not":
            av, avl = _b(self.eval(A[0]))
            return (~av) & avl, avl
        if op == "isnull":
            _, avl = self.eval(A[0])
            return ~avl, np.ones_like(avl)

        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            return self._compare(e)

        if op == "in_values":
            arg = A[0]
            if arg.ftype.is_string and isinstance(arg, Col):
                av, avl = self.eval(arg)
                d = self.dicts[arg.idx]
                assert d is not None
                if arg.ftype.is_ci:
                    canon = d.ci_canonical() if len(d) else \
                        np.zeros(0, np.int64)
                    codes = [d.lookup_ci(str(v)) for v in e.extra]
                    av = canon[np.clip(av, 0, max(len(d) - 1, 0))] \
                        if len(d) else av
                else:
                    codes = [d.lookup(str(v)) for v in e.extra]
                hit = np.isin(av, [c for c in codes if c >= 0])
            elif arg.ftype.is_string:
                # computed string (e.g. substring): string-domain membership
                sv, svl = self.eval_str(arg)
                hit = np.isin(sv, np.array([str(v) for v in e.extra],
                                           dtype=object))
                return hit & svl, svl
            else:
                av, avl = self.eval(arg)
                vals = e.extra
                hit = np.isin(av, np.array(vals))
            return hit & avl, avl
        if op == "like":
            import re

            from .client import _like_to_regex
            arg = A[0]
            flags = re.DOTALL
            if arg.ftype.is_ci:
                flags |= re.IGNORECASE  # ci collation LIKE
            rx = re.compile(_like_to_regex(str(e.extra)), flags)
            if not isinstance(arg, Col):
                sv, svl = self.eval_str(arg)
                hit = np.fromiter((rx.fullmatch(s) is not None for s in sv),
                                  bool, count=self.n)
                return hit & svl, svl
            av, avl = self.eval(arg)
            d = self.dicts[arg.idx]
            assert d is not None
            if len(d):
                table = np.fromiter((rx.fullmatch(s) is not None
                                     for s in d.values), bool, count=len(d))
                return table[np.clip(av, 0, len(d) - 1)] & avl, avl
            return np.zeros(self.n, bool), avl

        if op in ("add", "sub", "mul"):
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            if e.ftype.is_float:
                av = _f(av, A[0].ftype)
                bv = _f(bv, A[1].ftype)
            elif e.ftype.is_decimal and op in ("add", "sub"):
                av = _rescale(av, A[0].ftype, e.ftype.scale)
                bv = _rescale(bv, A[1].ftype, e.ftype.scale)
            fn = {"add": np.add, "sub": np.subtract, "mul": np.multiply}[op]
            return fn(av, bv), avl & bvl
        if op == "div":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            if e.ftype.is_float:
                av = _f(av, A[0].ftype)
                bv = _f(bv, A[1].ftype)
                nz = bv != 0
                return np.where(nz, av / np.where(nz, bv, 1.0), 0.0), \
                    avl & bvl & nz
            # exact decimal division via object ints
            sa = A[0].ftype.scale if A[0].ftype.is_decimal else 0
            sb = A[1].ftype.scale if A[1].ftype.is_decimal else 0
            target = e.ftype.scale
            nz = bv != 0
            ao = av.astype(object) * (10 ** (target - sa + sb))
            bo = np.where(nz, bv, 1).astype(object)
            q = np.abs(ao) // np.abs(bo)
            r = np.abs(ao) - q * np.abs(bo)
            q = q + (2 * r >= np.abs(bo))
            q = np.where((av < 0) != (bv < 0), -q, q)
            return q.astype(np.int64), avl & bvl & nz
        if op == "intdiv":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            nz = bv != 0
            sb = np.where(nz, bv, 1)
            q = np.abs(av) // np.abs(sb)
            q = np.where((av < 0) != (bv < 0), -q, q)
            return q, avl & bvl & nz
        if op == "mod":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            nz = bv != 0
            sb = np.where(nz, bv, 1)
            r = np.abs(av) % np.abs(sb)
            r = np.where(av < 0, -r, r)
            return r, avl & bvl & nz
        if op == "neg":
            av, avl = self.eval(A[0])
            return -av, avl
        if op == "abs":
            av, avl = self.eval(A[0])
            return np.abs(av), avl

        if op == "if":
            cv, cvl = _b(self.eval(A[0]))
            tv, tvl = self.eval(A[1])
            fv, fvl = self.eval(A[2])
            cond = cv & cvl
            return np.where(cond, tv, fv), np.where(cond, tvl, fvl)
        if op == "ifnull":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            return np.where(avl, av, bv), avl | bvl
        if op == "coalesce":
            out_v, out_vl = self.eval(A[0])
            for a in A[1:]:
                av, avl = self.eval(a)
                out_v = np.where(out_vl, out_v, av)
                out_vl = out_vl | avl
            return out_v, out_vl
        if op == "case":
            has_else = len(A) % 2 == 1
            pairs = (len(A) - 1) // 2 if has_else else len(A) // 2
            if has_else:
                out_v, out_vl = self.eval(A[-1])
                out_v = np.array(out_v, copy=True)
                out_vl = np.array(out_vl, copy=True)
            else:
                out_v = np.zeros(self.n, dtype=e.ftype.np_dtype)
                out_vl = np.zeros(self.n, dtype=bool)
            decided = np.zeros(self.n, dtype=bool)
            for i in range(pairs):
                cv, cvl = _b(self.eval(A[2 * i]))
                tv, tvl = self.eval(A[2 * i + 1])
                take = cv & cvl & ~decided
                out_v = np.where(take, tv, out_v)
                out_vl = np.where(take, tvl, out_vl)
                decided |= take
            return out_v, out_vl

        if op in ("year", "month", "day"):
            av, avl = self.eval(A[0])
            days = av
            if A[0].ftype.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
                days = av // 86_400_000_000
            y, m, d = _civil(days.astype(np.int64))
            return {"year": y, "month": m, "day": d}[op], avl
        if op == "date_add_days":
            av, avl = self.eval(A[0])
            return av + int(e.extra), avl
        if op == "cast":
            return self._cast(self.eval(A[0]), A[0].ftype, e.ftype)

        if op == "json_valid":
            import json as _json

            av, avl = self.eval_str(A[0])
            out = np.zeros(self.n, np.int64)
            for i, (s, v) in enumerate(zip(av, avl)):
                if v:
                    try:
                        _json.loads(s)
                        out[i] = 1
                    except ValueError:
                        pass
            return out, avl
        if op == "json_length":
            import json as _json

            av, avl = self.eval_str(A[0])
            out = np.zeros(self.n, np.int64)
            ok = np.zeros(self.n, bool)
            for i, (s, v) in enumerate(zip(av, avl)):
                if not v:
                    continue
                try:
                    doc = _json.loads(s)
                except ValueError:
                    continue
                out[i] = len(doc) if isinstance(doc, (list, dict)) else 1
                ok[i] = True
            return out, ok
        if op == "find_in_set":
            needle, nvl = self.eval_str(A[0])
            target = A[1]
            out = np.zeros(self.n, np.int64)
            if target.ftype.kind == TypeKind.SET:
                mv, mvl = self.eval(target)
                elems = target.ftype.elems
                for i, (s, m) in enumerate(zip(needle, mv)):
                    labels = [e for j, e in enumerate(elems)
                              if int(m) >> j & 1]
                    if s in labels:
                        out[i] = labels.index(s) + 1
                return out, nvl & mvl
            hv, hvl = self.eval_str(target)
            for i, (s, h) in enumerate(zip(needle, hv)):
                parts = h.split(",") if h else []
                if s in parts:
                    out[i] = parts.index(s) + 1
            return out, nvl & hvl

        raise NotImplementedError(f"host eval: {op}")

    def _compare(self, e: Call) -> VV:
        op = e.op
        a, b = e.args
        if a.ftype.is_string or b.ftype.is_string:
            ci = a.ftype.is_ci or b.ftype.is_ci
            if ci or isinstance(a, Call) or isinstance(b, Call):
                # ci collation or computed strings: compare in the
                # (casefolded) string domain (reference: collation-aware
                # compare, util/collate/collate.go:141)
                av2, avl = self.eval_str(a)
                bv2, bvl = self.eval_str(b)
                if ci:
                    av2 = np.array([s.casefold() for s in av2],
                                   dtype=object)
                    bv2 = np.array([s.casefold() for s in bv2],
                                   dtype=object)
            else:
                av, avl = self.eval(a)
                bv, bvl = self.eval(b)
                av2, bv2 = self._string_operands(a, av, b, bv, op)
        else:
            av, avl = self.eval(a)
            bv, bvl = self.eval(b)
            av2, bv2 = _align(a.ftype, av, b.ftype, bv)
        fn = {"eq": np.equal, "ne": np.not_equal, "lt": np.less,
              "le": np.less_equal, "gt": np.greater, "ge": np.greater_equal}[op]
        valid = avl & bvl
        return fn(av2, bv2) & valid, valid

    def _string_operands(self, a, av, b, bv, op):
        # code-space equality is only valid within ONE dictionary; any
        # cross-dictionary compare must go through the string domain
        same_dict = (
            isinstance(a, Col) and isinstance(b, Col)
            and a.ftype.is_string and b.ftype.is_string
            and self.dicts[a.idx] is self.dicts[b.idx]
        )
        col_vs_const = (
            (isinstance(a, Col) and isinstance(b, Const))
            or (isinstance(b, Col) and isinstance(a, Const))
        )

        def decode(e, v):
            if isinstance(e, Col) and e.ftype.is_string:
                d = self.dicts[e.idx]
                assert d is not None
                if op in ("eq", "ne") and (same_dict or col_vs_const):
                    return v  # codes compare fine within one dictionary
                vals = np.array(d.values + [""], dtype=object)
                return vals[np.clip(v, 0, len(d))]
            if isinstance(e, Const) and e.ftype.is_string:
                if op in ("eq", "ne"):
                    other = b if e is a else a
                    if isinstance(other, Col) and other.ftype.is_string:
                        d = self.dicts[other.idx]
                        assert d is not None
                        return np.full(self.n, d.lookup(str(e.value)),
                                       np.int64)
                return np.full(self.n, str(e.value), dtype=object)
            return v

        return decode(a, av), decode(b, bv)

    def _cast(self, vv: VV, src: FieldType, dst: FieldType) -> VV:
        v, vl = vv
        if dst.is_float:
            f = _f(v, src)
            return f, vl
        if dst.is_decimal:
            if src.is_decimal:
                return _rescale_round(v, src.scale, dst.scale), vl
            if src.is_integer:
                return v.astype(np.int64) * 10 ** dst.scale, vl
            if src.is_float:
                scaled = v * 10 ** dst.scale
                q = np.floor(np.abs(scaled) + 0.5)
                return np.where(scaled < 0, -q, q).astype(np.int64), vl
        if dst.is_integer:
            if src.is_decimal:
                return _rescale_round(v, src.scale, 0), vl
            if src.is_float:
                q = np.floor(np.abs(v) + 0.5)
                return np.where(v < 0, -q, q).astype(np.int64), vl
            return v.astype(np.int64), vl
        if dst.is_string and src.is_string:
            return v, vl
        raise NotImplementedError(f"host cast {src!r} -> {dst!r}")


# ---- helpers ----------------------------------------------------------------

def _truthy(v: np.ndarray) -> np.ndarray:
    if v.dtype != np.bool_:
        return v != 0
    return v


def _substring(s: str, start: int, length: Optional[int]) -> str:
    """MySQL SUBSTRING: 1-based; negative start counts from the end;
    start=0 yields ''. (reference: expression/builtin_string.go substring)"""
    if start == 0:
        return ""
    if start > 0:
        i = start - 1
    else:
        i = len(s) + start
        if i < 0:
            return ""
    if length is None:
        return s[i:]
    if length <= 0:
        return ""
    return s[i:i + length]


def _json_path_steps(path: str) -> Optional[list]:
    """'$.a.b[2]' -> ['a', 'b', 2]; None for malformed paths.
    Subset of the reference's path grammar (types/json/path_expr.go):
    member access and array indexing, no wildcards."""
    import re as _re

    if not path.startswith("$"):
        return None
    steps: list = []
    for m in _re.finditer(r"\.(\w+)|\.\"([^\"]+)\"|\[(\d+)\]|(.)",
                          path[1:]):
        if m.group(4) is not None:
            return None  # junk character
        if m.group(3) is not None:
            steps.append(int(m.group(3)))
        else:
            steps.append(m.group(1) or m.group(2))
    return steps


def _json_extract(doc: str, path: str):
    """JSON-serialized value at path, or None (missing/invalid)."""
    import json as _json

    try:
        v = _json.loads(doc)
    except ValueError:
        return None
    steps = _json_path_steps(path)
    if steps is None:
        return None
    for s in steps:
        if isinstance(s, int):
            if not isinstance(v, list) or s >= len(v):
                return None
            v = v[s]
        else:
            if not isinstance(v, dict) or s not in v:
                return None
            v = v[s]
    return _json.dumps(v, sort_keys=True, separators=(", ", ": "))


def _json_unquote(s: str) -> str:
    import json as _json

    if len(s) >= 2 and s[0] == '"' and s[-1] == '"':
        try:
            return str(_json.loads(s))
        except ValueError:
            return s
    return s


def _json_type_name(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "ARRAY"
    return "OBJECT"


def _b(vv: VV) -> VV:
    v, vl = vv
    return _truthy(np.asarray(v)), vl


def _f(v: np.ndarray, ft: FieldType) -> np.ndarray:
    out = v.astype(np.float64)
    if ft.is_decimal:
        out = out / 10 ** ft.scale
    return out


def _rescale(v: np.ndarray, ft: FieldType, target_scale: int) -> np.ndarray:
    s = ft.scale if ft.is_decimal else 0
    if s < target_scale:
        return v.astype(np.int64) * 10 ** (target_scale - s)
    return v


def _rescale_round(v: np.ndarray, s: int, target: int) -> np.ndarray:
    if s == target:
        return v
    if s < target:
        return v * 10 ** (target - s)
    f = 10 ** (s - target)
    q = (np.abs(v) + f // 2) // f
    return np.where(v < 0, -q, q)


def _align(at: FieldType, av, bt: FieldType, bv):
    if at.is_float or bt.is_float:
        return _f(av, at), _f(bv, bt)
    sa = at.scale if at.is_decimal else 0
    sb = bt.scale if bt.is_decimal else 0
    if sa < sb:
        av = av.astype(np.int64) * 10 ** (sb - sa)
    elif sb < sa:
        bv = bv.astype(np.int64) * 10 ** (sa - sb)
    return av, bv


def _civil(z: np.ndarray):
    z = z + 719_468
    era = np.where(z >= 0, z, z - 146_096) // 146_097
    doe = z - era * 146_097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    y = np.where(m <= 2, y + 1, y)
    return y, m, d
