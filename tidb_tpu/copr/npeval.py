"""NumpyEval: vectorized host-side expression evaluation.

The numpy twin of copr/eval.py (reference keeps the same duality: row-based
eval* alongside vectorized vecEval*, expression/builtin_*.go). Shared by the
host coprocessor fallback (copr/host_exec.py) and the host volcano operators
(executor/) for selections, projections, join/sort keys, and complete
aggregation over operator output chunks.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..chunk.column import Dictionary
from ..plan.expr import Call, Col, Const, PlanExpr
from ..types.field_type import FieldType, TypeKind

VV = tuple[np.ndarray, np.ndarray]


class NumpyEval:
    """Evaluates resolved expressions over (data, valid) numpy column pairs."""

    def __init__(
        self,
        cols: list[VV],
        dicts: list[Optional[Dictionary]],
        n: int,
    ) -> None:
        self.cols = cols
        self.dicts = dicts
        self.n = n

    # ---- string-domain evaluation -------------------------------------------
    def _registry_call(self, e: Call) -> VV:
        """Breadth-layer builtins (copr/funcs.py): rowwise Python with
        the registry's NULL semantics; args arrive in their natural
        domains (str / day-number int / EXACT stdlib decimal.Decimal for
        DECIMAL columns / int). The reference keeps exact MyDecimal
        semantics through every builtin (types/mydecimal.go); the r04
        decimal-as-float shortcut was a silent precision loss."""
        import decimal as _pydec

        from .. import obs
        from .funcs import REGISTRY

        fd = REGISTRY[e.op[3:]]
        vec = self._dict_vec_call(e, fd)
        if vec is not None:
            return vec
        # the de-vectorization tax, attributed per function: surfaced
        # through metrics_schema.tidb_registry_row_eval_total and the
        # registry-row-eval inspection rule
        obs.REGISTRY_ROW_EVALS.inc(self.n, func=fd.name)
        arg_vv = []
        for a in e.args:
            if a.ftype.is_string:
                v, vl = self.eval_str(a)
                dec_scale = None
            else:
                v, vl = self.eval(a)
                v = np.asarray(v)
                dec_scale = a.ftype.scale if a.ftype.is_decimal else None
            arg_vv.append((v, np.asarray(vl), dec_scale))
        n = self.n
        out = np.empty(n, dtype=object)
        valid = np.zeros(n, bool)
        for i in range(n):
            vals = []
            has_null = False
            for v, vl, dec_scale in arg_vv:
                if vl[i]:
                    x = v[i]
                    x = x.item() if hasattr(x, "item") else x
                    if dec_scale is not None:
                        # exact: unscaled int / 10**scale in the decimal
                        # domain, no float round trip
                        x = _pydec.Decimal(int(x)).scaleb(-dec_scale)
                    vals.append(x)
                else:
                    vals.append(None)
                    has_null = True
            if has_null and fd.null_prop:
                continue
            try:
                r = fd.fn(*vals)
            except (ValueError, TypeError, OverflowError,
                    ZeroDivisionError):
                r = None
            if r is not None:
                out[i] = r
                valid[i] = True
        return self._coerce_registry(e, fd, out, valid)

    def _dict_vec_call(self, e: Call, fd) -> Optional[VV]:
        """Dictionary-vectorized registry call: when the ONE string
        argument is a plain dict-coded column and every other argument
        is a constant, evaluate the builtin once per DISTINCT dictionary
        value and gather per row by code — len(dict) Python calls
        instead of n (the de-vectorization the registry-row-eval rule
        watches). Returns None when the shape doesn't apply and the
        per-row path must run."""
        import decimal as _pydec

        if not fd.dict_vec or not fd.null_prop:
            return None
        col_pos = None
        consts: dict[int, object] = {}
        for i, a in enumerate(e.args):
            if isinstance(a, Col) and a.ftype.is_string:
                if col_pos is not None:
                    return None  # two string columns: no single domain
                col_pos = i
            elif isinstance(a, Const):
                if a.value is None:
                    return None  # NULL const: per-row path propagates
                if a.ftype.is_string:
                    consts[i] = str(a.value)
                elif a.ftype.is_decimal:
                    consts[i] = _pydec.Decimal(
                        int(a.value)).scaleb(-a.ftype.scale)
                elif isinstance(a.value, (int, float, bool)):
                    consts[i] = a.value
                else:
                    return None
            else:
                return None
        if col_pos is None:
            return None
        c = e.args[col_pos]
        d = self.dicts[c.idx] if c.idx < len(self.dicts) else None
        if d is None or len(d) == 0 or len(d) > max(self.n, 1):
            return None  # fewer rows than values: per-row is cheaper
        codes, vl = self.cols[c.idx]
        dvals = np.empty(len(d), dtype=object)
        dok = np.zeros(len(d), bool)
        args = [consts.get(i) for i in range(len(e.args))]
        for ci, sval in enumerate(d.values):
            args[col_pos] = sval
            try:
                r = fd.fn(*args)
            except (ValueError, TypeError, OverflowError,
                    ZeroDivisionError):
                r = None
            if r is not None:
                dvals[ci] = r
                dok[ci] = True
        safe = np.clip(codes, 0, len(d) - 1)
        out = dvals[safe]
        valid = np.asarray(vl) & dok[safe]
        out = np.where(valid, out, None)
        return self._coerce_registry(e, fd, out, valid)

    def _coerce_registry(self, e: Call, fd, out: np.ndarray,
                         valid: np.ndarray) -> VV:
        """Registry results (object array) -> the typed (data, valid)
        pair per the FuncDef's declared return domain."""
        import decimal as _pydec

        n = self.n
        if fd.ret == "str":
            # string consumers read through eval_str (object array)
            for i in range(n):
                if not valid[i]:
                    out[i] = ""
            return out, valid
        idx = np.nonzero(valid)[0]
        if fd.ret == "float" or (fd.ret == "arg0" and e.ftype.is_float):
            arr = np.zeros(n, np.float64)
            if len(idx):
                arr[idx] = [float(out[i]) for i in idx]
        elif fd.ret == "arg0" and e.ftype.is_decimal:
            # exact fixed-point: Decimal/int results rescale without a
            # float round trip (MySQL half-away-from-zero on narrowing);
            # float results (float-natured fns) round at their precision
            import decimal as _pydec

            arr = np.zeros(n, np.int64)
            if len(idx):
                m = e.ftype.scale

                def _fix(r):
                    if isinstance(r, float):
                        r = _pydec.Decimal(repr(r))
                    elif not isinstance(r, _pydec.Decimal):
                        r = _pydec.Decimal(int(r))
                    return int(r.scaleb(m).to_integral_value(
                        rounding=_pydec.ROUND_HALF_UP))

                arr[idx] = [_fix(out[i]) for i in idx]
        else:
            arr = np.zeros(n, np.int64)
            if len(idx):
                arr[idx] = [int(out[i]) for i in idx]
        return arr, valid

    def eval_str(self, e: PlanExpr) -> VV:
        """Evaluate a string-typed expression to (object array of str, valid).

        Used when the value crosses dictionary domains (CASE branches,
        IFNULL over different columns, literals) — the caller re-encodes the
        result into a fresh dictionary."""
        if isinstance(e, Col):
            codes, vl = self.cols[e.idx]
            d = self.dicts[e.idx]
            if d is None or len(d) == 0:
                return np.full(self.n, "", dtype=object), \
                    np.zeros(self.n, bool) if d is None else vl
            vals = np.array(d.values, dtype=object)
            return vals[np.clip(codes, 0, len(d) - 1)], vl
        if isinstance(e, Const):
            if e.value is None:
                return (np.full(self.n, "", dtype=object),
                        np.zeros(self.n, bool))
            return (np.full(self.n, str(e.value), dtype=object),
                    np.ones(self.n, bool))
        assert isinstance(e, Call)
        op = e.op
        A = e.args
        if op.startswith("fx:"):
            return self._registry_call(e)
        if op == "if":
            cv, cvl = _b(self.eval(A[0]))
            tv, tvl = self.eval_str(A[1])
            fv, fvl = self.eval_str(A[2])
            cond = cv & cvl
            return np.where(cond, tv, fv), np.where(cond, tvl, fvl)
        if op == "ifnull":
            av, avl = self.eval_str(A[0])
            bv, bvl = self.eval_str(A[1])
            return np.where(avl, av, bv), avl | bvl
        if op == "coalesce":
            out_v, out_vl = self.eval_str(A[0])
            for a in A[1:]:
                av, avl = self.eval_str(a)
                out_v = np.where(out_vl, out_v, av)
                out_vl = out_vl | avl
            return out_v, out_vl
        if op == "case":
            has_else = len(A) % 2 == 1
            pairs = (len(A) - 1) // 2 if has_else else len(A) // 2
            if has_else:
                out_v, out_vl = self.eval_str(A[-1])
                out_v = np.array(out_v, copy=True)
                out_vl = np.array(out_vl, copy=True)
            else:
                out_v = np.full(self.n, "", dtype=object)
                out_vl = np.zeros(self.n, bool)
            decided = np.zeros(self.n, bool)
            for i in range(pairs):
                cv, cvl = _b(self.eval(A[2 * i]))
                tv, tvl = self.eval_str(A[2 * i + 1])
                take = cv & cvl & ~decided
                out_v = np.where(take, tv, out_v)
                out_vl = np.where(take, tvl, out_vl)
                decided |= take
            return out_v, out_vl
        if op == "substring":
            av, avl = self.eval_str(A[0])
            start, length = e.extra
            out = np.empty(self.n, dtype=object)
            for i, s in enumerate(av):
                out[i] = _substring(s, start, length)
            return out, avl
        if op in ("greatest", "least"):
            # string-domain comparison (numeric GREATEST lives in _call)
            fn = max if op == "greatest" else min
            parts = [self.eval_str(a) for a in A]
            valid = parts[0][1].copy()
            for _, vl in parts[1:]:
                valid = valid & vl  # MySQL: any NULL -> NULL
            out = np.array([fn(p[0][i] for p in parts)
                            for i in range(self.n)], dtype=object)
            return out, valid
        if op in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse"):
            av, avl = self.eval_str(A[0])
            fn = {"upper": str.upper, "lower": str.lower,
                  "trim": str.strip, "ltrim": str.lstrip,
                  "rtrim": str.rstrip,
                  "reverse": lambda s: s[::-1]}[op]
            return (np.array([fn(s) for s in av], dtype=object), avl)
        if op in ("concat", "concat_ws"):
            parts = [self._any_str(a) for a in A]
            n = self.n
            if op == "concat":
                # MySQL: any NULL argument -> NULL
                valid = parts[0][1].copy()
                for _, vl in parts[1:]:
                    valid = valid & vl
                out = np.array(
                    ["".join(p[0][i] for p in parts) for i in range(n)],
                    dtype=object)
                return out, valid
            sep, sep_ok = parts[0]
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = sep[i].join(p[0][i] for p in parts[1:]
                                     if p[1][i])  # NULL args skipped
            return out, sep_ok
        if op in ("left", "right", "repeat"):
            av, avl = self.eval_str(A[0])
            nv, nvl = self.eval(A[1])
            out = np.empty(self.n, dtype=object)
            for i, (s, k) in enumerate(zip(av, nv)):
                k = max(int(k), 0)
                out[i] = (s[:k] if op == "left" else
                          s[-k:] if (op == "right" and k) else
                          s * k if op == "repeat" else "")
            return out, avl & nvl
        if op == "replace":
            av, avl = self.eval_str(A[0])
            fv, fvl = self.eval_str(A[1])
            tv, tvl = self.eval_str(A[2])
            if any(a.ftype.is_ci for a in A):
                import re as _re
                out = np.array(
                    [_re.sub(_re.escape(f), t.replace("\\", "\\\\"), s,
                             flags=_re.IGNORECASE) if f else s
                     for s, f, t in zip(av, fv, tv)], dtype=object)
            else:
                out = np.array([s.replace(f, t) if f else s
                                for s, f, t in zip(av, fv, tv)],
                               dtype=object)
            return out, avl & fvl & tvl
        if op in ("lpad", "rpad"):
            av, avl = self.eval_str(A[0])
            nv, nvl = self.eval(A[1])
            pv, pvl = self.eval_str(A[2])
            out = np.empty(self.n, dtype=object)
            ok = avl & nvl & pvl
            for i, (s, k, p) in enumerate(zip(av, nv, pv)):
                k = int(k)
                if k < 0:  # MySQL: negative length -> NULL
                    out[i] = ""
                    ok[i] = False
                elif k < len(s):
                    out[i] = s[:k]
                elif not p:
                    out[i] = s if k <= len(s) else ""
                    ok[i] = ok[i] and k <= len(s)
                else:
                    pad = (p * ((k - len(s)) // len(p) + 1))[:k - len(s)]
                    out[i] = pad + s if op == "lpad" else s + pad
            return out, ok
        if op == "json_extract":
            av, avl = self.eval_str(A[0])
            out = np.full(self.n, "", dtype=object)
            ok = np.zeros(self.n, bool)
            for i, (s, v) in enumerate(zip(av, avl)):
                if not v:
                    continue
                r = _json_extract(s, str(e.extra))
                if r is not None:
                    out[i] = r
                    ok[i] = True
            return out, ok
        if op == "json_unquote":
            av, avl = self.eval_str(A[0])
            out = np.empty(self.n, dtype=object)
            for i, s in enumerate(av):
                out[i] = _json_unquote(s)
            return out, avl
        if op == "json_type":
            import json as _json

            av, avl = self.eval_str(A[0])
            out = np.full(self.n, "", dtype=object)
            ok = np.zeros(self.n, bool)
            for i, (s, v) in enumerate(zip(av, avl)):
                if not v:
                    continue
                try:
                    out[i] = _json_type_name(_json.loads(s))
                    ok[i] = True
                except ValueError:
                    pass
            return out, ok
        raise NotImplementedError(f"string eval: {op}")

    # ---- evaluation ---------------------------------------------------------
    def eval(self, e: PlanExpr) -> VV:
        if isinstance(e, Col):
            return self.cols[e.idx]
        if isinstance(e, Const):
            if e.value is None:
                return (np.zeros(self.n, dtype=e.ftype.np_dtype),
                        np.zeros(self.n, dtype=bool))
            v = e.value
            if e.ftype.is_string:
                # resolved per comparison; free-standing only for eq against
                # another string expr handled below
                return (np.full(self.n, -2, dtype=np.int64),
                        np.ones(self.n, dtype=bool))
            return (np.full(self.n, v, dtype=e.ftype.np_dtype),
                    np.ones(self.n, dtype=bool))
        assert isinstance(e, Call)
        return self._call(e)

    def _call(self, e: Call) -> VV:
        op = e.op
        A = e.args

        if op.startswith("fx:"):
            return self._registry_call(e)
        if op == "and":
            av, avl = _b(self.eval(A[0]))
            bv, bvl = _b(self.eval(A[1]))
            known_false = (avl & ~av) | (bvl & ~bv)
            valid = (avl & bvl) | known_false
            return av & bv & valid, valid
        if op == "or":
            av, avl = _b(self.eval(A[0]))
            bv, bvl = _b(self.eval(A[1]))
            value = (av & avl) | (bv & bvl)
            valid = (avl & bvl) | value
            return value, valid
        if op == "not":
            av, avl = _b(self.eval(A[0]))
            return (~av) & avl, avl
        if op == "isnull":
            _, avl = self.eval(A[0])
            return ~avl, np.ones_like(avl)
        if op == "rand_seeded":
            # one Random(seed) per evaluation, successive draws per row
            # (MySQL RAND(N) semantics, builtin_math.go randWithSeed)
            import random as _random
            rng = _random.Random(int(A[0].value))
            vals = np.fromiter((rng.random() for _ in range(self.n)),
                               np.float64, count=self.n)
            return vals, np.ones(self.n, bool)

        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            return self._compare(e)

        if op == "in_values":
            arg = A[0]
            if arg.ftype.is_string and isinstance(arg, Col):
                av, avl = self.eval(arg)
                d = self.dicts[arg.idx]
                assert d is not None
                if arg.ftype.is_ci:
                    canon = d.ci_canonical() if len(d) else \
                        np.zeros(0, np.int64)
                    codes = [d.lookup_ci(str(v)) for v in e.extra]
                    av = canon[np.clip(av, 0, max(len(d) - 1, 0))] \
                        if len(d) else av
                else:
                    codes = [d.lookup(str(v)) for v in e.extra]
                hit = np.isin(av, [c for c in codes if c >= 0])
            elif arg.ftype.is_string:
                # computed string (e.g. substring): string-domain membership
                sv, svl = self.eval_str(arg)
                hit = np.isin(sv, np.array([str(v) for v in e.extra],
                                           dtype=object))
                return hit & svl, svl
            else:
                av, avl = self.eval(arg)
                vals = e.extra
                hit = np.isin(av, np.array(vals))
            return hit & avl, avl
        if op == "like":
            import re

            from .client import _like_to_regex
            arg = A[0]
            flags = re.DOTALL
            if arg.ftype.is_ci:
                flags |= re.IGNORECASE  # ci collation LIKE
            rx = re.compile(_like_to_regex(str(e.extra)), flags)
            if not isinstance(arg, Col):
                sv, svl = self.eval_str(arg)
                hit = np.fromiter((rx.fullmatch(s) is not None for s in sv),
                                  bool, count=self.n)
                return hit & svl, svl
            av, avl = self.eval(arg)
            d = self.dicts[arg.idx]
            assert d is not None
            if len(d):
                table = np.fromiter((rx.fullmatch(s) is not None
                                     for s in d.values), bool, count=len(d))
                return table[np.clip(av, 0, len(d) - 1)] & avl, avl
            return np.zeros(self.n, bool), avl

        if op in ("add", "sub", "mul"):
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            if e.ftype.is_float:
                av = _f(av, A[0].ftype)
                bv = _f(bv, A[1].ftype)
            elif e.ftype.is_decimal and op in ("add", "sub"):
                av = _rescale(av, A[0].ftype, e.ftype.scale)
                bv = _rescale(bv, A[1].ftype, e.ftype.scale)
            fn = {"add": np.add, "sub": np.subtract, "mul": np.multiply}[op]
            return fn(av, bv), avl & bvl
        if op == "div":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            if e.ftype.is_float:
                av = _f(av, A[0].ftype)
                bv = _f(bv, A[1].ftype)
                nz = bv != 0
                return np.where(nz, av / np.where(nz, bv, 1.0), 0.0), \
                    avl & bvl & nz
            # exact decimal division via object ints
            sa = A[0].ftype.scale if A[0].ftype.is_decimal else 0
            sb = A[1].ftype.scale if A[1].ftype.is_decimal else 0
            target = e.ftype.scale
            nz = bv != 0
            ao = av.astype(object) * (10 ** (target - sa + sb))
            bo = np.where(nz, bv, 1).astype(object)
            q = np.abs(ao) // np.abs(bo)
            r = np.abs(ao) - q * np.abs(bo)
            q = q + (2 * r >= np.abs(bo))
            q = np.where((av < 0) != (bv < 0), -q, q)
            return q.astype(np.int64), avl & bvl & nz
        if op == "intdiv":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            nz = bv != 0
            sb = np.where(nz, bv, 1)
            q = np.abs(av) // np.abs(sb)
            q = np.where((av < 0) != (bv < 0), -q, q)
            return q, avl & bvl & nz
        if op == "mod":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            nz = bv != 0
            sb = np.where(nz, bv, 1)
            r = np.abs(av) % np.abs(sb)
            r = np.where(av < 0, -r, r)
            return r, avl & bvl & nz
        if op == "neg":
            av, avl = self.eval(A[0])
            return -av, avl
        if op == "abs":
            av, avl = self.eval(A[0])
            return np.abs(av), avl

        if op == "if":
            cv, cvl = _b(self.eval(A[0]))
            tv, tvl = self.eval(A[1])
            fv, fvl = self.eval(A[2])
            cond = cv & cvl
            return np.where(cond, tv, fv), np.where(cond, tvl, fvl)
        if op == "ifnull":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            return np.where(avl, av, bv), avl | bvl
        if op == "coalesce":
            out_v, out_vl = self.eval(A[0])
            for a in A[1:]:
                av, avl = self.eval(a)
                out_v = np.where(out_vl, out_v, av)
                out_vl = out_vl | avl
            return out_v, out_vl
        if op == "case":
            has_else = len(A) % 2 == 1
            pairs = (len(A) - 1) // 2 if has_else else len(A) // 2
            if has_else:
                out_v, out_vl = self.eval(A[-1])
                out_v = np.array(out_v, copy=True)
                out_vl = np.array(out_vl, copy=True)
            else:
                out_v = np.zeros(self.n, dtype=e.ftype.np_dtype)
                out_vl = np.zeros(self.n, dtype=bool)
            decided = np.zeros(self.n, dtype=bool)
            for i in range(pairs):
                cv, cvl = _b(self.eval(A[2 * i]))
                tv, tvl = self.eval(A[2 * i + 1])
                take = cv & cvl & ~decided
                out_v = np.where(take, tv, out_v)
                out_vl = np.where(take, tvl, out_vl)
                decided |= take
            return out_v, out_vl

        if op in ("year", "month", "day"):
            av, avl = self.eval(A[0])
            days = av
            if A[0].ftype.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
                days = av // 86_400_000_000
            y, m, d = _civil(days.astype(np.int64))
            return {"year": y, "month": m, "day": d}[op], avl
        if op == "date_add_days":
            av, avl = self.eval(A[0])
            return av + int(e.extra), avl
        if op == "cast":
            return self._cast(self.eval(A[0]), A[0].ftype, e.ftype)

        if op == "json_valid":
            import json as _json

            av, avl = self.eval_str(A[0])
            out = np.zeros(self.n, np.int64)
            for i, (s, v) in enumerate(zip(av, avl)):
                if v:
                    try:
                        _json.loads(s)
                        out[i] = 1
                    except ValueError:
                        pass
            return out, avl
        if op == "json_length":
            import json as _json

            av, avl = self.eval_str(A[0])
            out = np.zeros(self.n, np.int64)
            ok = np.zeros(self.n, bool)
            for i, (s, v) in enumerate(zip(av, avl)):
                if not v:
                    continue
                try:
                    doc = _json.loads(s)
                except ValueError:
                    continue
                out[i] = len(doc) if isinstance(doc, (list, dict)) else 1
                ok[i] = True
            return out, ok
        if op == "find_in_set":
            needle, nvl = self.eval_str(A[0])
            target = A[1]
            out = np.zeros(self.n, np.int64)
            if target.ftype.kind == TypeKind.SET:
                mv, mvl = self.eval(target)
                elems = target.ftype.elems
                for i, (s, m) in enumerate(zip(needle, mv)):
                    labels = [e for j, e in enumerate(elems)
                              if int(m) >> j & 1]
                    if s in labels:
                        out[i] = labels.index(s) + 1
                return out, nvl & mvl
            hv, hvl = self.eval_str(target)
            for i, (s, h) in enumerate(zip(needle, hv)):
                parts = h.split(",") if h else []
                if s in parts:
                    out[i] = parts.index(s) + 1
            return out, nvl & hvl

        if op in ("length", "char_length", "ascii"):
            sv, svl = self.eval_str(A[0])
            if op == "ascii":
                out = np.array([ord(s[0]) if s else 0 for s in sv],
                               np.int64)
            elif op == "length":
                out = np.array([len(s.encode("utf-8")) for s in sv],
                               np.int64)
            else:
                out = np.array([len(s) for s in sv], np.int64)
            return out, svl
        if op == "locate":
            nv, nvl = self.eval_str(A[0])
            hv, hvl = self.eval_str(A[1])
            if any(a.ftype.is_ci for a in A):
                out = np.array(
                    [h.casefold().find(sub.casefold()) + 1
                     for sub, h in zip(nv, hv)], np.int64)
            else:
                out = np.array([h.find(sub) + 1
                                for sub, h in zip(nv, hv)], np.int64)
            return out, nvl & hvl

        if op in ("round", "truncate"):
            av, avl = self.eval(A[0])
            d = int(e.extra or 0)
            at = A[0].ftype
            if at.is_float:
                scaled = np.asarray(av, np.float64) * (10.0 ** d)
                if op == "round":
                    q = np.floor(np.abs(scaled) + 0.5)
                else:
                    q = np.floor(np.abs(scaled))
                return np.where(scaled < 0, -q, q) / (10.0 ** d), avl
            s = at.scale if at.is_decimal else 0
            target = e.ftype.scale if e.ftype.is_decimal else 0
            v = np.asarray(av, np.int64)
            if d < 0:
                # single division covering both the scale drop and the
                # coarse digits (two-step rounding would compound:
                # ROUND(44.5, -1) must be 40, not 50)
                f = 10 ** (s - d)
                q = (np.abs(v) + (f // 2 if op == "round" else 0)) // f
                q = q * 10 ** (-d)
                return np.where(v < 0, -q, q), avl
            drop = s - max(target, 0) if s > max(target, 0) else 0
            if drop > 0:
                f = 10 ** drop
                q = (np.abs(v) + (f // 2 if op == "round" else 0)) // f
                v = np.where(v < 0, -q, q)
            return v, avl
        if op in ("floor", "ceil"):
            av, avl = self.eval(A[0])
            at = A[0].ftype
            if at.is_float:
                f = np.floor if op == "floor" else np.ceil
                return f(np.asarray(av, np.float64)), avl
            if at.is_decimal:
                s = 10 ** at.scale
                v = np.asarray(av, np.int64)
                if op == "floor":
                    return v // s, avl
                return -((-v) // s), avl
            return np.asarray(av, np.int64), avl
        if op in ("sqrt", "exp", "ln", "log2", "log10"):
            av, avl = self.eval(A[0])
            f = _f(np.asarray(av), A[0].ftype)
            fn = {"sqrt": np.sqrt, "exp": np.exp, "ln": np.log,
                  "log2": np.log2, "log10": np.log10}[op]
            with np.errstate(invalid="ignore", divide="ignore"):
                out = fn(f)
            ok = np.isfinite(out)  # MySQL: out-of-domain -> NULL
            return np.where(ok, out, 0.0), avl & ok
        if op == "log_base":
            bv, bvl = self.eval(A[0])
            xv, xvl = self.eval(A[1])
            b = _f(np.asarray(bv), A[0].ftype)
            x = _f(np.asarray(xv), A[1].ftype)
            with np.errstate(invalid="ignore", divide="ignore"):
                out = np.log(x) / np.log(b)
            ok = np.isfinite(out)
            return np.where(ok, out, 0.0), bvl & xvl & ok
        if op == "pow":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])
            with np.errstate(invalid="ignore", over="ignore"):
                out = np.power(_f(np.asarray(av), A[0].ftype),
                               _f(np.asarray(bv), A[1].ftype))
            ok = np.isfinite(out)
            return np.where(ok, out, 0.0), avl & bvl & ok
        if op == "sign":
            av, avl = self.eval(A[0])
            return np.sign(np.asarray(av)).astype(np.int64), avl
        if op in ("greatest", "least"):
            if e.ftype.is_string:
                raise NotImplementedError(
                    "string GREATEST/LEAST evaluates via eval_str")
            fn = np.maximum if op == "greatest" else np.minimum
            out_v, out_vl = None, None
            for a in A:
                v, vl = self.eval(a)
                v = np.asarray(v)
                if e.ftype.is_float:
                    v = _f(v, a.ftype)
                elif e.ftype.is_decimal:
                    v = _rescale(v, a.ftype, e.ftype.scale)
                if out_v is None:
                    out_v, out_vl = v, vl
                else:
                    out_v = fn(out_v, v)
                    out_vl = out_vl & vl  # MySQL: any NULL -> NULL
            return out_v, out_vl

        if op in ("dayofweek", "weekday", "dayofyear", "quarter"):
            av, avl = self.eval(A[0])
            days = np.asarray(av, np.int64)
            if A[0].ftype.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
                days = days // 86_400_000_000
            if op == "dayofweek":   # 1 = Sunday (1970-01-01 is Thursday)
                return (days + 4) % 7 + 1, avl
            if op == "weekday":     # 0 = Monday
                return (days + 3) % 7, avl
            y, m, d = _civil(days)
            if op == "quarter":
                return ((m - 1) // 3 + 1).astype(np.int64), avl
            jan1 = _days_from_civil(y, np.ones_like(m), np.ones_like(d))
            return days - jan1 + 1, avl
        if op in ("hour", "minute", "second"):
            av, avl = self.eval(A[0])
            us = np.asarray(av, np.int64)
            if A[0].ftype.kind == TypeKind.TIME:
                # TIME is a signed duration: components of |t|, hours
                # unbounded (MySQL HOUR('-26:30:00') = 26)
                sec = np.abs(us) // 1_000_000
                if op == "hour":
                    return sec // 3600, avl
            else:
                sec = us // 1_000_000
                if op == "hour":
                    return (sec // 3600) % 24, avl
            if op == "minute":
                return (sec // 60) % 60, avl
            return sec % 60, avl
        if op == "to_date":
            av, avl = self.eval(A[0])
            v = np.asarray(av, np.int64)
            if A[0].ftype.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
                v = v // 86_400_000_000
            return v.astype(np.int32), avl
        if op == "last_day":
            av, avl = self.eval(A[0])
            days = np.asarray(av, np.int64)
            if A[0].ftype.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
                days = days // 86_400_000_000
            y, m, _d = _civil(days)
            ny = np.where(m == 12, y + 1, y)
            nm = np.where(m == 12, 1, m + 1)
            nxt = _days_from_civil(ny, nm, np.ones_like(nm))
            return (nxt - 1).astype(np.int32), avl
        if op == "datediff":
            av, avl = self.eval(A[0])
            bv, bvl = self.eval(A[1])

            def to_days(v, ft):
                v = np.asarray(v, np.int64)
                if ft.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
                    v = v // 86_400_000_000
                return v
            return (to_days(av, A[0].ftype) - to_days(bv, A[1].ftype),
                    avl & bvl)

        raise NotImplementedError(f"host eval: {op}")

    def _any_str(self, a: PlanExpr) -> VV:
        """Any-typed expression stringified MySQL-style (CONCAT coercion:
        ints plain, decimals at column scale, dates ISO)."""
        if a.ftype.is_string:
            return self.eval_str(a)
        v, vl = self.eval(a)
        v = np.asarray(v)
        ft = a.ftype
        out = np.empty(self.n, dtype=object)
        if ft.is_decimal:
            from ..types.value import Decimal as _D
            s = ft.scale
            for i, x in enumerate(v):
                out[i] = str(_D(int(x), s))
        elif ft.kind == TypeKind.DATE:
            from ..types.value import decode_date
            for i, x in enumerate(v):
                out[i] = decode_date(int(x)).isoformat()
        elif ft.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
            from ..types.value import decode_datetime
            for i, x in enumerate(v):
                out[i] = decode_datetime(int(x)).isoformat(" ")
        elif ft.is_float:
            for i, x in enumerate(v):
                f = float(x)
                out[i] = repr(f) if not f.is_integer() else str(int(f))
        else:
            for i, x in enumerate(v):
                out[i] = str(int(x))
        return out, np.asarray(vl)

    def _compare(self, e: Call) -> VV:
        op = e.op
        a, b = e.args
        if a.ftype.is_string or b.ftype.is_string:
            ci = a.ftype.is_ci or b.ftype.is_ci
            if ci or isinstance(a, Call) or isinstance(b, Call):
                # ci collation or computed strings: compare in the
                # (casefolded) string domain (reference: collation-aware
                # compare, util/collate/collate.go:141)
                av2, avl = self.eval_str(a)
                bv2, bvl = self.eval_str(b)
                if ci:
                    av2 = np.array([s.casefold() for s in av2],
                                   dtype=object)
                    bv2 = np.array([s.casefold() for s in bv2],
                                   dtype=object)
            else:
                av, avl = self.eval(a)
                bv, bvl = self.eval(b)
                av2, bv2 = self._string_operands(a, av, b, bv, op)
        else:
            av, avl = self.eval(a)
            bv, bvl = self.eval(b)
            av2, bv2 = _align(a.ftype, av, b.ftype, bv)
        fn = {"eq": np.equal, "ne": np.not_equal, "lt": np.less,
              "le": np.less_equal, "gt": np.greater, "ge": np.greater_equal}[op]
        valid = avl & bvl
        return fn(av2, bv2) & valid, valid

    def _string_operands(self, a, av, b, bv, op):
        # code-space equality is only valid within ONE dictionary; any
        # cross-dictionary compare must go through the string domain
        same_dict = (
            isinstance(a, Col) and isinstance(b, Col)
            and a.ftype.is_string and b.ftype.is_string
            and self.dicts[a.idx] is self.dicts[b.idx]
        )
        col_vs_const = (
            (isinstance(a, Col) and isinstance(b, Const))
            or (isinstance(b, Col) and isinstance(a, Const))
        )

        def decode(e, v):
            if isinstance(e, Col) and e.ftype.is_string:
                d = self.dicts[e.idx]
                assert d is not None
                if op in ("eq", "ne") and (same_dict or col_vs_const):
                    return v  # codes compare fine within one dictionary
                vals = np.array(d.values + [""], dtype=object)
                return vals[np.clip(v, 0, len(d))]
            if isinstance(e, Const) and e.ftype.is_string:
                if op in ("eq", "ne"):
                    other = b if e is a else a
                    if isinstance(other, Col) and other.ftype.is_string:
                        d = self.dicts[other.idx]
                        assert d is not None
                        return np.full(self.n, d.lookup(str(e.value)),
                                       np.int64)
                return np.full(self.n, str(e.value), dtype=object)
            return v

        return decode(a, av), decode(b, bv)

    def _cast(self, vv: VV, src: FieldType, dst: FieldType) -> VV:
        v, vl = vv
        if dst.is_float:
            f = _f(v, src)
            return f, vl
        if dst.is_decimal:
            if src.is_decimal:
                return _rescale_round(v, src.scale, dst.scale), vl
            if src.is_integer:
                return v.astype(np.int64) * 10 ** dst.scale, vl
            if src.is_float:
                scaled = v * 10 ** dst.scale
                q = np.floor(np.abs(scaled) + 0.5)
                return np.where(scaled < 0, -q, q).astype(np.int64), vl
        if dst.is_integer:
            if src.is_decimal:
                return _rescale_round(v, src.scale, 0), vl
            if src.is_float:
                q = np.floor(np.abs(v) + 0.5)
                return np.where(v < 0, -q, q).astype(np.int64), vl
            return v.astype(np.int64), vl
        if dst.is_string and src.is_string:
            return v, vl
        raise NotImplementedError(f"host cast {src!r} -> {dst!r}")


# ---- helpers ----------------------------------------------------------------

def _truthy(v: np.ndarray) -> np.ndarray:
    if v.dtype != np.bool_:
        return v != 0
    return v


def _substring(s: str, start: int, length: Optional[int]) -> str:
    """MySQL SUBSTRING: 1-based; negative start counts from the end;
    start=0 yields ''. (reference: expression/builtin_string.go substring)"""
    if start == 0:
        return ""
    if start > 0:
        i = start - 1
    else:
        i = len(s) + start
        if i < 0:
            return ""
    if length is None:
        return s[i:]
    if length <= 0:
        return ""
    return s[i:i + length]


def _json_path_steps(path: str) -> Optional[list]:
    """'$.a.b[2]' -> ['a', 'b', 2]; None for malformed paths.
    Subset of the reference's path grammar (types/json/path_expr.go):
    member access and array indexing, no wildcards."""
    import re as _re

    if not path.startswith("$"):
        return None
    steps: list = []
    for m in _re.finditer(r"\.(\w+)|\.\"([^\"]+)\"|\[(\d+)\]|(.)",
                          path[1:]):
        if m.group(4) is not None:
            return None  # junk character
        if m.group(3) is not None:
            steps.append(int(m.group(3)))
        else:
            steps.append(m.group(1) or m.group(2))
    return steps


def _json_extract(doc: str, path: str):
    """JSON-serialized value at path, or None (missing/invalid)."""
    import json as _json

    try:
        v = _json.loads(doc)
    except ValueError:
        return None
    steps = _json_path_steps(path)
    if steps is None:
        return None
    for s in steps:
        if isinstance(s, int):
            if not isinstance(v, list) or s >= len(v):
                return None
            v = v[s]
        else:
            if not isinstance(v, dict) or s not in v:
                return None
            v = v[s]
    return _json.dumps(v, sort_keys=True, separators=(", ", ": "))


def _json_unquote(s: str) -> str:
    import json as _json

    if len(s) >= 2 and s[0] == '"' and s[-1] == '"':
        try:
            return str(_json.loads(s))
        except ValueError:
            return s
    return s


def _json_type_name(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "ARRAY"
    return "OBJECT"


def _b(vv: VV) -> VV:
    v, vl = vv
    return _truthy(np.asarray(v)), vl


def _f(v: np.ndarray, ft: FieldType) -> np.ndarray:
    out = v.astype(np.float64)
    if ft.is_decimal:
        out = out / 10 ** ft.scale
    return out


def _rescale(v: np.ndarray, ft: FieldType, target_scale: int) -> np.ndarray:
    s = ft.scale if ft.is_decimal else 0
    if s < target_scale:
        return v.astype(np.int64) * 10 ** (target_scale - s)
    return v


def _rescale_round(v: np.ndarray, s: int, target: int) -> np.ndarray:
    if s == target:
        return v
    if s < target:
        return v * 10 ** (target - s)
    f = 10 ** (s - target)
    q = (np.abs(v) + f // 2) // f
    return np.where(v < 0, -q, q)


def _align(at: FieldType, av, bt: FieldType, bv):
    if at.is_float or bt.is_float:
        return _f(av, at), _f(bv, bt)
    sa = at.scale if at.is_decimal else 0
    sb = bt.scale if bt.is_decimal else 0
    if sa < sb:
        av = av.astype(np.int64) * 10 ** (sb - sa)
    elif sb < sa:
        bv = bv.astype(np.int64) * 10 ** (sa - sb)
    return av, bv


def _days_from_civil(y: np.ndarray, m: np.ndarray,
                     d: np.ndarray) -> np.ndarray:
    """(year, month, day) -> days since 1970-01-01 (inverse of _civil;
    Hinnant's days_from_civil)."""
    y = np.asarray(y, np.int64) - (np.asarray(m, np.int64) <= 2)
    m = np.asarray(m, np.int64)
    d = np.asarray(d, np.int64)
    era = np.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146_097 + doe - 719_468


def _civil(z: np.ndarray):
    z = z + 719_468
    era = np.where(z >= 0, z, z - 146_096) // 146_097
    doe = z - era * 146_097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    y = np.where(m <= 2, y + 1, y)
    return y, m, d
